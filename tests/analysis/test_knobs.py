"""The typed knob registry: parse semantics, registration guards, and the
docs contract (every registered knob's generated table row appears verbatim
in docs/knobs.md, so ``--knob-table`` output and the docs cannot drift)."""

from pathlib import Path

import pytest

from dynamo_tpu.utils import knobs

REPO_ROOT = Path(__file__).resolve().parents[2]


def test_str_knob_default_and_override():
    assert knobs.get("DYN_LOG", env={}) == "info"
    assert knobs.get("DYN_LOG", env={"DYN_LOG": "debug"}) == "debug"


def test_bool_knob_semantics():
    assert knobs.get("DYN_KV_STREAM", env={}) is True
    for raw in ("0", "false", "off", "no", ""):
        assert knobs.get("DYN_KV_STREAM", env={"DYN_KV_STREAM": raw}) is False
    for raw in ("1", "true", "yes", "on"):
        assert knobs.get("DYN_KV_STREAM", env={"DYN_KV_STREAM": raw}) is True
    # an unrecognized token keeps the default — DYN_CP_RECONNECT=2 must not
    # silently disable reconnect
    assert knobs.get("DYN_CP_RECONNECT", env={"DYN_CP_RECONNECT": "2"}) is True


def test_tri_state_bool_distinguishes_unset():
    assert knobs.get("DYN_DECODE_OVERLAP", env={}) is None
    assert knobs.get("DYN_DECODE_OVERLAP", env={"DYN_DECODE_OVERLAP": "0"}) is False
    assert knobs.get("DYN_DECODE_OVERLAP", env={"DYN_DECODE_OVERLAP": "1"}) is True


def test_numeric_knobs_degrade_to_default_on_garbage():
    assert knobs.get("DYN_RETRY_MAX", env={"DYN_RETRY_MAX": "3"}) == 3
    assert knobs.get("DYN_RETRY_MAX", env={"DYN_RETRY_MAX": "zz"}) == 1
    assert knobs.get("DYN_CONNECT_TIMEOUT_S", env={"DYN_CONNECT_TIMEOUT_S": "2.5"}) == 2.5
    assert knobs.get("DYN_CONNECT_TIMEOUT_S", env={}) == 30.0


def test_unregistered_name_raises():
    with pytest.raises(KeyError):
        knobs.get("DYN_NO_SUCH_KNOB")
    with pytest.raises(KeyError):
        knobs.get_raw("DYN_NO_SUCH_KNOB")


def test_registration_guards():
    with pytest.raises(ValueError):
        knobs.register("DYN_LOG", type="str", doc="duplicate")
    with pytest.raises(ValueError):
        knobs.register("DYN_TEST_NO_DOC", type="str")
    with pytest.raises(ValueError):
        knobs.register("DYN_TEST_BAD_TYPE", type="blob", doc="x")


def test_is_set(monkeypatch):
    assert knobs.is_set("DYN_LOG", env={"DYN_LOG": "info"})
    assert not knobs.is_set("DYN_LOG", env={})


def test_every_knob_table_row_is_in_docs():
    docs = (REPO_ROOT / "docs" / "knobs.md").read_text()
    for section in (knobs.OBS, knobs.PERF, knobs.ROBUST, knobs.ARCH):
        for row in knobs.knob_table(section).splitlines()[2:]:
            assert row in docs, f"docs/knobs.md is missing the row: {row}"


def test_every_knob_has_a_section_table():
    # each registered knob belongs to one of the four documented sections
    sections = {knobs.OBS, knobs.PERF, knobs.ROBUST, knobs.ARCH}
    for knob in knobs.all_knobs():
        assert knob.section in sections, knob.name
