"""The tier-1 dynlint gate: the repo must be clean against its recorded
baseline.  This is the in-process twin of ``scripts/dynlint.py --check`` —
pure AST, no JAX import — so analyzer debt cannot grow without failing the
suite, and paid-down debt cannot linger in the baseline unrecorded."""

import json
from pathlib import Path

from dynamo_tpu import analysis
from dynamo_tpu.analysis import core

REPO_ROOT = Path(__file__).resolve().parents[2]


def test_repo_has_a_baseline():
    path = REPO_ROOT / core.BASELINE_NAME
    assert path.exists(), "run scripts/dynlint.py --write-baseline"
    data = json.loads(path.read_text())
    assert data["version"] == 1
    assert isinstance(data["counts"], dict)


def test_repo_is_dynlint_clean_against_baseline():
    findings, summary = analysis.analyze(REPO_ROOT)
    baseline = core.load_baseline(REPO_ROOT / core.BASELINE_NAME)
    new, stale = core.diff_baseline(findings, baseline)
    assert not new, (
        "NEW analyzer findings (fix, pragma with a reason, or re-record the "
        "baseline deliberately):\n" + "\n".join(f.render() for f in new)
    )
    assert not stale, (
        "STALE baseline entries (debt was paid down — re-record with "
        "scripts/dynlint.py --write-baseline):\n" + "\n".join(stale)
    )
    assert summary["files_scanned"] > 100  # the scan actually covered the tree


def test_all_dyn_spawns_and_env_reads_are_sanctioned():
    """PR 12's acceptance bar, pinned: zero *current* findings at all — the
    async-hygiene and knob-registry migrations drove real debt to zero, so
    the committed baseline must stay empty rather than accrete."""
    baseline = core.load_baseline(REPO_ROOT / core.BASELINE_NAME)
    assert baseline == {}, (
        "the baseline is expected to be empty; new debt should be fixed or "
        "explicitly pragma'd, not baselined: " + ", ".join(baseline)
    )
