"""ctypes binding for the native radix index (csrc/radix_index.cpp).

Interface-compatible with the Python ``RadixTree``
(dynamo_tpu/llm/kv_router/indexer.py), which remains the behavioral spec and
fallback.
"""

from __future__ import annotations

import ctypes

from dynamo_tpu.llm.kv_router.protocols import OverlapScores, RouterEvent
from dynamo_tpu.native import load_native

MAX_WORKERS_OUT = 4096


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.radix_new.restype = ctypes.c_void_p
    lib.radix_free.argtypes = [ctypes.c_void_p]
    lib.radix_apply_stored.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32,
    ]
    lib.radix_apply_removed.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int32,
    ]
    lib.radix_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.radix_find_matches.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.radix_find_matches.restype = ctypes.c_int32
    lib.radix_size.argtypes = [ctypes.c_void_p]
    lib.radix_size.restype = ctypes.c_int32
    lib.radix_worker_block_count.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.radix_worker_block_count.restype = ctypes.c_int32
    return lib


def native_available() -> bool:
    return load_native("radix_index") is not None


class NativeRadixTree:
    def __init__(self) -> None:
        lib = load_native("radix_index")
        if lib is None:
            raise RuntimeError("native radix index unavailable")
        self._lib = _bind(lib)
        self._handle = ctypes.c_void_p(self._lib.radix_new())

    def __del__(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.radix_free(handle)
            self._handle = None

    @staticmethod
    def _hash_array(hashes: list[int]):
        return (ctypes.c_uint64 * len(hashes))(*hashes)

    def apply(self, event: RouterEvent) -> None:
        kv = event.event
        if kv.kind == "stored":
            arr = self._hash_array(kv.block_hashes)
            parent = kv.parent_hash if kv.parent_hash is not None else 0
            self._lib.radix_apply_stored(
                self._handle, event.worker_id, arr, len(kv.block_hashes),
                ctypes.c_uint64(parent), 1 if kv.parent_hash is not None else 0,
            )
        elif kv.kind == "removed":
            arr = self._hash_array(kv.block_hashes)
            self._lib.radix_apply_removed(self._handle, event.worker_id, arr, len(kv.block_hashes))
        elif kv.kind == "cleared":
            self.remove_worker(event.worker_id)

    def remove_worker(self, worker_id: int) -> None:
        self._lib.radix_remove_worker(self._handle, worker_id)

    def find_matches(self, block_hashes: list[int]) -> OverlapScores:
        if not block_hashes:
            return OverlapScores(scores={}, total_blocks=0)
        arr = self._hash_array(block_hashes)
        out_workers = (ctypes.c_int64 * MAX_WORKERS_OUT)()
        out_scores = (ctypes.c_int32 * MAX_WORKERS_OUT)()
        n = self._lib.radix_find_matches(
            self._handle, arr, len(block_hashes), out_workers, out_scores, MAX_WORKERS_OUT
        )
        return OverlapScores(
            scores={int(out_workers[i]): int(out_scores[i]) for i in range(n)},
            total_blocks=len(block_hashes),
        )

    def size(self) -> int:
        return self._lib.radix_size(self._handle)

    def worker_block_count(self, worker_id: int) -> int:
        return self._lib.radix_worker_block_count(self._handle, worker_id)
