"""ctypes binding for the native data-plane codec (csrc/dataplane.cpp).

``NativeFrameDecoder`` incrementally splits raw socket chunks into two-part
frames (the per-token response-stream hot path).  The pure-Python codec
(dynamo_tpu/runtime/codec.py) remains the behavioral spec and fallback;
sender-side frame coalescing is already handled by the asyncio transport
write buffer, so only the read side is native.
"""

from __future__ import annotations

import ctypes

import msgpack

from dynamo_tpu.native import load_native
from dynamo_tpu.runtime.codec import TwoPartMessage


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.dp_decoder_new.restype = ctypes.c_void_p
    lib.dp_decoder_free.argtypes = [ctypes.c_void_p]
    lib.dp_feed.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.dp_feed.restype = ctypes.c_int
    lib.dp_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.dp_next.restype = ctypes.c_int
    lib.dp_pending.argtypes = [ctypes.c_void_p]
    lib.dp_pending.restype = ctypes.c_int64
    lib.dp_drain.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.dp_drain.restype = ctypes.c_int32
    return lib


def native_available() -> bool:
    return load_native("dataplane") is not None


class NativeFrameDecoder:
    """Incremental two-part frame decoder over raw byte chunks."""

    def __init__(self) -> None:
        lib = load_native("dataplane")
        if lib is None:
            raise RuntimeError("native dataplane codec unavailable")
        self._lib = _bind(lib)
        self._handle = ctypes.c_void_p(self._lib.dp_decoder_new())

    def __del__(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.dp_decoder_free(handle)
            self._handle = None

    def feed(self, chunk: bytes) -> None:
        if self._lib.dp_feed(self._handle, chunk, len(chunk)) != 0:
            raise ValueError("corrupt two-part stream (oversized frame)")

    def next(self) -> TwoPartMessage | None:
        """Complete frame, or None if more bytes are needed."""
        hdr = ctypes.c_void_p()
        hlen = ctypes.c_int64()
        pay = ctypes.c_void_p()
        plen = ctypes.c_int64()
        rc = self._lib.dp_next(
            self._handle, ctypes.byref(hdr), ctypes.byref(hlen),
            ctypes.byref(pay), ctypes.byref(plen),
        )
        if rc == 0:
            return None
        if rc < 0:
            raise ValueError("corrupt two-part stream (oversized frame)")
        header = msgpack.unpackb(ctypes.string_at(hdr, hlen.value), raw=False)
        payload = ctypes.string_at(pay, plen.value) if plen.value else b""
        return TwoPartMessage(header=header, payload=payload)

    _MAX_DRAIN = 512

    def drain(self) -> list[TwoPartMessage]:
        """All complete frames, via one C call + one region copy per batch."""
        out: list[TwoPartMessage] = []
        spans = (ctypes.c_int64 * (4 * self._MAX_DRAIN))()
        while True:
            region = ctypes.c_void_p()
            region_len = ctypes.c_int64()
            n = self._lib.dp_drain(
                self._handle, spans, self._MAX_DRAIN,
                ctypes.byref(region), ctypes.byref(region_len),
            )
            if n < 0:
                raise ValueError("corrupt two-part stream (oversized frame)")
            if n == 0:
                return out
            view = memoryview(ctypes.string_at(region, region_len.value))
            for i in range(n):
                ho, hl, po, pl = spans[i * 4 : i * 4 + 4]
                header = msgpack.unpackb(view[ho : ho + hl], raw=False)
                payload = bytes(view[po : po + pl]) if pl else b""
                out.append(TwoPartMessage(header=header, payload=payload))
            if n < self._MAX_DRAIN:
                return out

    @property
    def pending(self) -> int:
        return int(self._lib.dp_pending(self._handle))
