"""Native (C++) component loader.

Builds csrc/ sources on demand with g++ into ``csrc/build/`` and binds them
via ctypes (this image has no pybind11; the C ABI keeps the boundary thin).
``DYN_DISABLE_NATIVE=1`` forces the pure-Python twins.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils import knobs

logger = get_logger("native")

CSRC = Path(__file__).parent.parent.parent / "csrc"
BUILD = CSRC / "build"

_libs: dict[str, ctypes.CDLL | None] = {}


def load_native(name: str) -> ctypes.CDLL | None:
    """Compile (cached) + load ``csrc/<name>.cpp`` as lib<name>.so."""
    if knobs.get("DYN_DISABLE_NATIVE"):
        return None
    if name in _libs:
        return _libs[name]
    source = CSRC / f"{name}.cpp"
    lib_path = BUILD / f"lib{name}.so"
    try:
        if not lib_path.exists() or source.stat().st_mtime > lib_path.stat().st_mtime:
            BUILD.mkdir(parents=True, exist_ok=True)
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 str(source), "-o", str(lib_path)],
                check=True, capture_output=True, text=True,
            )
            logger.info("built native %s", lib_path.name)
        _libs[name] = ctypes.CDLL(str(lib_path))
    except (subprocess.CalledProcessError, OSError) as exc:
        detail = getattr(exc, "stderr", "") or repr(exc)
        logger.warning("native %s unavailable (%s); using Python fallback", name, detail)
        _libs[name] = None
    return _libs[name]
