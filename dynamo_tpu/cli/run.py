"""dynamo-tpu run — the single-command launcher.

``dynamo-tpu run in=<http|text|batch:FILE|none> out=<jax|echo|mocker|dyn>``
(reference: launch/dynamo-run/src/{opt.rs,lib.rs} ``dynamo run in=X out=Y``).

- ``out=jax|echo|mocker`` spawns the in-process engine and (unless
  ``in=none``) a frontend in the same process over the memory control plane.
- ``out=dyn`` runs frontend-only against a dynctl control plane; workers
  register themselves from other processes (``in=none out=jax`` there).

Examples:
  dynamo-tpu run in=http out=jax --model-path /models/llama-3-8b --port 8080
  dynamo-tpu run in=text out=echo --model-path tests/data/tiny-chat-model
  dynamo-tpu run in=none out=jax --model-path ... --control-plane 127.0.0.1:2379
  dynamo-tpu run in=http out=dyn --control-plane 127.0.0.1:2379 --router-mode kv
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from dynamo_tpu.runtime.client import RouterMode
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger("cli.run")


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(prog="dynamo-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)
    run = sub.add_parser("run", help="serve a model")
    run.add_argument("io", nargs="*", help="in=<http|text|batch:FILE|none> out=<jax|echo|mocker|dyn>")
    run.add_argument("--model-path", help="local model dir (tokenizer/config/weights)")
    run.add_argument("--model-name", help="served model name (default: dir name)")
    run.add_argument("--host", default="0.0.0.0")
    run.add_argument("--port", type=int, default=8080)
    run.add_argument("--control-plane", default=None, help="dynctl host:port (default: in-process memory)")
    run.add_argument("--namespace", default="dynamo")
    run.add_argument("--component", default="backend")
    run.add_argument("--endpoint", default="generate")
    run.add_argument("--router-mode", choices=[m.value for m in RouterMode], default="round_robin")
    run.add_argument("--request-template", default=None,
                     help="JSON file with default model/temperature/max_tokens")
    run.add_argument("--num-blocks", type=int, default=256, help="KV cache blocks in HBM")
    run.add_argument("--kv-block-size", type=int, default=16)
    run.add_argument("--max-batch-size", type=int, default=8)
    run.add_argument("--context-length", type=int, default=None)
    run.add_argument("--tensor-parallel-size", type=int, default=1)
    run.add_argument("--warmup", action="store_true",
                     help="pre-compile every serving program before registering")
    run.add_argument("--compilation-cache", default=None, metavar="DIR",
                     help="persistent JAX compilation cache directory "
                          "(default: DYN_COMPILE_CACHE_DIR, else "
                          "~/.cache/dynamo_tpu/jax_cache; set "
                          "DYN_COMPILE_CACHE_DIR='' to disable); with "
                          "--warmup the serving programs also AOT-compile "
                          "in parallel (cold restarts reuse the cache)")
    run.add_argument("--speculative", choices=["ngram"], default=None,
                     help="speculative decoding (ngram = prompt-lookup "
                          "self-drafting with exact greedy verification)")
    run.add_argument("--spec-tokens", type=int, default=4,
                     help="draft tokens verified per step")
    run.add_argument("--spec-ngram", type=int, default=2,
                     help="lookup n-gram width for ngram drafting")
    run.add_argument("--kv-cache-dtype", choices=["fp8", "bf16", "f32"],
                     default=None,
                     help="KV cache storage dtype (fp8 halves KV bytes; "
                          "default: model dtype)")
    run.add_argument("--quantize", choices=["int8"], default=None,
                     help="weight-only quantization (all served families; "
                          "halves decode HBM traffic — the TPU analog of "
                          "the reference's FP8 serving)")
    run.add_argument("--host-offload-blocks", type=int, default=0,
                     help="G2 host-DRAM KV tier size (0 = off): HBM "
                          "evictions offload here and restore on prefix hit")
    run.add_argument("--disk-offload-blocks", type=int, default=0,
                     help="G3 SSD KV tier size (needs --host-offload-blocks)")
    run.add_argument("--remote-kv-store", default=None, metavar="HOST:PORT",
                     help="G4 remote KV tier: a block-store server "
                          "(python -m dynamo_tpu.llm.block_manager.remote); "
                          "bottom-tier evictions cascade there over DCN")
    args = parser.parse_args(argv)

    args.input, args.output = "http", "jax"
    for tok in args.io:
        if tok.startswith("in="):
            args.input = tok[3:]
        elif tok.startswith("out="):
            args.output = tok[4:]
        else:
            parser.error(f"unrecognized positional {tok!r} (want in=... / out=...)")
    return args


async def _run(args) -> int:
    configure_logging()
    if args.compilation_cache:
        import jax

        jax.config.update("jax_compilation_cache_dir", args.compilation_cache)
    else:
        # default-on persistence: the engine would resolve this itself at
        # init, but doing it here covers out=echo/mocker spawns too and
        # logs the resolved dir once at startup
        from dynamo_tpu.engine.engine import _ensure_compile_cache

        resolved = _ensure_compile_cache()
        if resolved:
            logger.info("persistent compile cache: %s", resolved)
    control_plane = args.control_plane or "memory"
    runtime = await DistributedRuntime.create(
        RuntimeConfig(control_plane=control_plane, namespace=args.namespace)
    )
    from dynamo_tpu.serve import serve_frontend, serve_worker

    worker = None
    if args.output in ("jax", "echo", "mocker"):
        if not args.model_path:
            print("error: --model-path required for local engines", file=sys.stderr)
            return 2
        overrides = {}
        if args.output == "jax":
            overrides = dict(
                num_blocks=args.num_blocks,
                max_batch_size=args.max_batch_size,
            )
            if args.context_length:
                overrides["max_model_len"] = args.context_length
            if args.tensor_parallel_size > 1:
                from dynamo_tpu.parallel.mesh import MeshConfig

                overrides["mesh"] = MeshConfig(tp=args.tensor_parallel_size)
            if args.warmup:
                overrides["warmup"] = True
            if args.quantize:
                overrides["quantize"] = args.quantize
            if args.kv_cache_dtype:
                overrides["kv_cache_dtype"] = args.kv_cache_dtype
            if args.speculative:
                overrides["speculative"] = args.speculative
                overrides["spec_tokens"] = args.spec_tokens
                overrides["spec_ngram"] = args.spec_ngram
            if args.host_offload_blocks:
                overrides["host_offload_blocks"] = args.host_offload_blocks
            if args.disk_offload_blocks:
                overrides["disk_offload_blocks"] = args.disk_offload_blocks
            if args.remote_kv_store:
                overrides["remote_store_addr"] = args.remote_kv_store
        worker = await serve_worker(
            runtime,
            args.model_path,
            model_name=args.model_name,
            namespace=args.namespace,
            component=args.component,
            endpoint=args.endpoint,
            engine_kind=args.output,
            **overrides,
        )
    elif args.output != "dyn":
        print(f"error: unknown out={args.output}", file=sys.stderr)
        return 2

    try:
        if args.input == "http":
            service, watcher = await serve_frontend(
                runtime,
                host=args.host,
                port=args.port,
                router_mode=RouterMode(args.router_mode),
                request_template=args.request_template,
            )
            print(f"listening on http://{args.host}:{service.port}/v1", file=sys.stderr)
            await runtime.wait_for_shutdown()
            await watcher.stop()
            await service.stop()
        elif args.input == "text" or args.input.startswith("batch:"):
            await _run_local_io(runtime, args)
        elif args.input == "none":
            print("worker running; ctrl-c to stop", file=sys.stderr)
            await runtime.wait_for_shutdown()
        else:
            print(f"error: unknown in={args.input}", file=sys.stderr)
            return 2
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        if worker is not None:
            await worker.shutdown()
        await runtime.close()
    return 0


async def _run_local_io(runtime, args) -> None:
    """in=text REPL / in=batch:file one-shot, through the full pipeline."""
    from dynamo_tpu.llm.http.service import ModelManager
    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.protocols.aggregator import aggregate_chat_stream
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.engine import Context

    manager = ModelManager()
    watcher = ModelWatcher(runtime, manager, router_mode=RouterMode(args.router_mode))
    await watcher.start()
    for _ in range(100):
        if manager.model_names():
            break
        await asyncio.sleep(0.05)
    names = manager.model_names()
    if not names:
        print("no models registered", file=sys.stderr)
        return
    model = names[0]
    engine = manager.chat_engines[model]

    async def ask(prompt: str) -> str:
        req = ChatCompletionRequest.model_validate(
            {"model": model, "messages": [{"role": "user", "content": prompt}]}
        )
        stream = await engine.generate(Context(req))

        async def data_only():
            async for ann in stream:
                if not ann.is_annotation() and ann.data is not None:
                    yield ann.data

        response = await aggregate_chat_stream(data_only())
        return response.choices[0].message.content if response.choices else ""

    if args.input == "text":
        print(f"interactive mode, model={model}; empty line exits", file=sys.stderr)
        loop = asyncio.get_running_loop()
        while True:
            line = await loop.run_in_executor(None, sys.stdin.readline)
            line = line.strip()
            if not line:
                break
            print(await ask(line))
    else:
        path = args.input[len("batch:"):]
        with open(path) as f:
            prompts = [json.loads(l)["prompt"] if l.strip().startswith("{") else l.strip()
                       for l in f if l.strip()]
        for prompt in prompts:
            print(json.dumps({"prompt": prompt, "response": await ask(prompt)}))
    await watcher.stop()


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.cmd == "run":
        return asyncio.run(_run(args))
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
