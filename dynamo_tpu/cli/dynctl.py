"""dynctl — run the standalone control-plane server, or administer models
registered in it (the reference's etcd+NATS deployment and llmctl admin CLI
in one tool: launch/llmctl/src/main.rs).

Usage:
  python -m dynamo_tpu.cli.dynctl serve [--host H] [--port P]
  python -m dynamo_tpu.cli.dynctl list-models   [--control-plane H:P]
  python -m dynamo_tpu.cli.dynctl list-instances [--control-plane H:P]
  python -m dynamo_tpu.cli.dynctl remove-model NAME [--control-plane H:P]
  python -m dynamo_tpu.cli.dynctl drain INSTANCE_ID [--timeout S] [--control-plane H:P]
  python -m dynamo_tpu.cli.dynctl migrate REQUEST_ID DST [--reason R] [--control-plane H:P]
  python -m dynamo_tpu.cli.dynctl topology [--json] [--control-plane H:P]
  python -m dynamo_tpu.cli.dynctl flight dump [INSTANCE_ID] [--control-plane H:P]
"""

from __future__ import annotations

import argparse
import asyncio
import json


async def _amain(args) -> int:
    if args.cmd == "serve":
        from dynamo_tpu.runtime.controlplane.server import run_server

        await run_server(args.host, args.port)
        return 0

    from dynamo_tpu.llm.discovery import MODELS_PREFIX, ModelEntry
    from dynamo_tpu.runtime.component import ROOT_PATH
    from dynamo_tpu.runtime.controlplane import connect_control_plane

    plane = await connect_control_plane(args.control_plane)
    try:
        if args.cmd == "list-models":
            entries = await plane.kv.get_prefix(MODELS_PREFIX)
            for e in entries:
                entry = ModelEntry.from_json(e.value)
                print(
                    f"{entry.name}\t{entry.endpoint_path()}\t{entry.instance_id:016x}\t"
                    f"{','.join(entry.model_types)}"
                )
            if not entries:
                print("(no models registered)")
        elif args.cmd == "list-instances":
            entries = await plane.kv.get_prefix(ROOT_PATH)
            for e in entries:
                if "/instances/" in e.key:
                    d = json.loads(e.value)
                    print(f"{d['namespace']}.{d['component']}.{d['endpoint']}\t{d['instance_id']:016x}")
        elif args.cmd == "topology":
            from dynamo_tpu.topology.card import CARDS_PREFIX, TopologyCard
            from dynamo_tpu.topology.map import TopologyMap

            topo = TopologyMap()
            for e in await plane.kv.get_prefix(CARDS_PREFIX):
                topo.upsert(TopologyCard.from_json(e.value))
            if args.json:
                print(json.dumps(topo.to_dict(), indent=2))
            elif not topo.nodes:
                print("(no topology cards published)")
            else:
                d = topo.to_dict()
                print(f"{'WORKER':<18} {'ROLE':<8} {'SLICE':<10} {'HOST':<16} ADDRESS")
                for wid, card in d["nodes"].items():
                    print(
                        f"{wid:<18} {card['role'] or '-':<8} "
                        f"{card['slice_label'] or '-':<10} "
                        f"{card['host'] or '-':<16} {card['transfer_address'] or '-'}"
                    )
                if d["links"]:
                    print()
                    print(
                        f"{'A':<18} {'B':<18} {'HOP':<6} {'MEASURED':>12} "
                        f"{'PRIOR':>12} {'RTT':>9} {'PROBES':>7}"
                    )
                    for link in d["links"]:
                        measured = (
                            f"{link['measured_bps'] / 1e9:.2f}GB/s"
                            if link["measured_bps"] > 0 else "-"
                        )
                        rtt = (
                            f"{link['rtt_s'] * 1e3:.2f}ms"
                            if link["rtt_s"] > 0 else "-"
                        )
                        print(
                            f"{link['a']:<18} {link['b']:<18} "
                            f"{link['hop'] or '?':<6} {measured:>12} "
                            f"{link['prior_bps'] / 1e9:>10.1f}GB/s "
                            f"{rtt:>9} {link['probes_total']:>7}"
                        )
                print()
                print(
                    f"informative={d['informative']} "
                    f"links={sum(1 for _ in d['links'])} age={d['age_s']:.1f}s"
                )
        elif args.cmd == "remove-model":
            n = await plane.kv.delete_prefix(f"{MODELS_PREFIX}{args.name}/")
            print(f"removed {n} registration(s) for {args.name}")
        elif args.cmd == "drain":
            from dynamo_tpu.runtime.component import ctl_subject

            needle = args.instance.lower()
            if needle.startswith("0x"):
                needle = needle[2:]
            matches = []
            for e in await plane.kv.get_prefix(ROOT_PATH):
                if "/instances/" not in e.key:
                    continue
                d = json.loads(e.value)
                hex16 = f"{d['instance_id']:016x}"
                if needle in (hex16, f"{d['instance_id']:x}") or hex16.startswith(needle):
                    matches.append(d)
            if not matches:
                print(f"no instance matches {args.instance!r}")
                return 1
            if len(matches) > 1:
                print(f"ambiguous instance id {args.instance!r} ({len(matches)} matches)")
                return 1
            inst = matches[0]
            budget = args.timeout or 30.0
            reply = await plane.bus.request(
                ctl_subject(inst["subject"]),
                json.dumps({"op": "drain", "timeout_s": args.timeout}).encode(),
                timeout=budget + 10.0,
            )
            result = json.loads(reply.decode())
            # the lease is revoked before the worker replies; confirm the
            # instance really is gone from the view
            gone = not any(
                "/instances/" in e.key
                and json.loads(e.value)["instance_id"] == inst["instance_id"]
                for e in await plane.kv.get_prefix(ROOT_PATH)
            )
            print(
                f"drained {inst['subject']}: ok={result.get('ok')} "
                f"handed_off={result.get('handed_off')} "
                f"duration={result.get('duration_s')}s deregistered={gone}"
            )
            return 0 if result.get("ok") and gone else 1
        elif args.cmd == "flight":
            from dynamo_tpu.runtime.component import ctl_subject

            # resolve instances: an explicit id (hex prefix ok) or, with no
            # argument, every registered instance gets a dump request
            needle = (args.instance or "").lower()
            if needle.startswith("0x"):
                needle = needle[2:]
            matches = []
            for e in await plane.kv.get_prefix(ROOT_PATH):
                if "/instances/" not in e.key:
                    continue
                d = json.loads(e.value)
                hex16 = f"{d['instance_id']:016x}"
                if (not needle or needle in (hex16, f"{d['instance_id']:x}")
                        or hex16.startswith(needle)):
                    matches.append(d)
            if not matches:
                print(
                    f"no instance matches {args.instance!r}" if args.instance
                    else "(no instances registered)"
                )
                return 1
            if args.instance and len(matches) > 1:
                print(f"ambiguous instance id {args.instance!r} ({len(matches)} matches)")
                return 1
            failed = False
            for inst in matches:
                try:
                    reply = await plane.bus.request(
                        ctl_subject(inst["subject"]),
                        json.dumps({"op": "flight_dump"}).encode(),
                        timeout=args.timeout,
                    )
                    result = json.loads(reply.decode())
                except (asyncio.TimeoutError, RuntimeError):
                    print(f"{inst['subject']}: no reply (worker gone?)")
                    failed = True
                    continue
                if not result.get("ok"):
                    print(f"{inst['subject']}: {result.get('error', 'dump failed')}")
                    failed = True
                    continue
                paths = result.get("paths") or []
                if not result.get("enabled", True):
                    print(f"{inst['subject']}: flight recorder disabled (DYN_FLIGHT=0)")
                elif not paths:
                    print(f"{inst['subject']}: no recorders live (nothing dumped)")
                else:
                    for path in paths:
                        print(f"{inst['subject']}: {path}")
            return 1 if failed else 0
        elif args.cmd == "migrate":
            from dynamo_tpu.runtime.migration import MIGRATE_SUBJECT

            op = {
                "op": "migrate",
                "request_id": args.request_id,
                "dst": args.dst,
                "reason": args.reason,
            }
            try:
                # only the dispatcher that owns the request replies; the
                # flip itself is bounded by DYN_MIGRATE_FLIP_TIMEOUT_S on
                # the owning side, so pad generously here
                reply = await plane.bus.request(
                    MIGRATE_SUBJECT, json.dumps(op).encode(), timeout=args.timeout
                )
            except (asyncio.TimeoutError, RuntimeError) as exc:
                # a remote control plane wraps the bus timeout in the RPC
                # error channel (RuntimeError), the in-memory one raises it
                if isinstance(exc, RuntimeError) and "Timeout" not in repr(exc):
                    raise
                print(
                    f"no dispatcher owns request {args.request_id!r} "
                    "(wrong id, already finished, or DYN_MIGRATE=0)"
                )
                return 1
            result = json.loads(reply.decode())
            if result.get("ok"):
                print(
                    f"migrated {result['request_id']}: "
                    f"{result['src']} -> {result['dst']} "
                    f"(hop={result.get('hop') or '?'} "
                    f"hidden={result.get('hidden_s')}s)"
                )
                return 0
            print(f"migrate failed: {result.get('error')}")
            return 1
    finally:
        await plane.close()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(prog="dynctl")
    sub = parser.add_subparsers(dest="cmd", required=True)
    serve = sub.add_parser("serve", help="run the control-plane server")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=2379)
    for name in ("list-models", "list-instances"):
        p = sub.add_parser(name)
        p.add_argument("--control-plane", default="127.0.0.1:2379")
    topo = sub.add_parser(
        "topology", help="dump the fleet topology map (nodes + classified links)"
    )
    topo.add_argument("--json", action="store_true", help="emit the map as JSON")
    topo.add_argument("--control-plane", default="127.0.0.1:2379")
    rm = sub.add_parser("remove-model")
    rm.add_argument("name")
    rm.add_argument("--control-plane", default="127.0.0.1:2379")
    drain = sub.add_parser(
        "drain", help="gracefully empty a worker, then deregister it"
    )
    drain.add_argument("instance", help="instance id (hex, prefix ok)")
    drain.add_argument("--timeout", type=float, default=None,
                       help="drain budget in seconds (default DYN_DRAIN_TIMEOUT_S)")
    drain.add_argument("--control-plane", default="127.0.0.1:2379")
    mig = sub.add_parser(
        "migrate", help="move one live decode session to another worker"
    )
    mig.add_argument("request_id",
                     help="id of the in-flight session: the request/trace id "
                          "(x-request-id header, frontend logs) or the "
                          "dispatcher's internal session id")
    mig.add_argument("dst", nargs="?", default=None,
                     help="destination instance id (hex, prefix ok); omit to "
                          "let the coordinator pick the cheapest-hop worker")
    mig.add_argument("--reason", default="manual",
                     help="migration reason; anything but 'manual' also "
                          "authorizes a DCN-hop destination")
    mig.add_argument("--timeout", type=float, default=30.0,
                     help="seconds to wait for the owning dispatcher's reply")
    mig.add_argument("--control-plane", default="127.0.0.1:2379")
    fl = sub.add_parser(
        "flight", help="perf flight recorder operations (dump)"
    )
    fl.add_argument("action", choices=["dump"],
                    help="dump: write every live recorder's ring to JSONL")
    fl.add_argument("instance", nargs="?", default=None,
                    help="instance id (hex, prefix ok); omit to dump every "
                         "registered instance")
    fl.add_argument("--timeout", type=float, default=10.0,
                    help="seconds to wait for each worker's reply")
    fl.add_argument("--control-plane", default="127.0.0.1:2379")
    args = parser.parse_args()
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    raise SystemExit(main())
