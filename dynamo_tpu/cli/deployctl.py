"""Deployment CLI: build a graph artifact, push it, deploy it.

The reference's ``dynamo build`` / ``dynamo deploy`` pair (reference:
deploy/sdk/src/dynamo/sdk/cli/deployment.py) — against this repo's
api-store (deploy/api_store.py) and operator (deploy/operator.py):

    # render an SDK graph to a manifest file
    python -m dynamo_tpu.cli.deployctl build examples.hello_world.hello_world:Frontend \\
        --out frontend.graph.json

    # push it to the api-store as a versioned artifact
    python -m dynamo_tpu.cli.deployctl push frontend.graph.json \\
        --store http://api-store:8085 --name chat --version v1

    # build+push in one step
    python -m dynamo_tpu.cli.deployctl build <entry> --store http://... --version v1

    # deploy a stored artifact (applies the graph CR; the operator's watch
    # reconciles it into component CRs / Deployments / Services)
    python -m dynamo_tpu.cli.deployctl deploy chat v1 --store http://api-store:8085

    # list artifacts
    python -m dynamo_tpu.cli.deployctl list --store http://api-store:8085
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from dynamo_tpu.deploy.deployment import (
    build_graph_manifest,
    deploy_artifact,
    fetch_artifact,
    push_artifact,
)
from dynamo_tpu.utils.logging import configure_logging


def _build(args) -> int:
    manifest = build_graph_manifest(
        args.entry,
        name=args.name,
        namespace=args.namespace,
        image=args.image,
        control_plane=args.control_plane,
    )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(manifest, f, indent=2)
        print(f"wrote {args.out}")
    else:
        print(json.dumps(manifest, indent=2))
    if args.store:
        name = args.name or manifest["metadata"]["name"]
        record = asyncio.run(
            push_artifact(args.store, name, args.version, manifest)
        )
        print(f"pushed {name}:{args.version} → {args.store}")
        return 0 if record else 1
    return 0


def _push(args) -> int:
    with open(args.manifest) as f:
        manifest = json.load(f)
    name = args.name or manifest.get("metadata", {}).get("name")
    if not name:
        print("error: --name required (manifest has no metadata.name)", file=sys.stderr)
        return 2
    asyncio.run(push_artifact(args.store, name, args.version, manifest))
    print(f"pushed {name}:{args.version} → {args.store}")
    return 0


def _deploy(args) -> int:
    from dynamo_tpu.deploy.operator import KubectlClient

    async def run() -> None:
        record = await fetch_artifact(args.store, args.name, args.version)
        await deploy_artifact(
            KubectlClient(), record, namespace=args.namespace or None
        )

    asyncio.run(run())
    print(f"deployed {args.name}:{args.version}")
    return 0


def _list(args) -> int:
    import aiohttp

    async def run() -> list:
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"{args.store.rstrip('/')}/api/v1/graphs"
            ) as resp:
                resp.raise_for_status()
                return await resp.json()

    for row in asyncio.run(run()):
        print(f"{row['name']}\t{','.join(row['versions'])}")
    return 0


def main(argv=None) -> int:
    configure_logging()
    parser = argparse.ArgumentParser(prog="deployctl", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("build", help="render an SDK graph to a manifest")
    b.add_argument("entry", help="module:ClassName of the entry @service")
    b.add_argument("--name", default=None, help="graph name (default: entry service)")
    b.add_argument("--namespace", default="default")
    b.add_argument("--image", default="dynamo-tpu:latest")
    b.add_argument("--control-plane", default="dynctl:2379")
    b.add_argument("--out", default=None, help="write manifest JSON here")
    b.add_argument("--store", default=None, help="api-store URL (push after build)")
    b.add_argument("--version", default="v1")
    b.set_defaults(fn=_build)

    p = sub.add_parser("push", help="push a built manifest to the api-store")
    p.add_argument("manifest", help="manifest JSON file from `build --out`")
    p.add_argument("--store", required=True)
    p.add_argument("--name", default=None)
    p.add_argument("--version", default="v1")
    p.set_defaults(fn=_push)

    d = sub.add_parser("deploy", help="apply a stored artifact's graph CR")
    d.add_argument("name")
    d.add_argument("version")
    d.add_argument("--store", required=True)
    d.add_argument("--namespace", default=None)
    d.set_defaults(fn=_deploy)

    ls = sub.add_parser("list", help="list artifacts in the api-store")
    ls.add_argument("--store", required=True)
    ls.set_defaults(fn=_list)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
