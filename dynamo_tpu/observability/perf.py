"""Utilization accounting: analytical FLOPs/bytes cost model + rolling MFU.

The north-star question — "how close to the hardware are we?" — needs a
denominator.  This module supplies it analytically from model geometry (no
profiling run required):

- :func:`model_cost` derives a :class:`ModelCost` (parameter count, weight
  bytes streamed per forward, linear FLOPs per token, attention FLOPs per
  attended context token, KV-cache bytes per token) from any registered
  family's config by duck-typing the common geometry fields.  MoE families
  count ACTIVE expert FLOPs but TOTAL expert bytes (decode streams only the
  routed experts, but capacity planning cares about resident weights);
  exotic attention geometries (MLA) degrade to the GQA approximation.
- :class:`UtilizationTracker` turns the engine device loop's per-step facts
  (prefill/decode token counts, attended context tokens, weight streams,
  emitted tokens, step wall time) into rolling-window **MFU**
  (model FLOPs utilization), **MBU** (model bandwidth utilization),
  **goodput** (emitted tokens/s — tokens a client actually received, as
  opposed to computed-then-discarded work) plus cumulative totals.

Peak hardware numbers come from ``DYN_PEAK_TFLOPS`` / ``DYN_PEAK_GBPS`` when
set, else a nominal per-device-kind table (bf16 peak, HBM bandwidth), else a
conservative CPU fallback — the point of MFU is trend and cross-worker
comparison, not spec-sheet precision.

Everything here is exported through ``JaxLlmEngine.stats()`` →
``ForwardPassMetrics`` → ``dyn_worker_*`` gauges (components/metrics_service)
and consumed by the planner and ``scripts/dyn_top.py``.
"""

from __future__ import annotations

import os
import threading
import time
from dynamo_tpu.utils import knobs
from collections import deque
from dataclasses import dataclass

# nominal (bf16 peak FLOPs, HBM bytes/s) per device kind — matched as a
# lowercase substring of jax's device_kind.  Order matters: first hit wins.
NOMINAL_PEAKS: tuple[tuple[str, float, float], ...] = (
    ("v6e", 918e12, 1640e9),
    ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("cpu", 0.5e12, 50e9),
)
_FALLBACK_PEAKS = (0.5e12, 50e9)

_DTYPE_BYTES = {
    "float8_e4m3fn": 1, "float8_e5m2": 1, "fp8": 1, "float8": 1,
    "int8": 1, "bfloat16": 2, "bf16": 2, "float16": 2, "f16": 2,
    "float32": 4, "f32": 4, "float64": 8,
}


def _dtype_bytes(dtype: object, default: int = 2) -> int:
    if dtype is None:
        return default
    if isinstance(dtype, str):
        return _DTYPE_BYTES.get(dtype, default)
    name = getattr(dtype, "__name__", None) or getattr(dtype, "name", None)
    if name is not None:
        return _DTYPE_BYTES.get(str(name), default)
    try:
        import numpy as np

        return int(np.dtype(dtype).itemsize)
    except Exception:  # noqa: BLE001
        return default


@dataclass(frozen=True)
class ModelCost:
    """Analytical per-token cost of one model geometry."""

    param_count: int                # resident weight parameters
    weight_bytes: int               # bytes to stream ALL weights once
    linear_flops_per_token: int     # matmul FLOPs per token (2·active params)
    attn_flops_per_ctx_token: int   # QK^T + AV FLOPs per attended ctx token
    kv_bytes_per_token: int         # KV cache bytes written per new token

    def flops(self, tokens: int, attn_ctx_tokens: int) -> float:
        """Total FLOPs to compute ``tokens`` new positions that together
        attended ``attn_ctx_tokens`` context positions."""
        return (
            tokens * self.linear_flops_per_token
            + attn_ctx_tokens * self.attn_flops_per_ctx_token
        )

    def bytes_moved(
        self, tokens: int, attn_ctx_tokens: int, weight_streams: float
    ) -> float:
        """HBM bytes: weights streamed ``weight_streams`` times, KV written
        per new token, KV read per attended context token."""
        return (
            weight_streams * self.weight_bytes
            + tokens * self.kv_bytes_per_token
            + attn_ctx_tokens * self.kv_bytes_per_token
        )


def model_cost(
    model, *, quantize: str | None = None, kv_cache_dtype: object = None
) -> ModelCost:
    """Derive a :class:`ModelCost` from a family config by duck-typing the
    shared geometry fields (LlamaConfig and friends).  Never raises: absent
    fields fall back to conservative defaults, so an exotic family gets an
    approximation instead of no utilization signal."""
    h = int(getattr(model, "hidden_size", 0) or 1)
    layers = int(getattr(model, "num_layers", 0) or 1)
    heads = int(getattr(model, "num_heads", 0) or 1)
    head_dim = int(getattr(model, "head_dim", 0) or max(h // heads, 1))
    kv_heads = int(getattr(model, "num_kv_heads", 0) or heads)
    inter = int(getattr(model, "intermediate_size", 0) or 4 * h)
    vocab = int(getattr(model, "vocab_size", 0) or 1)
    tied = bool(getattr(model, "tie_word_embeddings", False))

    attn_params = h * heads * head_dim + 2 * h * kv_heads * head_dim + heads * head_dim * h

    num_experts = int(getattr(model, "num_experts", 0) or 0)
    if num_experts > 1:
        expert_inter = int(
            getattr(model, "expert_intermediate_size", 0)
            or getattr(model, "moe_intermediate_size", 0)
            or inter
        )
        active_experts = int(
            getattr(model, "experts_per_token", 0)
            or getattr(model, "num_experts_per_tok", 0)
            or 2
        )
        mlp_params_total = num_experts * 3 * h * expert_inter + h * num_experts
        mlp_params_active = active_experts * 3 * h * expert_inter + h * num_experts
    else:
        mlp_params_total = mlp_params_active = 3 * h * inter

    embed = vocab * h
    head_params = 0 if tied else vocab * h
    param_count = embed + head_params + layers * (attn_params + mlp_params_total)
    # active matmul params per token: embedding lookup is a gather (no
    # matmul), the unembedding projection always runs
    active_params = vocab * h + layers * (attn_params + mlp_params_active)

    weight_dtype_bytes = _dtype_bytes(getattr(model, "dtype", None))
    if quantize == "int8":
        weight_dtype_bytes = 1

    kv_dtype_bytes = _dtype_bytes(kv_cache_dtype, default=weight_dtype_bytes)

    return ModelCost(
        param_count=param_count,
        weight_bytes=param_count * weight_dtype_bytes,
        linear_flops_per_token=2 * active_params,
        # per attended context position per layer: 2·heads·head_dim for
        # QK^T plus the same for attention·V
        attn_flops_per_ctx_token=4 * layers * heads * head_dim,
        kv_bytes_per_token=2 * layers * kv_heads * head_dim * kv_dtype_bytes,
    )


def detect_peaks() -> tuple[float, float]:
    """(peak FLOPs/s, peak bytes/s) for this host: env override →
    device-kind table → conservative fallback."""
    env_tflops = knobs.get("DYN_PEAK_TFLOPS")
    env_gbps = knobs.get("DYN_PEAK_GBPS")
    kind = ""
    if not (env_tflops and env_gbps):
        try:
            import jax

            kind = jax.devices()[0].device_kind.lower()
        except Exception:  # noqa: BLE001
            kind = ""
    flops, gbps = _FALLBACK_PEAKS
    for needle, f, b in NOMINAL_PEAKS:
        if needle in kind:
            flops, gbps = f, b
            break
    if env_tflops:
        flops = env_tflops * 1e12
    if env_gbps:
        gbps = env_gbps * 1e9
    return flops, gbps


@dataclass
class _Sample:
    t: float
    duration_s: float
    flops: float
    bytes_moved: float
    emitted_tokens: int
    prefill_tokens: int
    decode_tokens: int


class UtilizationTracker:
    """Rolling MFU / MBU / goodput over the engine's step stream.

    Called once per scheduler iteration from the device thread; the asyncio
    stats reader calls :meth:`rates`/:meth:`stats` concurrently, so sample
    mutation and iteration share a lock (uncontended in the common case —
    one writer, ~1Hz readers).  ``window_s`` (``DYN_UTIL_WINDOW_S``) bounds
    both staleness and memory."""

    def __init__(
        self,
        cost: ModelCost,
        *,
        peak_flops: float | None = None,
        peak_bytes_per_s: float | None = None,
        window_s: float | None = None,
    ):
        self.cost = cost
        if peak_flops is None or peak_bytes_per_s is None:
            detected_f, detected_b = detect_peaks()
            peak_flops = peak_flops if peak_flops is not None else detected_f
            peak_bytes_per_s = (
                peak_bytes_per_s if peak_bytes_per_s is not None else detected_b
            )
        self.peak_flops = max(float(peak_flops), 1.0)
        self.peak_bytes_per_s = max(float(peak_bytes_per_s), 1.0)
        if window_s is None:
            window_s = knobs.get("DYN_UTIL_WINDOW_S")
        self.window_s = max(window_s, 0.1)
        self._samples: deque[_Sample] = deque()
        self._lock = threading.Lock()
        # cumulative totals (monotone; exported as *_total mirrors)
        self.prefill_tokens_total = 0
        self.decode_tokens_total = 0
        self.emitted_tokens_total = 0
        self.flops_total = 0.0
        self.bytes_total = 0.0
        self.busy_time_total_s = 0.0

    def observe_step(
        self,
        *,
        duration_s: float,
        prefill_tokens: int = 0,
        decode_tokens: int = 0,
        attn_ctx_tokens: int = 0,
        weight_streams: float = 0.0,
        emitted_tokens: int = 0,
        now: float | None = None,
    ) -> None:
        tokens = prefill_tokens + decode_tokens
        flops = self.cost.flops(tokens, attn_ctx_tokens) if tokens else 0.0
        moved = (
            self.cost.bytes_moved(tokens, attn_ctx_tokens, weight_streams)
            if (tokens or weight_streams)
            else 0.0
        )
        t = time.monotonic() if now is None else now
        with self._lock:
            self.prefill_tokens_total += prefill_tokens
            self.decode_tokens_total += decode_tokens
            self.emitted_tokens_total += emitted_tokens
            self.flops_total += flops
            self.bytes_total += moved
            if tokens:
                self.busy_time_total_s += duration_s
            self._samples.append(
                _Sample(
                    t=t, duration_s=duration_s, flops=flops, bytes_moved=moved,
                    emitted_tokens=emitted_tokens, prefill_tokens=prefill_tokens,
                    decode_tokens=decode_tokens,
                )
            )
            self._prune(t)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        samples = self._samples
        while samples and samples[0].t < horizon:
            samples.popleft()

    def rates(self, now: float | None = None) -> dict:
        """Windowed rates.  The denominator is wall time spanned by the
        window (not summed step time): idle gaps correctly drag MFU down —
        an engine that computes brilliantly 10% of the time is 10% utilized."""
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune(t)
            samples = list(self._samples)
        if not samples:
            return {
                "mfu_perc": 0.0, "bandwidth_util_perc": 0.0,
                "goodput_tokens_per_second": 0.0,
                "prefill_tokens_per_second": 0.0,
                "tokens_per_second": 0.0,
            }
        span = max(t - samples[0].t, sum(s.duration_s for s in samples), 1e-6)
        flops = sum(s.flops for s in samples)
        moved = sum(s.bytes_moved for s in samples)
        emitted = sum(s.emitted_tokens for s in samples)
        computed = sum(s.prefill_tokens + s.decode_tokens for s in samples)
        return {
            "mfu_perc": min(flops / span / self.peak_flops, 1.0),
            "bandwidth_util_perc": min(moved / span / self.peak_bytes_per_s, 1.0),
            "goodput_tokens_per_second": emitted / span,
            "prefill_tokens_per_second": sum(
                s.prefill_tokens for s in samples
            ) / span,
            "tokens_per_second": computed / span,
        }

    def stats(self) -> dict:
        """Merged into ``JaxLlmEngine.stats()`` — names are wire-stable
        (ForwardPassMetrics and the Prometheus exporter key off them)."""
        out = self.rates()
        out.update(
            prefill_tokens_total=self.prefill_tokens_total,
            decode_tokens_total=self.decode_tokens_total,
            tokens_emitted_total=self.emitted_tokens_total,
            model_flops_total=self.flops_total,
            model_bytes_total=self.bytes_total,
            busy_time_total_s=self.busy_time_total_s,
        )
        return out
