"""End-to-end request observability.

One request, one ``trace_id``, visible in every layer it touches:

- ``trace``        — the propagated context (``trace_id``/``span_id``/parent)
  minted at the HTTP frontend (honoring an incoming ``x-request-id``) and
  carried through the control-plane request envelope and data-plane prologue.
- ``recorder``     — process-wide span recorder with a bounded buffer,
  JSONL and Chrome-trace (``chrome://tracing`` / Perfetto) exporters, and
  per-request lifecycle summaries (queue wait, prefill, TTFT, KV transfer).
- ``step_metrics`` — engine step telemetry (batch occupancy, running/waiting
  counts, KV pool usage, preemptions) accumulated on the device thread and
  surfaced through the existing Prometheus registries.
- ``perf``        — utilization accounting: analytical FLOPs/bytes cost
  model per model geometry + rolling MFU / bandwidth-utilization / goodput
  (``UtilizationTracker``), exported as ``dyn_worker_*`` gauges.
- ``slo``         — burn-rate SLO tracking over the frontend's TTFT/ITL/
  error stream (``SloTracker``), exported as ``dyn_slo_*`` metrics and the
  frontend's ``/slo`` endpoint.

See docs/observability.md for the metric families, env vars, and formats.
"""

from dynamo_tpu.observability.flight import FlightRecorder, flight_dir, latest_dump, load_dump
from dynamo_tpu.observability.perf import ModelCost, UtilizationTracker, model_cost
from dynamo_tpu.observability.recorder import (
    Span,
    SpanRecorder,
    get_recorder,
    set_recorder,
)
from dynamo_tpu.observability.slo import SloConfig, SloObjective, SloTracker
from dynamo_tpu.observability.step_metrics import StepTelemetry
from dynamo_tpu.observability.trace import TraceContext, new_span_id, new_trace_id

__all__ = [
    "FlightRecorder",
    "ModelCost",
    "SloConfig",
    "SloObjective",
    "SloTracker",
    "Span",
    "SpanRecorder",
    "StepTelemetry",
    "TraceContext",
    "UtilizationTracker",
    "flight_dir",
    "get_recorder",
    "latest_dump",
    "load_dump",
    "model_cost",
    "new_span_id",
    "new_trace_id",
    "set_recorder",
]
