"""End-to-end request observability.

One request, one ``trace_id``, visible in every layer it touches:

- ``trace``        — the propagated context (``trace_id``/``span_id``/parent)
  minted at the HTTP frontend (honoring an incoming ``x-request-id``) and
  carried through the control-plane request envelope and data-plane prologue.
- ``recorder``     — process-wide span recorder with a bounded buffer,
  JSONL and Chrome-trace (``chrome://tracing`` / Perfetto) exporters, and
  per-request lifecycle summaries (queue wait, prefill, TTFT, KV transfer).
- ``step_metrics`` — engine step telemetry (batch occupancy, running/waiting
  counts, KV pool usage, preemptions) accumulated on the device thread and
  surfaced through the existing Prometheus registries.

See docs/observability.md for the metric families, env vars, and formats.
"""

from dynamo_tpu.observability.recorder import (
    Span,
    SpanRecorder,
    get_recorder,
    set_recorder,
)
from dynamo_tpu.observability.step_metrics import StepTelemetry
from dynamo_tpu.observability.trace import TraceContext, new_span_id, new_trace_id

__all__ = [
    "Span",
    "SpanRecorder",
    "StepTelemetry",
    "TraceContext",
    "get_recorder",
    "new_span_id",
    "new_trace_id",
    "set_recorder",
]
