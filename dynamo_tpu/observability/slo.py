"""SLO layer over the frontend's latency/error stream: burn-rate tracking.

An SLO here is "at least ``target`` of observations are good", where good
means TTFT/ITL under a threshold or a request finishing without server
error.  The tracker keeps per-second good/bad buckets and computes the
Google-SRE **burn rate** over multiple windows:

    burn_rate = observed_bad_fraction / error_budget      (budget = 1 - target)

Burn rate 1.0 = exactly consuming the budget; 14.4 over 5 minutes is the
classic "page now" threshold.  Multi-window (default 5m + 1h) separates a
transient blip from a sustained burn.

Configuration (all optional — defaults give a working SLO plane out of the
box so ``dyn_slo_*`` families are always present):

- ``DYN_SLO_TTFT_S`` (default 2.0) / ``DYN_SLO_TTFT_TARGET`` (default 0.99)
- ``DYN_SLO_ITL_S`` (default 0.2) / ``DYN_SLO_ITL_TARGET`` (default 0.99)
- ``DYN_SLO_ERROR_TARGET`` (default 0.999) — request success-rate objective
- ``DYN_SLO_WINDOWS`` (default ``300,3600``) — comma-separated seconds
- ``DYN_SLO_SHED_BURN`` (default 0 = off) — burn-rate threshold above which
  frontend admission control (dynamo_tpu/robustness/admission.py) sheds
  instead of queueing

The HTTP frontend feeds it from the metric guards (llm/http/metrics.py),
renders :meth:`SloTracker.render` onto ``/metrics``, and serves
:meth:`SloTracker.status` as JSON on ``/slo``.
"""

from __future__ import annotations

import os
import threading
import time
from dynamo_tpu.utils import knobs
from dataclasses import dataclass, field

DEFAULT_WINDOWS_S = (300.0, 3600.0)
# per-second buckets are pruned past the longest window; cap the worst case
_MAX_SPAN_S = 2 * 3600


@dataclass(frozen=True)
class SloObjective:
    name: str                       # "ttft" | "itl" | "error_rate" | custom
    target: float                   # good fraction, e.g. 0.99
    threshold_s: float | None = None  # latency objectives: good iff <= this

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.target, 1e-9)


@dataclass
class SloConfig:
    objectives: tuple[SloObjective, ...] = ()
    windows_s: tuple[float, ...] = DEFAULT_WINDOWS_S
    shed_burn_threshold: float = 0.0

    @classmethod
    def from_env(cls) -> "SloConfig":
        objectives = (
            SloObjective("ttft", knobs.get("DYN_SLO_TTFT_TARGET"),
                         threshold_s=knobs.get("DYN_SLO_TTFT_S")),
            SloObjective("itl", knobs.get("DYN_SLO_ITL_TARGET"),
                         threshold_s=knobs.get("DYN_SLO_ITL_S")),
            SloObjective("error_rate", knobs.get("DYN_SLO_ERROR_TARGET")),
        )
        raw = knobs.get("DYN_SLO_WINDOWS")
        windows: list[float] = []
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                w = float(part)
            except ValueError:
                continue
            if w > 0:
                windows.append(min(w, _MAX_SPAN_S))
        return cls(
            objectives=objectives,
            windows_s=tuple(windows) or DEFAULT_WINDOWS_S,
            shed_burn_threshold=knobs.get("DYN_SLO_SHED_BURN"),
        )


@dataclass
class _Counts:
    good: int = 0
    bad: int = 0


class SloTracker:
    """Per-second good/bad buckets per objective + burn-rate math.

    Thread-safe (a lock around the bucket maps): observations come from the
    frontend event loop, reads from /metrics scrapes and the admission
    gate."""

    def __init__(self, config: SloConfig | None = None):
        self.config = config or SloConfig.from_env()
        self._by_objective = {o.name: o for o in self.config.objectives}
        self._buckets: dict[str, dict[int, _Counts]] = {
            o.name: {} for o in self.config.objectives
        }
        self._totals: dict[str, _Counts] = {
            o.name: _Counts() for o in self.config.objectives
        }
        longest = max(self.config.windows_s, default=300.0)
        self._span_s = min(max(longest, 1.0), _MAX_SPAN_S)
        self._lock = threading.Lock()
        # worst_burn_rate() memo for the admission hot path (see below)
        self._worst_cache: tuple[float, float] = (-1e18, 0.0)

    # -- feeding -----------------------------------------------------------
    def observe_latency(self, objective: str, seconds: float,
                        now: float | None = None) -> None:
        obj = self._by_objective.get(objective)
        if obj is None or obj.threshold_s is None:
            return
        self._observe(objective, seconds <= obj.threshold_s, now)

    def observe_outcome(self, objective: str, good: bool,
                        now: float | None = None) -> None:
        if objective in self._by_objective:
            self._observe(objective, good, now)

    def _observe(self, objective: str, good: bool, now: float | None) -> None:
        t = int(time.time() if now is None else now)
        with self._lock:
            buckets = self._buckets[objective]
            counts = buckets.setdefault(t, _Counts())
            totals = self._totals[objective]
            if good:
                counts.good += 1
                totals.good += 1
            else:
                counts.bad += 1
                totals.bad += 1
            # prune: drop seconds no window can see anymore
            horizon = t - int(self._span_s) - 1
            if len(buckets) > self._span_s + 2:
                for sec in [s for s in buckets if s < horizon]:
                    del buckets[sec]

    # -- querying ----------------------------------------------------------
    def _window_counts(self, objective: str, window_s: float,
                       now: float | None = None) -> _Counts:
        t = time.time() if now is None else now
        horizon = int(t - window_s)
        out = _Counts()
        with self._lock:
            for sec, counts in self._buckets.get(objective, {}).items():
                if sec > horizon:
                    out.good += counts.good
                    out.bad += counts.bad
        return out

    def burn_rate(self, objective: str, window_s: float,
                  now: float | None = None) -> float:
        """bad_fraction / error_budget over the window (0.0 when no traffic:
        an idle service is not burning budget)."""
        obj = self._by_objective.get(objective)
        if obj is None:
            return 0.0
        counts = self._window_counts(objective, window_s, now)
        total = counts.good + counts.bad
        if not total:
            return 0.0
        return (counts.bad / total) / obj.error_budget

    def worst_burn_rate(self, now: float | None = None) -> float:
        """Max burn rate across every objective over the SHORTEST window —
        the admission-control signal: sheds should react to the fast window,
        not wait out the hour.

        Computing it scans every per-second bucket, and the admission gate
        consults it per saturated request — exactly when the frontend is
        busiest — so wall-clock calls (``now=None``) are memoized for 1s.
        An explicit ``now`` bypasses the cache (tests, /slo snapshots)."""
        if not self.config.objectives or not self.config.windows_s:
            return 0.0
        use_cache = now is None
        if use_cache:
            now = time.time()
            cached_at, cached = self._worst_cache
            if now - cached_at < 1.0:
                return cached
        window = min(self.config.windows_s)
        worst = max(
            self.burn_rate(o.name, window, now) for o in self.config.objectives
        )
        if use_cache:
            self._worst_cache = (now, worst)
        return worst

    def status(self, now: float | None = None) -> dict:
        """The ``/slo`` endpoint payload."""
        t = time.time() if now is None else now
        objectives = {}
        for o in self.config.objectives:
            windows = {}
            for w in self.config.windows_s:
                counts = self._window_counts(o.name, w, t)
                total = counts.good + counts.bad
                windows[str(int(w))] = {
                    "good": counts.good,
                    "bad": counts.bad,
                    "bad_fraction": (counts.bad / total) if total else 0.0,
                    "burn_rate": self.burn_rate(o.name, w, t),
                }
            with self._lock:
                totals = self._totals[o.name]
                good_total, bad_total = totals.good, totals.bad
            objectives[o.name] = {
                "target": o.target,
                "threshold_s": o.threshold_s,
                "error_budget": o.error_budget,
                "good_total": good_total,
                "bad_total": bad_total,
                "windows": windows,
                # per-objective worst window: the planner's burn-rate input
                # (planner.burn_rates_from_slo) — which objective burns
                # decides WHICH pool the autopilot grows
                "worst_burn_rate": max(
                    (w["burn_rate"] for w in windows.values()), default=0.0
                ),
            }
        return {
            "objectives": objectives,
            "windows_s": list(self.config.windows_s),
            "worst_burn_rate": self.worst_burn_rate(t),
            "shed_burn_threshold": self.config.shed_burn_threshold,
        }

    # -- exposition --------------------------------------------------------
    def render(self, now: float | None = None) -> bytes:
        """Prometheus text exposition of the ``dyn_slo_*`` families (appended
        to the frontend's /metrics body, like the resilience counters)."""
        lines = [
            "# HELP dyn_slo_burn_rate_ratio SLO burn rate (bad fraction / error budget) per objective and window",
            "# TYPE dyn_slo_burn_rate_ratio gauge",
        ]
        for o in self.config.objectives:
            for w in self.config.windows_s:
                lines.append(
                    f'dyn_slo_burn_rate_ratio{{objective="{o.name}",window="{int(w)}"}} '
                    f"{self.burn_rate(o.name, w, now):.6g}"
                )
        lines += [
            "# HELP dyn_slo_good_total Observations meeting the SLO objective",
            "# TYPE dyn_slo_good_total counter",
        ]
        with self._lock:
            totals = {name: (c.good, c.bad) for name, c in self._totals.items()}
        for o in self.config.objectives:
            lines.append(f'dyn_slo_good_total{{objective="{o.name}"}} {totals[o.name][0]}')
        lines += [
            "# HELP dyn_slo_bad_total Observations violating the SLO objective",
            "# TYPE dyn_slo_bad_total counter",
        ]
        for o in self.config.objectives:
            lines.append(f'dyn_slo_bad_total{{objective="{o.name}"}} {totals[o.name][1]}')
        lines += [
            "# HELP dyn_slo_threshold_seconds Latency threshold of the SLO objective",
            "# TYPE dyn_slo_threshold_seconds gauge",
        ]
        for o in self.config.objectives:
            if o.threshold_s is not None:
                lines.append(
                    f'dyn_slo_threshold_seconds{{objective="{o.name}"}} {o.threshold_s:g}'
                )
        return ("\n".join(lines) + "\n").encode()
