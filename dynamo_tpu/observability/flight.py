"""Perf flight recorder: an always-on, bounded ring of per-step telemetry.

Every engine keeps a :class:`FlightRecorder` — a byte-budgeted ring buffer of
per-step telemetry (token counts, batch occupancy, KV usage, MFU/goodput),
SLO burn-rate samples, and discrete events (preemptions, drains, migrations,
injected faults, unified-batch fallbacks) stamped with monotonic timestamps.
The ring costs one dict append per step while everything is healthy; when
something goes wrong the last N seconds of engine behavior are already in
memory and get dumped to JSONL:

- on demand       — ``dynctl flight dump`` (the ingress ``flight_dump`` ctl op)
- on burn breach  — worst-window SLO burn rate above ``DYN_FLIGHT_BURN``
- on worker crash — a ``spawn_logged`` task died with a real exception
- on drain        — the ingress drain state machine started

Dump files are JSONL: one header object (schema version, source, reason,
record count) followed by one record per line, written under
``DYN_FLIGHT_DIR`` (default ``$DYN_CACHE_DIR/flight``).  The planner's load
predictors re-fit from these dumps (``load_predictor.replay_trace``) so
capacity can pre-position ahead of recorded diurnal crests, and
``dyn_top --flight`` tails the newest one.

``DYN_FLIGHT=0`` is bookkeeping-free: the recorder stores nothing, every
``record_*`` call early-returns before touching the ring, and hot paths are
expected to guard with ``if recorder.enabled:`` so not even the kwargs dict
is built.

Summary counters are exposed as ``dyn_flight_*`` on both metric surfaces:
:func:`render` appends a text exposition to the frontend ``/metrics`` body
(like the resilience counters) and the engine merges :meth:`stats` keys into
its ``stats()`` dict, which the metrics service mirrors as worker-labeled
gauges.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import weakref
from collections import deque
from pathlib import Path
from typing import Any, Callable

from dynamo_tpu.utils import knobs
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("dynamo_tpu.observability.flight")

FLIGHT_SCHEMA_VERSION = 1

# discrete-event taxonomy (docs/observability.md); record_event accepts any
# of these (and tolerates new ones — the dump format is self-describing)
EVENT_KINDS = (
    "preemption",          # scheduler victimized a running sequence
    "drain",               # ingress drain state machine started
    "migration",           # live session migration started/committed/aborted
    "fault",               # chaos fault injected (DYN_FAULTS)
    "unified_fallback",    # unified-batch step fell back to split phases
    "step_error",          # engine step raised
    "crash",               # a spawn_logged task died with a real exception
    "burn_breach",         # worst-window SLO burn crossed DYN_FLIGHT_BURN
)

# min seconds between AUTOMATIC dumps for the same reason — a burn storm or
# crash loop must not turn the flight recorder into a disk-filling hazard
DUMP_COOLDOWN_S = 30.0

_REGISTRY: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
_registry_lock = threading.Lock()


def flight_enabled() -> bool:
    """The master gate (``DYN_FLIGHT``)."""
    return bool(knobs.get(knobs.K_FLIGHT))


def flight_dir() -> Path:
    """Directory dumps land in (``DYN_FLIGHT_DIR`` > ``DYN_CACHE_DIR/flight``)."""
    explicit = knobs.get(knobs.K_FLIGHT_DIR)
    if explicit:
        return Path(explicit).expanduser()
    cache = knobs.get(knobs.K_CACHE_DIR)
    base = Path(cache).expanduser() if cache else Path.home() / ".cache" / "dynamo_tpu"
    return base / "flight"


def latest_dump(directory: str | os.PathLike | None = None) -> Path | None:
    """Newest flight dump in ``directory`` (default :func:`flight_dir`)."""
    root = Path(directory) if directory is not None else flight_dir()
    try:
        dumps = sorted(root.glob("flight-*.jsonl"), key=lambda p: p.stat().st_mtime)
    except OSError:
        return None
    return dumps[-1] if dumps else None


def load_dump(path: str | os.PathLike) -> tuple[dict, list[dict]]:
    """(header, records) of one JSONL flight dump."""
    header: dict = {}
    records: list[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if i == 0 and "schema_version" in obj:
                header = obj
            else:
                records.append(obj)
    return header, records


class FlightRecorder:
    """Byte-budgeted ring of telemetry records with JSONL dump-on-trigger.

    Thread-safe: the engine's device thread appends steps while asyncio-side
    triggers (ctl ops, crash callbacks) read and dump.
    """

    def __init__(
        self,
        *,
        source: str = "engine",
        capacity_bytes: int | None = None,
        enabled: bool | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.source = source
        self.enabled = flight_enabled() if enabled is None else bool(enabled)
        if capacity_bytes is None:
            capacity_bytes = int(knobs.get(knobs.K_FLIGHT_BUFFER_BYTES))
        self.capacity_bytes = max(int(capacity_bytes), 0)
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque[tuple[int, dict]] = deque()  # (encoded size, record)
        self.buffer_bytes = 0
        self.records_total = 0
        self.dropped_total = 0
        self.dumps_total = 0
        self.last_dump_reason = ""
        self.last_dump_path: str | None = None
        self._last_auto_dump: dict[str, float] = {}  # reason -> monotonic t
        if self.enabled:
            with _registry_lock:
                _REGISTRY.add(self)

    # -- recording -----------------------------------------------------------

    def _append(self, record: dict) -> None:
        size = len(json.dumps(record, separators=(",", ":"), default=str))
        with self._lock:
            if size > self.capacity_bytes:
                # a single record bigger than the whole budget can never fit
                self.dropped_total += 1
                return
            while self._ring and self.buffer_bytes + size > self.capacity_bytes:
                evicted_size, _ = self._ring.popleft()
                self.buffer_bytes -= evicted_size
                self.dropped_total += 1
            self._ring.append((size, record))
            self.buffer_bytes += size
            self.records_total += 1

    def record_step(self, **fields: Any) -> None:
        """One engine step.  Hot path — callers guard with ``if rec.enabled:``
        so the kwargs dict is never built when the recorder is off."""
        if not self.enabled:
            return
        self._append({"kind": "step", "t": self._clock(), **fields})

    def record_burn(self, objective: str, burn_rate: float, window_s: float) -> None:
        if not self.enabled:
            return
        self._append({
            "kind": "burn", "t": self._clock(),
            "objective": objective, "burn_rate": burn_rate, "window_s": window_s,
        })

    def record_event(self, event: str, **fields: Any) -> None:
        if not self.enabled:
            return
        self._append({"kind": "event", "t": self._clock(), "event": event, **fields})

    # -- introspection -------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def records(self) -> list[dict]:
        with self._lock:
            return [rec for _, rec in self._ring]

    def occupancy(self) -> float:
        """Ring fullness (bytes used / budget) — the dyn_top FLIGHT column."""
        if not self.capacity_bytes:
            return 0.0
        with self._lock:
            return self.buffer_bytes / self.capacity_bytes

    def stats(self) -> dict:
        """``flight_*`` keys merged into engine ``stats()`` (metrics service
        mirrors them as ``dyn_flight_*`` worker gauges)."""
        with self._lock:
            return {
                "flight_records_total": self.records_total,
                "flight_dropped_total": self.dropped_total,
                "flight_dumps_total": self.dumps_total,
                "flight_buffer_bytes": self.buffer_bytes,
                "flight_last_dump_reason": self.last_dump_reason,
            }

    # -- dumping -------------------------------------------------------------

    def dump(self, reason: str, path: str | os.PathLike | None = None) -> Path | None:
        """Write the ring to a JSONL file; returns the path (None when the
        recorder is disabled).  The ring is NOT cleared — a later, worse
        trigger still sees the full window."""
        if not self.enabled:
            return None
        with self._lock:
            records = [rec for _, rec in self._ring]
            self.dumps_total += 1
            seq = self.dumps_total
            self.last_dump_reason = reason
        if path is None:
            safe_reason = re.sub(r"[^a-z0-9_]+", "-", reason.lower()).strip("-") or "manual"
            directory = flight_dir()
            path = directory / (
                f"flight-{self.source}-{os.getpid()}-{seq:03d}-{safe_reason}.jsonl"
            )
        path = Path(path)
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                header = {
                    "schema_version": FLIGHT_SCHEMA_VERSION,
                    "source": self.source,
                    "reason": reason,
                    "records": len(records),
                    "dumped_at": time.time(),
                }
                f.write(json.dumps(header, separators=(",", ":")) + "\n")
                for rec in records:
                    f.write(json.dumps(rec, separators=(",", ":"), default=str) + "\n")
        except OSError as exc:
            logger.warning("flight dump to %s failed: %s", path, exc)
            return None
        self.last_dump_path = str(path)
        logger.info("flight recorder dumped %d records to %s (reason=%s)",
                    len(records), path, reason)
        return path

    def maybe_dump(self, reason: str) -> Path | None:
        """Automatic-trigger dump, rate-limited per reason (burn storms and
        crash loops must not fill the disk)."""
        if not self.enabled:
            return None
        now = time.monotonic()
        last = self._last_auto_dump.get(reason, 0.0)
        if now - last < DUMP_COOLDOWN_S:
            return None
        self._last_auto_dump[reason] = now
        return self.dump(reason)


# -- process-wide helpers (crash/burn hooks, aggregate exposition) -----------


def recorders() -> tuple[FlightRecorder, ...]:
    with _registry_lock:
        return tuple(_REGISTRY)


def dump_all(reason: str, *, force: bool = True) -> list[Path]:
    """Dump every live recorder in the process; returns the paths written."""
    paths = []
    for rec in recorders():
        path = rec.dump(reason) if force else rec.maybe_dump(reason)
        if path is not None:
            paths.append(path)
    return paths


def dump_all_on_drain(**fields: Any) -> list[Path]:
    """Drain hook (ingress state machine): record the drain event on every
    live recorder and dump the pre-drain window (rate-limited)."""
    if not flight_enabled():
        return []
    paths = []
    for rec in recorders():
        rec.record_event("drain", **fields)
        path = rec.maybe_dump("drain")
        if path is not None:
            paths.append(path)
    return paths


def on_task_crash(name: str, exc: BaseException) -> None:
    """Crash hook called from the ``spawn_logged`` done-callback: record the
    crash on every live recorder and dump them (rate-limited)."""
    if not flight_enabled():
        return
    for rec in recorders():
        rec.record_event("crash", task=name, error=f"{type(exc).__name__}: {exc}")
        rec.maybe_dump("crash")


_BURN_CHECK_PERIOD_S = 1.0
_last_burn_check = 0.0
_burn_lock = threading.Lock()


def check_burn(slo_tracker, now: float | None = None) -> bool:
    """Burn-breach trigger, called per finished request from the frontend:
    when the worst-window burn rate crosses ``DYN_FLIGHT_BURN``, record a
    burn sample on every recorder and auto-dump.  Rate-limited to one check
    per second (``worst_burn_rate`` memoizes on the same cadence)."""
    threshold = float(knobs.get(knobs.K_FLIGHT_BURN))
    if threshold <= 0 or not flight_enabled():
        return False
    global _last_burn_check
    wall = time.monotonic()
    with _burn_lock:
        if wall - _last_burn_check < _BURN_CHECK_PERIOD_S:
            return False
        _last_burn_check = wall
    worst = slo_tracker.worst_burn_rate(now)
    if worst <= threshold:
        return False
    for rec in recorders():
        rec.record_burn("worst", worst, 0.0)
        rec.maybe_dump("burn_breach")
    return True


def render() -> bytes:
    """Prometheus text exposition of the aggregate ``dyn_flight_*`` families,
    appended to the frontend ``/metrics`` body (like the resilience
    counters).  Families are always declared — zeros when no recorder is
    live — so dashboards and check_metrics see a stable surface."""
    totals = {"records": 0, "dropped": 0, "dumps": 0, "buffer": 0}
    for rec in recorders():
        s = rec.stats()
        totals["records"] += s["flight_records_total"]
        totals["dropped"] += s["flight_dropped_total"]
        totals["dumps"] += s["flight_dumps_total"]
        totals["buffer"] += s["flight_buffer_bytes"]
    lines = [
        "# HELP dyn_flight_records_total Flight-recorder records captured",
        "# TYPE dyn_flight_records_total counter",
        f"dyn_flight_records_total {totals['records']}",
        "# HELP dyn_flight_dropped_total Flight-recorder records evicted over the byte budget",
        "# TYPE dyn_flight_dropped_total counter",
        f"dyn_flight_dropped_total {totals['dropped']}",
        "# HELP dyn_flight_dumps_total Flight-recorder JSONL dumps written",
        "# TYPE dyn_flight_dumps_total counter",
        f"dyn_flight_dumps_total {totals['dumps']}",
        "# HELP dyn_flight_buffer_bytes Flight-recorder ring occupancy in bytes",
        "# TYPE dyn_flight_buffer_bytes gauge",
        f"dyn_flight_buffer_bytes {totals['buffer']}",
        "",
    ]
    return "\n".join(lines).encode()
