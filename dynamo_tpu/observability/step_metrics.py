"""Engine step telemetry.

The engine's device loop calls :meth:`StepTelemetry.observe_step` once per
scheduler iteration (plain Python assignments under the GIL — safe to read
from the asyncio thread).  The snapshot rides the existing telemetry path:
``JaxLlmEngine.stats()`` merges it, ``WorkerMetricsPublisher`` ships it as
``ForwardPassMetrics``, and ``components/metrics_service.py`` exports it as
``dyn_worker_*`` Prometheus gauges — no new registry, one coherent pipeline.
"""

from __future__ import annotations

import time
from dataclasses import dataclass


@dataclass
class StepSnapshot:
    """State of the most recent engine step."""

    iteration: int = 0
    num_running: int = 0
    num_waiting: int = 0
    batch_occupancy_perc: float = 0.0   # running lanes / max_batch_size
    kv_usage_perc: float = 0.0          # used blocks / pool blocks
    kv_active_blocks: int = 0
    step_duration_s: float = 0.0
    timestamp_s: float = 0.0
    prefill_tokens: int = 0             # prompt tokens computed this step
    decode_tokens: int = 0              # decode positions computed this step


class StepTelemetry:
    """Latest-step snapshot + monotone counters, cheap enough for every step."""

    def __init__(self, max_batch_size: int):
        self.max_batch_size = max(max_batch_size, 1)
        self.snapshot = StepSnapshot()
        self.steps_total = 0
        self.busy_steps_total = 0        # steps with at least one running lane
        self.step_time_total_s = 0.0

    def observe_step(
        self,
        *,
        iteration: int,
        num_running: int,
        num_waiting: int,
        kv_active_blocks: int,
        kv_total_blocks: int,
        step_duration_s: float,
        prefill_tokens: int = 0,
        decode_tokens: int = 0,
    ) -> None:
        self.snapshot = StepSnapshot(
            iteration=iteration,
            num_running=num_running,
            num_waiting=num_waiting,
            batch_occupancy_perc=num_running / self.max_batch_size,
            kv_usage_perc=(
                kv_active_blocks / kv_total_blocks if kv_total_blocks else 0.0
            ),
            kv_active_blocks=kv_active_blocks,
            step_duration_s=step_duration_s,
            timestamp_s=time.time(),
            prefill_tokens=prefill_tokens,
            decode_tokens=decode_tokens,
        )
        self.steps_total += 1
        if num_running:
            self.busy_steps_total += 1
        self.step_time_total_s += step_duration_s

    def stats(self) -> dict:
        """Merged into ``JaxLlmEngine.stats()`` (names stable: the wire
        protocol and the Prometheus exporter key off them).  The ``step_*``
        names are the state AT the latest step — a coherent point-in-time
        view, unlike the live scheduler/allocator reads the engine's other
        stats fields take mid-drain."""
        s = self.snapshot
        return {
            "batch_occupancy_perc": s.batch_occupancy_perc,
            "step_num_running": s.num_running,
            "step_num_waiting": s.num_waiting,
            "step_kv_usage_perc": s.kv_usage_perc,
            "step_kv_active_blocks": s.kv_active_blocks,
            "engine_steps_total": self.steps_total,
            "engine_busy_steps_total": self.busy_steps_total,
            "engine_step_time_total_s": self.step_time_total_s,
            "last_step_duration_s": s.step_duration_s,
        }
