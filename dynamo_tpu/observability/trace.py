"""Trace context: the identity a request carries across layers.

W3C-trace-context-shaped (a 32-hex trace id, 16-hex span ids) but carried on
this stack's own wire envelopes rather than HTTP headers between internal
hops: the frontend mints the context (honoring an incoming ``x-request-id``
as the trace id), and every downstream layer derives child contexts from it.

The wire form is a tiny msgpack/json-safe dict (``{"t","s","p"}``) so it can
ride the control-plane request envelope (runtime/client.py), the data-plane
frame headers (runtime/codec.py), and control-plane RPC frames
(runtime/controlplane/wire.py) without schema machinery.
"""

from __future__ import annotations

import os
import re
import uuid
from dataclasses import dataclass


def new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars


def new_span_id() -> str:
    return os.urandom(8).hex()  # 16 hex chars


# request ids become trace ids; keep them safe for logs/filenames/metrics
_SAFE_ID = re.compile(r"[^A-Za-z0-9._\-]")
_MAX_ID_LEN = 128


def sanitize_request_id(raw: str | None) -> str | None:
    """Clamp a client-supplied ``x-request-id`` to something safe to echo,
    log, and use as a trace id (None when unusable)."""
    if not raw:
        return None
    cleaned = _SAFE_ID.sub("_", raw.strip())[:_MAX_ID_LEN]
    return cleaned or None


# the one reserved key every transport uses to carry a TraceContext wire
# dict (control-plane RPC frames, the request envelope's control map,
# data-plane frame headers, the disagg prefill-queue item)
TRACE_WIRE_KEY = "tr"


def stamp_trace(mapping: dict, trace: "TraceContext | None") -> dict:
    """Stamp a TraceContext onto any wire mapping (no-op for None)."""
    if trace is not None:
        mapping[TRACE_WIRE_KEY] = trace.to_wire()
    return mapping


def read_trace(mapping: object) -> "TraceContext | None":
    """Decode a wire mapping's trace context (None when absent/malformed)."""
    if not isinstance(mapping, dict):
        return None
    return TraceContext.from_wire(mapping.get(TRACE_WIRE_KEY))


@dataclass(frozen=True)
class TraceContext:
    """The context of the *current enclosing span*: children derive from it
    via :meth:`child`, serialization via :meth:`to_wire`."""

    trace_id: str
    span_id: str
    parent_span_id: str | None = None

    @classmethod
    def new_root(cls, trace_id: str | None = None) -> "TraceContext":
        return cls(trace_id=trace_id or new_trace_id(), span_id=new_span_id())

    def child(self) -> "TraceContext":
        return TraceContext(
            trace_id=self.trace_id, span_id=new_span_id(), parent_span_id=self.span_id
        )

    def to_wire(self) -> dict:
        d = {"t": self.trace_id, "s": self.span_id}
        if self.parent_span_id:
            d["p"] = self.parent_span_id
        return d

    @classmethod
    def from_wire(cls, d: object) -> "TraceContext | None":
        """Lenient decode: malformed/absent contexts degrade to None (a
        broken peer must never fail a request over telemetry)."""
        if not isinstance(d, dict):
            return None
        trace_id, span_id = d.get("t"), d.get("s")
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        parent = d.get("p")
        return cls(
            trace_id=trace_id,
            span_id=span_id,
            parent_span_id=parent if isinstance(parent, str) else None,
        )
