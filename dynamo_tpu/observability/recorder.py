"""Span recorder: bounded in-memory buffer + JSONL / Chrome-trace exporters.

Every layer records spans here (the HTTP frontend, the KV router, the push
dispatch, the worker ingress, and the engine's device thread — the recorder
is thread-safe).  Spans carry the propagated :class:`TraceContext`, so one
request's tree can be reassembled with :meth:`SpanRecorder.spans_for` and
summarized with :meth:`SpanRecorder.summary`.

Exports:

- ``export_jsonl`` — one JSON object per span (grep/jq-friendly).  Setting
  ``DYN_TRACE_JSONL=/path/file.jsonl`` streams every finished span there
  live.  ``DYN_TRACE_MAX_BYTES`` bounds it: when the file would exceed the
  limit it rotates to ``file.jsonl.1`` (replacing any previous rotation)
  and a fresh file starts — at most ~2x the limit on disk, newest spans
  always in the live file.  0/unset = unbounded (previous behavior).
- ``export_chrome_trace`` — Chrome trace-event format ("X" complete events,
  microsecond timestamps) loadable in ``chrome://tracing`` or Perfetto;
  components render as processes, requests as threads.

Buffer size: ``DYN_TRACE_BUFFER`` (spans, default 4096).  Per-process
singleton via :func:`get_recorder`; tests may install a fresh one with
:func:`set_recorder`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from dynamo_tpu.observability.trace import TraceContext
from dynamo_tpu.utils import knobs

_DEFAULT_BUFFER = 4096


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_span_id: str | None
    name: str
    component: str
    start_s: float              # unix epoch seconds
    end_s: float
    status: str = "ok"
    attrs: dict = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end_s - self.start_s)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "component": self.component,
            "start_s": self.start_s,
            "end_s": self.end_s,
            "duration_s": self.duration_s,
            "status": self.status,
            "attrs": self.attrs,
        }


class SpanHandle:
    """An open span; :meth:`end` records it.  ``.ctx`` is the context
    downstream work should parent to."""

    __slots__ = ("_recorder", "ctx", "name", "component", "start_s", "attrs", "_done")

    def __init__(self, recorder: "SpanRecorder", ctx: TraceContext, name: str,
                 component: str, attrs: dict | None):
        self._recorder = recorder
        self.ctx = ctx
        self.name = name
        self.component = component
        self.start_s = time.time()
        self.attrs = dict(attrs or {})
        self._done = False

    def end(self, status: str = "ok", **attrs) -> None:
        if self._done:  # idempotent: error paths may double-close
            return
        self._done = True
        self.attrs.update(attrs)
        self._recorder._record(
            Span(
                trace_id=self.ctx.trace_id,
                span_id=self.ctx.span_id,
                parent_span_id=self.ctx.parent_span_id,
                name=self.name,
                component=self.component,
                start_s=self.start_s,
                end_s=time.time(),
                status=status,
                attrs=self.attrs,
            )
        )

class SpanRecorder:
    def __init__(
        self,
        max_spans: int | None = None,
        jsonl_path: str | None = None,
        max_jsonl_bytes: int | None = None,
    ):
        if max_spans is None:
            max_spans = knobs.get("DYN_TRACE_BUFFER")
        self._spans: deque[Span] = deque(maxlen=max(max_spans, 1))
        self._lock = threading.Lock()
        self._jsonl_path = jsonl_path or knobs.get("DYN_TRACE_JSONL") or None
        if max_jsonl_bytes is None:
            max_jsonl_bytes = knobs.get("DYN_TRACE_MAX_BYTES")
        self._max_jsonl_bytes = max(max_jsonl_bytes, 0)
        self._file_lock = threading.Lock()
        self._jsonl_bytes = 0
        if self._jsonl_path and self._max_jsonl_bytes:
            try:
                self._jsonl_bytes = os.path.getsize(self._jsonl_path)
            except OSError:
                self._jsonl_bytes = 0

    # -- recording ---------------------------------------------------------
    def start(
        self,
        name: str,
        parent: TraceContext | None,
        *,
        component: str,
        root_trace_id: str | None = None,
        attrs: dict | None = None,
    ) -> SpanHandle | None:
        """Open a child span under ``parent`` (or a root span when ``parent``
        is None and ``root_trace_id`` is given).  Returns None — record
        nothing — when there is no trace to attach to: untraced requests
        stay zero-cost."""
        if parent is not None:
            ctx = parent.child()
        elif root_trace_id is not None:
            ctx = TraceContext.new_root(root_trace_id)
        else:
            return None
        return SpanHandle(self, ctx, name, component, attrs)

    def record(
        self,
        name: str,
        parent: TraceContext | None,
        start_s: float,
        end_s: float,
        *,
        component: str,
        status: str = "ok",
        attrs: dict | None = None,
    ) -> TraceContext | None:
        """Record a completed span with explicit timestamps (device-thread
        paths measure first, record after).  Returns the new span's context
        (for nesting) or None when untraced."""
        if parent is None:
            return None
        ctx = parent.child()
        self._record(
            Span(
                trace_id=ctx.trace_id,
                span_id=ctx.span_id,
                parent_span_id=ctx.parent_span_id,
                name=name,
                component=component,
                start_s=start_s,
                end_s=end_s,
                status=status,
                attrs=dict(attrs or {}),
            )
        )
        return ctx

    def _record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        if self._jsonl_path:
            line = json.dumps(span.to_dict(), default=str) + "\n"
            with self._file_lock:
                try:
                    if (
                        self._max_jsonl_bytes
                        and self._jsonl_bytes
                        and self._jsonl_bytes + len(line) > self._max_jsonl_bytes
                    ):
                        # size-based rotation: keep one previous generation,
                        # newest spans always land in the live file
                        os.replace(self._jsonl_path, self._jsonl_path + ".1")
                        self._jsonl_bytes = 0
                    with open(self._jsonl_path, "a") as f:
                        f.write(line)
                    self._jsonl_bytes += len(line)
                except OSError:
                    pass  # live export is best-effort; the buffer still has it

    # -- querying ----------------------------------------------------------
    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def spans_for(self, trace_id: str) -> list[Span]:
        return sorted(
            (s for s in self.snapshot() if s.trace_id == trace_id),
            key=lambda s: s.start_s,
        )

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def summary(self, trace_id: str) -> dict:
        """Per-request lifecycle summary assembled from the span tree:
        queue wait, prefill time, decode time, TTFT, per-token ITL, and KV
        transfer bytes/latency (zeros for phases the request never hit)."""
        spans = self.spans_for(trace_id)

        def total(name: str) -> float:
            return sum(s.duration_s for s in spans if s.name == name)

        root = next((s for s in spans if s.parent_span_id is None), None)
        ttft = None
        for s in spans:
            if ttft is None and s.attrs.get("ttft_s") is not None:
                ttft = float(s.attrs["ttft_s"])
        decode_spans = [s for s in spans if s.name == "engine.decode"]
        decode_s = sum(s.duration_s for s in decode_spans)
        # ITL is averaged PER decode span (an n>1 fanout yields one decode
        # span per choice; summing time across spans but taking one span's
        # token count would inflate the figure n-fold)
        itl_gaps = sum(
            max(int(s.attrs.get("tokens_out", 0) or 0) - 1, 0) for s in decode_spans
        )
        tokens_out = int(root.attrs.get("tokens_out", 0) or 0) if root else 0
        if not tokens_out:
            tokens_out = sum(
                int(s.attrs.get("tokens_out", 0) or 0) for s in decode_spans
            )
        kv_spans = [s for s in spans if s.name == "kv.transfer"]
        summary = {
            "trace_id": trace_id,
            "spans": len(spans),
            "total_s": root.duration_s if root else sum(s.duration_s for s in spans),
            "status": root.status if root else ("ok" if spans else "missing"),
            "queue_wait_s": total("engine.queue"),
            "prefill_s": total("engine.prefill"),
            "decode_s": decode_s,
            "ttft_s": ttft,
            "tokens_out": tokens_out,
            "itl_avg_s": (decode_s / itl_gaps) if itl_gaps else None,
            "kv_transfer_bytes": sum(
                int(s.attrs.get("bytes", 0) or 0) for s in kv_spans
            ),
            "kv_transfer_s": sum(s.duration_s for s in kv_spans),
        }
        return summary

    # -- exporters ---------------------------------------------------------
    def to_jsonl(self, trace_id: str | None = None) -> str:
        spans = self.spans_for(trace_id) if trace_id else self.snapshot()
        return "".join(json.dumps(s.to_dict(), default=str) + "\n" for s in spans)

    def export_jsonl(self, path: str, trace_id: str | None = None) -> int:
        spans = self.spans_for(trace_id) if trace_id else self.snapshot()
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(s.to_dict(), default=str) + "\n")
        return len(spans)

    def to_chrome_trace(self, trace_id: str | None = None) -> dict:
        """Chrome trace-event JSON: one "X" (complete) event per span, with
        components mapped to pids (named via metadata events) so Perfetto
        lays the request out frontend/router/worker/engine lanes."""
        spans = self.spans_for(trace_id) if trace_id else self.snapshot()
        components = sorted({s.component for s in spans})
        pid_of = {c: i + 1 for i, c in enumerate(components)}
        tids: dict[str, int] = {}
        events: list[dict] = [
            {
                "ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": comp},
            }
            for comp, pid in pid_of.items()
        ]
        for s in spans:
            tid = tids.setdefault(s.trace_id, len(tids) + 1)
            events.append(
                {
                    "name": s.name,
                    "cat": s.component,
                    "ph": "X",
                    "ts": s.start_s * 1e6,       # microseconds
                    "dur": s.duration_s * 1e6,
                    "pid": pid_of[s.component],
                    "tid": tid,
                    "args": {
                        "trace_id": s.trace_id,
                        "span_id": s.span_id,
                        "parent_span_id": s.parent_span_id,
                        "status": s.status,
                        **{k: str(v) for k, v in s.attrs.items()},
                    },
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str, trace_id: str | None = None) -> int:
        doc = self.to_chrome_trace(trace_id)
        with open(path, "w") as f:
            json.dump(doc, f)
        return sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")


_global_lock = threading.Lock()
_global_recorder: SpanRecorder | None = None


def get_recorder() -> SpanRecorder:
    global _global_recorder
    with _global_lock:
        if _global_recorder is None:
            _global_recorder = SpanRecorder()
        return _global_recorder


def set_recorder(recorder: SpanRecorder) -> SpanRecorder:
    global _global_recorder
    with _global_lock:
        _global_recorder = recorder
        return recorder
