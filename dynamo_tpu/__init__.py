"""dynamo_tpu — a TPU-native distributed LLM inference framework.

A ground-up re-design of the capabilities of NVIDIA Dynamo (the orchestration
plane for high-throughput distributed LLM serving) for TPU hardware:

- ``dynamo_tpu.runtime``  — distributed runtime: streaming engines, pipeline
  graph, component/endpoint discovery, control plane (KV store with leases and
  watches + message bus), TCP data plane.  (Reference: ``lib/runtime`` crate.)
- ``dynamo_tpu.llm``      — LLM domain library: OpenAI protocol types, HTTP
  frontend, preprocessor, detokenizing backend, KV-aware router, disaggregated
  prefill/decode router, KV block manager, mocker engine.  (Reference:
  ``lib/llm`` crate.)
- ``dynamo_tpu.models``   — JAX model definitions (Llama/Qwen/Mixtral-class)
  built for pjit/SPMD sharding over a ``jax.sharding.Mesh``.
- ``dynamo_tpu.ops``      — TPU compute ops: paged attention, block
  gather/scatter (Pallas), RoPE, rmsnorm, sampling.
- ``dynamo_tpu.parallel`` — mesh construction, sharding specs, multi-host
  bootstrap, cross-mesh KV transfer (ICI/DCN; replaces NIXL/RDMA).
- ``dynamo_tpu.engine``   — the in-process JAX inference engine: paged KV
  cache, continuous-batching scheduler, streaming generate loop.  (Replaces
  the reference's vLLM/SGLang/TRT-LLM adapters with a native engine.)
- ``dynamo_tpu.planner``  — load/SLA autoscaling planner.
- ``dynamo_tpu.sdk``      — service-graph DSL + local serving.

The compute path is JAX/XLA/Pallas; orchestration is asyncio Python with
native (C++) components for hot data-plane paths under ``csrc/``.
"""

__version__ = "0.1.0"
