"""TPU resource allocator — per-replica chip assignment for local serving.

The reference partitions host GPUs across service worker replicas and
exports ``CUDA_VISIBLE_DEVICES`` per process (reference:
deploy/sdk/src/dynamo/sdk/cli/allocator.py:53-151 — ``assign_gpus`` +
``get_resource_envs``).  Without this, two ``workers=2`` services on one
host would all claim the whole TPU slice and the second process would hang
in libtpu chip init.  The TPU-native analog partitions the host's chips and
exports ``TPU_VISIBLE_CHIPS`` per replica process.

TPU-first deviations from the reference:

- **No fractional chips.** The reference fractionally time-shares a GPU
  between services (``assign_gpus`` count<1).  libtpu claims a chip
  exclusively for one process — a fractional request is a deployment error
  here, not a scheduling strategy, so it raises :class:`ResourceError`.
- **Contiguous runs.** Chips are assigned as contiguous index runs so a
  tp>1 replica's chips sit on adjacent ICI links (chip index order follows
  the physical torus on single-host slices); the reference assigns
  arbitrary free GPU indices.
- **Fail fast on over-subscription.** The reference logs a warning and
  serves anyway (CUDA time-shares); on TPU the over-subscribed process
  would deadlock on the chip claim, so exhausting the inventory raises
  unless ``DYN_DISABLE_AUTO_TPU_ALLOCATION=1`` opts the deployment out of
  allocation entirely (the operator/K8s path does its own placement via
  the ``google.com/tpu`` extended resource — deploy/operator.py).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils import knobs

logger = get_logger("sdk.allocator")

# opt-out switch, mirroring the reference's DYN_DISABLE_AUTO_GPU_ALLOCATION
DISABLE_ENV = "DYN_DISABLE_AUTO_TPU_ALLOCATION"
# the env var libtpu reads to restrict a process to a chip subset; also
# what ChipInventory.detect() honors when the parent was itself restricted
VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"


class ResourceError(RuntimeError):
    """Chip request that cannot be satisfied (or is meaningless on TPU)."""


@dataclass(frozen=True)
class ChipInventory:
    """The TPU chips this host may hand out, as libtpu chip indices."""

    chips: tuple[int, ...]
    device_kind: str = "tpu"

    @classmethod
    def detect(cls, env: dict | None = None) -> "ChipInventory":
        """Inventory from the environment, cheapest signal first.

        1. ``TPU_VISIBLE_CHIPS`` — already restricted (nested supervisors,
           operator-managed pods): inherit exactly that subset.
        2. ``DYN_TPU_CHIP_COUNT`` — explicit operator knob.
        3. An initialized jax TPU backend, if one already exists in this
           process (never initializes jax here: supervisor CLIs must not
           pay — or wedge on — device bring-up just to plan processes).
        4. Otherwise: empty inventory (CPU host / no TPU visible).
        """
        env = os.environ if env is None else env
        visible = env.get(VISIBLE_CHIPS_ENV)
        if visible:
            return cls(chips=tuple(int(c) for c in visible.split(",") if c != ""))
        count = knobs.get("DYN_TPU_CHIP_COUNT", env=env)
        if count:
            return cls(chips=tuple(range(count)))
        try:
            import jax
            from jax._src import xla_bridge

            # private check on purpose: the PUBLIC backends() call would
            # INITIALIZE the backend, i.e. claim the TPU from the planner
            # process — the one thing detect() must never do
            if xla_bridge._backends and jax.default_backend() == "tpu":
                return cls(
                    chips=tuple(d.id for d in jax.local_devices()),
                    device_kind=jax.local_devices()[0].device_kind,
                )
        except Exception:  # noqa: BLE001 — detection must never raise
            pass
        return cls(chips=())


@dataclass
class ResourceAllocator:
    """Hands out disjoint chip sets to service replicas on one host."""

    inventory: ChipInventory
    _free: list[int] = field(init=False)
    assignments: dict[str, list[list[int]]] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self._free = sorted(self.inventory.chips)

    @property
    def remaining(self) -> int:
        return len(self._free)

    def assign_chips(self, count: float, service_name: str = "") -> list[int]:
        """Claim ``count`` chips as a contiguous run; they leave the pool.

        Raises :class:`ResourceError` on fractional requests (TPU chips are
        process-exclusive) and on over-subscription (the claim would
        deadlock at runtime, so fail at plan time)."""
        if count != int(count) or count < 1:
            raise ResourceError(
                f"{service_name or 'service'}: requested {count} TPU chips — "
                "chips are process-exclusive (libtpu claims whole chips); "
                "use integer counts, or omit the tpu resource for CPU-only "
                "services"
            )
        count = int(count)
        if count > len(self._free):
            raise ResourceError(
                f"{service_name or 'service'}: requested {count} TPU chips "
                f"but only {len(self._free)} of {len(self.inventory.chips)} "
                f"remain unassigned; set {DISABLE_ENV}=1 to manage "
                f"{VISIBLE_CHIPS_ENV} manually"
            )
        # prefer a contiguous run (ICI adjacency); fall back to the lowest
        # free indices when fragmentation leaves no run long enough
        run = self._contiguous_run(count)
        assigned = run if run is not None else self._free[:count]
        for c in assigned:
            self._free.remove(c)
        if service_name:
            self.assignments.setdefault(service_name, []).append(list(assigned))
        logger.info(
            "assigned chips %s to %s (%d remain)",
            assigned, service_name or "<anon>", len(self._free),
        )
        return list(assigned)

    def _contiguous_run(self, count: int) -> list[int] | None:
        free = self._free
        for i in range(len(free) - count + 1):
            window = free[i : i + count]
            if window[-1] - window[0] == count - 1:
                return list(window)
        return None

    def replica_envs(
        self, *, tpu: float, workers: int, service_name: str = ""
    ) -> list[dict[str, str]]:
        """One env overlay per worker replica, each with a disjoint chip set
        (the reference's local-deployment branch: one ``assign_gpus`` call
        per worker → per-worker ``CUDA_VISIBLE_DEVICES``)."""
        envs = []
        for _ in range(workers):
            chips = self.assign_chips(tpu, service_name)
            envs.append({
                VISIBLE_CHIPS_ENV: ",".join(str(c) for c in chips),
                # the framework's own record, independent of libtpu's var
                "DYN_TPU_CHIPS": ",".join(str(c) for c in chips),
            })
        return envs


def plan_resource_envs(
    services: list, *, inventory: ChipInventory | None = None,
    env: dict | None = None,
) -> dict[str, list[dict[str, str]]]:
    """Per-service, per-replica env overlays for a whole dependency closure.

    ``services`` is a list of @service-decorated classes (sdk/graph.py).
    Services without a ``tpu`` resource get empty overlays.  Returns {} for
    every service when allocation is disabled or no chips are visible —
    processes then see whatever the parent saw, exactly like the reference
    with DYN_DISABLE_AUTO_GPU_ALLOCATION set."""
    env = os.environ if env is None else env
    if knobs.get(DISABLE_ENV, env=env):
        return {}
    inventory = ChipInventory.detect(env) if inventory is None else inventory
    requested = {
        cls._dyn_service.name: cls._dyn_service
        for cls in services
        if (cls._dyn_service.resources or {}).get("tpu")
    }
    if not requested:
        return {}
    if not inventory.chips:
        logger.warning(
            "services %s request TPU chips but none are visible on this "
            "host; skipping chip allocation", sorted(requested),
        )
        return {}
    allocator = ResourceAllocator(inventory)
    return {
        name: allocator.replica_envs(
            tpu=config.resources["tpu"], workers=config.workers,
            service_name=name,
        )
        for name, config in requested.items()
    }
