"""Worker entrypoint for subprocess-mode service graphs (the serve_dynamo.py
analog, reference: deploy/sdk/.../cli/serve_dynamo.py): load ``module:Class``,
connect the control plane, deploy the service, run until signalled."""

from __future__ import annotations

import argparse
import asyncio
import signal

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.sdk.graph import deploy_service, resolve_entry
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger("sdk.runner")


async def amain(target: str, control_plane: str) -> int:
    configure_logging()
    cls = resolve_entry(target)

    runtime = await DistributedRuntime.create(RuntimeConfig(control_plane=control_plane))
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, runtime.shutdown)

    handles = await deploy_service(runtime, cls)
    logger.info("service %s up", target)
    await runtime.wait_for_shutdown()
    for handle in handles:
        await handle.shutdown(drain_timeout=10)
    await runtime.close()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("target", help="module:Class of the @service")
    parser.add_argument("--control-plane", default="127.0.0.1:2379")
    args = parser.parse_args()
    return asyncio.run(amain(args.target, args.control_plane))


if __name__ == "__main__":
    raise SystemExit(main())
