"""Process supervisor — the local serving substrate.

The circus-arbiter equivalent (reference: deploy/sdk/.../cli/serving.py
create_circus_watcher): each *watcher* is a named process spec with a target
replica count; the supervisor spawns/retires/restarts OS processes to match,
with exponential backoff on crash loops and graceful SIGTERM drain.
"""

from __future__ import annotations

import asyncio
import os
import signal
import sys
import time
from dataclasses import dataclass, field

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("sdk.supervisor")


@dataclass
class ProcessSpec:
    name: str
    cmd: list[str]                      # argv; {replica} substituted
    env: dict[str, str] = field(default_factory=dict)
    # per-replica env overlays on top of ``env`` (index → vars) — how the
    # TPU allocator pins each replica to its disjoint chip set
    # (sdk/allocator.py; reference allocator.py's per-worker
    # CUDA_VISIBLE_DEVICES list).  A replica index past the list's end gets
    # no overlay; a restart of replica i re-applies overlay i, so the
    # restarted process reclaims the SAME chips.
    replica_env: list[dict[str, str]] = field(default_factory=list)
    replicas: int = 1                   # default target for add_watcher
    cwd: str | None = None
    restart: bool = True
    max_restarts: int = 5
    stop_timeout_s: float = 10.0


@dataclass
class _Replica:
    index: int
    process: asyncio.subprocess.Process
    started_at: float
    restarts: int = 0


class ProcessSupervisor:
    def __init__(self) -> None:
        self._specs: dict[str, ProcessSpec] = {}
        self._replicas: dict[str, dict[int, _Replica]] = {}
        self._targets: dict[str, int] = {}
        self._monitor: asyncio.Task | None = None
        self._stopping = False

    def add_watcher(self, spec: ProcessSpec, replicas: int | None = None) -> None:
        self._specs[spec.name] = spec
        self._replicas.setdefault(spec.name, {})
        self._targets[spec.name] = spec.replicas if replicas is None else replicas

    async def start(self) -> None:
        self._stopping = False
        for name in self._specs:
            await self._reconcile(name)
        if self._monitor is None:
            self._monitor = spawn_logged(self._monitor_loop())

    async def set_replicas(self, name: str, n: int) -> None:
        self._targets[name] = max(0, n)
        await self._reconcile(name)

    def replica_count(self, name: str) -> int:
        return len(self._replicas.get(name, {}))

    async def stop(self) -> None:
        self._stopping = True
        if self._monitor is not None:
            self._monitor.cancel()
            self._monitor = None
        for name in list(self._specs):
            self._targets[name] = 0
            await self._reconcile(name)

    # -- internals ---------------------------------------------------------
    async def _reconcile(self, name: str) -> None:
        spec = self._specs[name]
        replicas = self._replicas[name]
        target = self._targets[name]
        # scale up
        idx = 0
        while len(replicas) < target:
            while idx in replicas:
                idx += 1
            replicas[idx] = await self._spawn(spec, idx)
        # scale down: retire highest indices first
        while len(replicas) > target:
            highest = max(replicas)
            await self._terminate(spec, replicas.pop(highest))

    async def _spawn(self, spec: ProcessSpec, index: int) -> _Replica:
        cmd = [arg.replace("{replica}", str(index)) for arg in spec.cmd]
        env = dict(os.environ)
        env.update(spec.env)
        if spec.replica_env:
            if index >= len(spec.replica_env):
                # scaling past the planned overlays would spawn a replica
                # seeing the WHOLE chip inventory — exactly the libtpu
                # claim collision the allocator exists to prevent.  Fail
                # the scale-up loudly; re-plan with more workers (or set
                # DYN_DISABLE_AUTO_TPU_ALLOCATION=1) to go further.
                raise RuntimeError(
                    f"{spec.name}[{index}]: no chip-env overlay planned for "
                    f"this replica ({len(spec.replica_env)} were allocated); "
                    "re-plan the deployment with more workers"
                )
            env.update(spec.replica_env[index])
        env["DYN_REPLICA_INDEX"] = str(index)
        process = await asyncio.create_subprocess_exec(
            *cmd, env=env, cwd=spec.cwd,
            stdout=sys.stderr, stderr=sys.stderr,
        )
        logger.info("spawned %s[%d] pid=%d", spec.name, index, process.pid)
        return _Replica(index=index, process=process, started_at=time.monotonic())

    async def _terminate(self, spec: ProcessSpec, replica: _Replica) -> None:
        process = replica.process
        if process.returncode is None:
            process.send_signal(signal.SIGTERM)
            try:
                await asyncio.wait_for(process.wait(), spec.stop_timeout_s)
            except asyncio.TimeoutError:
                logger.warning("%s[%d] did not stop; killing", spec.name, replica.index)
                process.kill()
                await process.wait()
        logger.info("stopped %s[%d]", spec.name, replica.index)

    async def _monitor_loop(self) -> None:
        while True:
            await asyncio.sleep(0.5)
            for name, spec in self._specs.items():
                replicas = self._replicas[name]
                for index, replica in list(replicas.items()):
                    if replica.process.returncode is None:
                        continue
                    del replicas[index]
                    if self._stopping or not spec.restart:
                        continue
                    if len(replicas) >= self._targets[name]:
                        continue
                    if replica.restarts >= spec.max_restarts:
                        logger.error(
                            "%s[%d] crash-looped %d times; giving up",
                            name, index, replica.restarts,
                        )
                        continue
                    backoff = min(2.0 ** replica.restarts * 0.2, 10.0)
                    logger.warning(
                        "%s[%d] exited rc=%s; restarting in %.1fs",
                        name, index, replica.process.returncode, backoff,
                    )
                    await asyncio.sleep(backoff)
                    new = await self._spawn(spec, index)
                    new.restarts = replica.restarts + 1
                    replicas[index] = new
