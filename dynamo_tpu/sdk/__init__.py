"""Service-graph SDK: declare components, run them locally under a process
supervisor, deploy to k8s (reference: deploy/sdk — @service/@endpoint/
depends DSL + circus-based ``dynamo serve``)."""

from dynamo_tpu.sdk.supervisor import ProcessSpec, ProcessSupervisor
from dynamo_tpu.sdk.graph import DynamoService, depends, endpoint, service

__all__ = [
    "ProcessSpec",
    "ProcessSupervisor",
    "DynamoService",
    "depends",
    "endpoint",
    "service",
]
