"""Service-graph DSL.

Declarative component graphs (reference: deploy/sdk/.../core/lib.py —
``@service`` :88, ``@endpoint``, ``depends()`` :121, lifecycle hooks
:149-175):

    @service(workers=2, resources={"tpu": 1})
    class Worker:
        @endpoint()
        async def generate(self, request, ctx):
            yield {...}

    @service()
    class Processor:
        worker = depends(Worker)          # client to Worker.generate
        @endpoint()
        async def generate(self, request, ctx):
            async for item in await self.worker.generate(request):
                yield item

Deployment modes:
- ``deploy_inprocess(Entry, runtime)`` — whole graph in one process
  (tests/dev; descriptors resolve to direct engine calls over the memory
  control plane);
- ``ProcessSupervisor`` specs via ``to_process_specs`` — one OS process per
  service replica running ``dynamo_tpu.sdk.runner`` (the serve_dynamo.py
  analog), discovering each other through the control plane.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable

from dynamo_tpu.runtime.client import PushRouter, RemoteEngine, RouterMode
from dynamo_tpu.runtime.engine import Context, FnEngine, ResponseStream
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("sdk.graph")


@dataclass
class ServiceConfig:
    name: str
    workers: int = 1
    resources: dict[str, Any] = field(default_factory=dict)
    namespace: str = "dynamo"
    # deploy-plane kind (deploy/crds.py COMPONENT_KINDS): how the builder
    # renders this service into a DynamoComponentDeployment
    component_type: str = "worker"


@dataclass
class EndpointDef:
    name: str
    method_name: str


class Depends:
    """Declares a dependency on another service; resolves to a client."""

    def __init__(self, target: type):
        self.target = target
        self.attr_name: str | None = None

    def __set_name__(self, owner, name):
        self.attr_name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        resolved = getattr(obj, f"_dyn_dep_{self.attr_name}", None)
        if resolved is None:
            raise RuntimeError(
                f"dependency {self.attr_name} not wired (service not deployed)"
            )
        return resolved


def service(name: str | None = None, *, workers: int = 1, resources: dict | None = None,
            namespace: str = "dynamo",
            component_type: str = "worker") -> Callable[[type], type]:
    def wrap(cls: type) -> type:
        cls._dyn_service = ServiceConfig(
            name=name or cls.__name__.lower(),
            workers=workers,
            resources=resources or {},
            namespace=namespace,
            component_type=component_type,
        )
        cls._dyn_endpoints = [
            EndpointDef(name=m._dyn_endpoint_name, method_name=attr)
            for attr, m in vars(cls).items()
            if callable(m) and hasattr(m, "_dyn_endpoint_name")
        ]
        cls._dyn_deps = {
            attr: dep for attr, dep in vars(cls).items() if isinstance(dep, Depends)
        }
        return cls

    return wrap


def endpoint(name: str | None = None):
    def wrap(fn):
        fn._dyn_endpoint_name = name or fn.__name__
        return fn

    return wrap


def depends(target: type) -> Depends:
    return Depends(target)


def async_on_start(fn):
    fn._dyn_on_start = True
    return fn


def resolve_entry(entry: str) -> type:
    """``pkg.module:ClassName`` → the class object (shared by the runner
    and the deploy-plane builder so the two paths cannot drift)."""
    import importlib

    module_name, _, qualname = entry.partition(":")
    if not qualname:
        raise ValueError(f"entry {entry!r} must look like module:ClassName")
    obj = importlib.import_module(module_name)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def dependency_closure(entry: type) -> list[type]:
    """Entry service + transitive dependencies, dependency-first order."""
    seen: dict[type, None] = {}

    def visit(cls: type):
        for dep in getattr(cls, "_dyn_deps", {}).values():
            visit(dep.target)
        if cls not in seen:
            seen[cls] = None

    visit(entry)
    return list(seen)


class _BoundEndpointEngine:
    """Adapts a service method (async generator) to the AsyncEngine shape."""

    def __init__(self, instance, method):
        self._instance = instance
        self._method = method

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        sig = inspect.signature(self._method)
        if len(sig.parameters) >= 3:
            gen = self._method(self._instance, request.data, request.ctx)
        else:
            gen = self._method(self._instance, request.data)
        return ResponseStream(gen, request.ctx)


async def deploy_service(runtime, cls: type, *, instance=None) -> list:
    """Instantiate one service, wire deps to control-plane clients, serve
    its endpoints.  Returns the EndpointService handles."""
    config: ServiceConfig = cls._dyn_service
    obj = instance if instance is not None else cls()
    # wire dependencies: clients to the dep's first endpoint
    for attr, dep in cls._dyn_deps.items():
        dep_config: ServiceConfig = dep.target._dyn_service
        dep_endpoints = dep.target._dyn_endpoints
        if not dep_endpoints:
            raise ValueError(f"{dep.target.__name__} has no endpoints to depend on")
        ep = (
            runtime.namespace(dep_config.namespace)
            .component(dep_config.name)
            .endpoint(dep_endpoints[0].name)
        )
        router = await PushRouter.from_endpoint(ep, RouterMode.ROUND_ROBIN)
        setattr(obj, f"_dyn_dep_{attr}", RemoteEngine(router))

    # lifecycle hook
    for attr, member in vars(cls).items():
        if callable(member) and getattr(member, "_dyn_on_start", False):
            await member(obj)

    services = []
    for ep_def in cls._dyn_endpoints:
        ep = (
            runtime.namespace(config.namespace)
            .component(config.name)
            .endpoint(ep_def.name)
        )
        method = getattr(cls, ep_def.method_name)
        handle = await ep.serve(_BoundEndpointEngine(obj, method))
        services.append(handle)
    logger.info("deployed service %s (%d endpoints)", config.name, len(services))
    return services


async def deploy_inprocess(entry: type, runtime) -> dict[type, list]:
    """Deploy the whole dependency closure in one process."""
    handles: dict[type, list] = {}
    for cls in dependency_closure(entry):
        handles[cls] = await deploy_service(runtime, cls)
    return handles


def to_process_specs(
    entry: type, *, control_plane: str, python=None, chip_inventory=None,
) -> list:
    """One ProcessSpec per service for the supervisor (subprocess mode).

    Services declaring ``resources={"tpu": n}`` get per-replica
    ``TPU_VISIBLE_CHIPS`` overlays from the resource allocator
    (sdk/allocator.py) so replicas claim disjoint chips; ``chip_inventory``
    overrides host detection (tests, explicit topologies).  Spec replica
    targets come from the @service ``workers`` count."""
    import sys

    from dynamo_tpu.sdk.allocator import plan_resource_envs
    from dynamo_tpu.sdk.supervisor import ProcessSpec

    closure = dependency_closure(entry)
    chip_envs = plan_resource_envs(closure, inventory=chip_inventory)
    specs = []
    for cls in closure:
        config: ServiceConfig = cls._dyn_service
        specs.append(
            ProcessSpec(
                name=config.name,
                cmd=[
                    python or sys.executable, "-m", "dynamo_tpu.sdk.runner",
                    f"{cls.__module__}:{cls.__qualname__}",
                    "--control-plane", control_plane,
                ],
                replica_env=chip_envs.get(config.name, []),
                replicas=config.workers,
            )
        )
    return specs


class DynamoService:
    """Convenience base class (optional; plain classes work too)."""
