"""Metrics service: aggregates worker load metrics into Prometheus.

(Reference: components/metrics/src/lib.rs — scrapes ``load_metrics``,
aggregates ProcessedEndpoints, exposes Prometheus; plus the KV-hit-rate
event subscription, KVHitRateEvent.)

Run: ``python -m dynamo_tpu.components.metrics_service --control-plane H:P``
"""

from __future__ import annotations

import argparse
import asyncio

from aiohttp import web
from prometheus_client import CollectorRegistry, Counter, Gauge, generate_latest

from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.llm.kv_router.protocols import KV_HIT_RATE_SUBJECT, KvHitRateEvent
from dynamo_tpu.planner.state import PLANNER_STATE_EVENT, PlannerStateEvent
from dynamo_tpu.robustness import counters as robustness_counters
from dynamo_tpu.runtime.component import Component
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import configure_logging, get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("components.metrics")

PREFIX = "dyn_worker"


class MetricsService:
    def __init__(self, component: Component, *, host: str = "0.0.0.0", port: int = 9091):
        self.component = component
        self.host = host
        self.port = port
        self.aggregator = KvMetricsAggregator(component)
        self.registry = CollectorRegistry()
        self.kv_active = Gauge(
            f"{PREFIX}_kv_active_blocks", "Active KV blocks", ["worker"], registry=self.registry
        )
        self.kv_total = Gauge(
            f"{PREFIX}_kv_total_blocks", "Total KV blocks", ["worker"], registry=self.registry
        )
        self.cache_usage = Gauge(
            f"{PREFIX}_cache_usage_perc", "KV cache usage", ["worker"], registry=self.registry
        )
        self.waiting = Gauge(
            f"{PREFIX}_requests_waiting", "Queued requests", ["worker"], registry=self.registry
        )
        # engine step telemetry (emitted every scheduler iteration by the
        # engine's device loop; observability.step_metrics)
        self.running = Gauge(
            f"{PREFIX}_requests_running", "Running (decoding) requests",
            ["worker"], registry=self.registry,
        )
        self.batch_occupancy = Gauge(
            f"{PREFIX}_batch_occupancy_perc",
            "Decode-lane occupancy of the latest engine step (running/slots)",
            ["worker"], registry=self.registry,
        )
        self.preemptions = Gauge(
            f"{PREFIX}_preemptions",
            "Sequences preempted for KV pressure (cumulative)",
            ["worker"], registry=self.registry,
        )
        # ragged unified-batch step (engine unified_batch knob): one-dispatch
        # mixed windows served, and the admission-forced pipeline drains the
        # unified step removes (flat while unified serves the traffic)
        self.unified_windows = Gauge(
            f"{PREFIX}_unified_windows",
            "Mixed prefill+decode windows served by the ragged unified-batch "
            "dispatch (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.admission_drains = Gauge(
            f"{PREFIX}_admission_drains",
            "Decode-pipeline drains forced by new-sequence admission "
            "(cumulative)",
            ["worker"], registry=self.registry,
        )
        self.unified_fallbacks = Gauge(
            f"{PREFIX}_unified_fallbacks_total",
            "Unified-batch windows (or engine inits) downgraded to the "
            "split step, by reason slug (cumulative mirrored counter)",
            ["worker", "reason"], registry=self.registry,
        )
        # mirrored remote counters need .set(), so they are gauges —
        # named WITHOUT the counter-reserved _total suffix
        self.prefix_hits = Gauge(
            f"{PREFIX}_prefix_hits", "Engine prefix-cache hits (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.prefix_cached_tokens = Gauge(
            f"{PREFIX}_prefix_cached_tokens",
            "Prompt tokens served from the prefix cache (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.spec_accepted = Gauge(
            f"{PREFIX}_spec_accepted_tokens",
            "Draft tokens accepted by speculative verification (cumulative)",
            ["worker"], registry=self.registry,
        )
        # utilization accounting (observability/perf.py): rolling rates and
        # cumulative token/wasted-work totals per worker.  Mirrored remote
        # values, so gauges throughout (same rationale as the counters
        # below); rates carry their unit in the name.
        self.mfu = Gauge(
            f"{PREFIX}_mfu_perc",
            "Model FLOPs utilization over the rolling window (0-1)",
            ["worker"], registry=self.registry,
        )
        self.bandwidth_util = Gauge(
            f"{PREFIX}_bandwidth_util_perc",
            "Model HBM bandwidth utilization over the rolling window (0-1)",
            ["worker"], registry=self.registry,
        )
        self.goodput = Gauge(
            f"{PREFIX}_goodput_tokens_per_second",
            "Tokens per second actually delivered to callers (rolling window)",
            ["worker"], registry=self.registry,
        )
        self.prefill_rate = Gauge(
            f"{PREFIX}_prefill_tokens_per_second",
            "Prompt tokens per second computed (rolling window)",
            ["worker"], registry=self.registry,
        )
        self.prefill_tokens = Gauge(
            f"{PREFIX}_prefill_tokens",
            "Prompt tokens computed (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.decode_tokens = Gauge(
            f"{PREFIX}_decode_tokens",
            "Decode positions computed (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.tokens_emitted = Gauge(
            f"{PREFIX}_tokens_emitted",
            "Tokens emitted to caller streams (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.preempted_tokens = Gauge(
            f"{PREFIX}_preempted_tokens",
            "Context tokens recomputed due to KV-pressure preemption (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.spec_rejected = Gauge(
            f"{PREFIX}_spec_rejected_tokens",
            "Draft tokens rejected by speculative verification (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.wasted_tokens = Gauge(
            f"{PREFIX}_wasted_tokens",
            "Tokens computed that bought nothing a client received (cumulative)",
            ["worker"], registry=self.registry,
        )
        # engine phase timing (DYN_ENGINE_PHASE_TIMING=1): cumulative wall
        # seconds per decode/prefill phase — makes the overlap/sync pipeline
        # difference (decode.retire vs decode.readback) visible in /metrics
        self.phase_seconds = Gauge(
            f"{PREFIX}_engine_phase_seconds",
            "Cumulative engine wall seconds per hot-loop phase "
            "(DYN_ENGINE_PHASE_TIMING=1)",
            ["worker", "phase"], registry=self.registry,
        )
        # predictive prefetch (prefetch/pager.py via engine stats):
        # canonical dyn_prefetch_* family names from the subsystem contract
        # — mirrored remote counters, so gauges (same rationale as the
        # resilience counters below)
        self.prefetch_hits = Gauge(
            "dyn_prefetch_hits_total",
            "Prefetched KV blocks consumed by a sequence before eviction "
            "(cumulative)",
            ["worker"], registry=self.registry,
        )
        self.prefetch_misses = Gauge(
            "dyn_prefetch_misses_total",
            "Prefetched KV blocks evicted before any sequence matched them "
            "(cumulative)",
            ["worker"], registry=self.registry,
        )
        self.prefetch_stale = Gauge(
            "dyn_prefetch_stale_total",
            "Prefetch hints cancelled because they expired before paging ran "
            "(cumulative)",
            ["worker"], registry=self.registry,
        )
        self.prefetch_hidden = Gauge(
            "dyn_prefetch_hidden_seconds",
            "Page-in wall seconds moved off request critical paths by "
            "prefetch (cumulative)",
            ["worker"], registry=self.registry,
        )
        # disagg streamed KV transfer (llm/disagg.DisaggDecodeEngine stats):
        # canonical dyn_disagg_* family names — mirrored remote counters, so
        # gauges (same rationale as the prefetch family above).  The hidden
        # ratio is the headline: what fraction of transfer wall time the
        # streamed protocol moved off the TTFT critical path.
        self.disagg_remote_prefills = Gauge(
            "dyn_disagg_remote_prefills_total",
            "Prefills served by a remote prefill worker (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.disagg_local_prefills = Gauge(
            "dyn_disagg_local_prefills_total",
            "Prefills served locally after the disagg router declined remote "
            "(cumulative)",
            ["worker"], registry=self.registry,
        )
        self.disagg_prefill_timeouts = Gauge(
            "dyn_disagg_prefill_timeouts_total",
            "Remote prefills abandoned for local fallback after timeout "
            "(cumulative)",
            ["worker"], registry=self.registry,
        )
        self.disagg_transfer_bytes = Gauge(
            "dyn_disagg_kv_transfer_bytes_total",
            "KV bytes received from prefill workers (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.disagg_transfer_seconds = Gauge(
            "dyn_disagg_kv_transfer_seconds_total",
            "Wall seconds spent receiving+injecting KV transfer parts "
            "(cumulative)",
            ["worker"], registry=self.registry,
        )
        self.disagg_transfer_hidden = Gauge(
            "dyn_disagg_kv_transfer_hidden_seconds_total",
            "KV transfer seconds overlapped with remote prefill compute "
            "instead of exposed to TTFT (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.disagg_transfer_parts = Gauge(
            "dyn_disagg_kv_transfer_parts_total",
            "Streamed KV transfer parts received (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.disagg_hidden_ratio = Gauge(
            "dyn_disagg_transfer_hidden_ratio",
            "Fraction of KV transfer wall time hidden behind prefill "
            "compute (cumulative ratio, 0-1)",
            ["worker"], registry=self.registry,
        )
        self.disagg_bandwidth = Gauge(
            "dyn_disagg_kv_transfer_bandwidth_bps",
            "Measured inbound KV transfer bandwidth, bytes/second "
            "(cumulative mean; 0 until measured)",
            ["worker"], registry=self.registry,
        )
        # offload-tier occupancy (engine offload_tiers snapshot): capacity
        # and usage per mounted tier (g2 host / g3 disk / g4 remote)
        self.offload_blocks = Gauge(
            "dyn_worker_offload_blocks",
            "Offload-tier capacity in KV blocks",
            ["worker", "tier"], registry=self.registry,
        )
        self.offload_blocks_used = Gauge(
            "dyn_worker_offload_blocks_used",
            "Offload-tier blocks holding content",
            ["worker", "tier"], registry=self.registry,
        )
        self.offload_blocks_pinned = Gauge(
            "dyn_worker_offload_blocks_pinned",
            "Hot shared prefixes pinned tier-resident",
            ["worker", "tier"], registry=self.registry,
        )
        # perf flight recorder (observability/flight.py via engine stats):
        # ring bookkeeping per worker — mirrored remote counters, so gauges
        # with the canonical *_total names (same rationale as above).  The
        # last-dump reason rides as a label on a value-1 info series
        # (dyn_topology_worker_info precedent) — the dyn_top FLIGHT column
        # reads it.
        self.flight_records = Gauge(
            "dyn_flight_records_total",
            "Flight-recorder records captured (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.flight_dropped = Gauge(
            "dyn_flight_dropped_total",
            "Flight-recorder records evicted over the byte budget (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.flight_dumps = Gauge(
            "dyn_flight_dumps_total",
            "Flight-recorder JSONL dumps written (cumulative)",
            ["worker"], registry=self.registry,
        )
        self.flight_buffer = Gauge(
            "dyn_flight_buffer_bytes",
            "Flight-recorder ring occupancy in bytes",
            ["worker"], registry=self.registry,
        )
        self.flight_last_dump = Gauge(
            "dyn_flight_last_dump_info",
            "Per-worker last flight-dump trigger (value always 1; the "
            "reason rides as a label; absent until something dumped)",
            ["worker", "reason"], registry=self.registry,
        )
        self._seen_flight_dumps: set[tuple[str, str]] = set()
        self._worker_gauges = (
            self.kv_active, self.kv_total, self.cache_usage, self.waiting,
            self.running, self.batch_occupancy, self.preemptions,
            self.unified_windows, self.admission_drains,
            self.prefix_hits, self.prefix_cached_tokens, self.spec_accepted,
            self.mfu, self.bandwidth_util, self.goodput, self.prefill_rate,
            self.prefill_tokens, self.decode_tokens, self.tokens_emitted,
            self.preempted_tokens, self.spec_rejected, self.wasted_tokens,
            self.prefetch_hits, self.prefetch_misses, self.prefetch_stale,
            self.prefetch_hidden,
            self.disagg_remote_prefills, self.disagg_local_prefills,
            self.disagg_prefill_timeouts, self.disagg_transfer_bytes,
            self.disagg_transfer_seconds, self.disagg_transfer_hidden,
            self.disagg_transfer_parts, self.disagg_hidden_ratio,
            self.disagg_bandwidth,
            self.flight_records, self.flight_dropped, self.flight_dumps,
            self.flight_buffer,
        )
        self._seen_workers: set[str] = set()
        self._seen_phases: set[tuple[str, str]] = set()
        self._seen_fallback_reasons: set[tuple[str, str]] = set()
        self._seen_tiers: set[tuple[str, str]] = set()
        self.hit_blocks = Counter(
            f"{PREFIX}_kv_hit_blocks_total", "Matched prefix blocks routed", registry=self.registry
        )
        self.isl_blocks = Counter(
            f"{PREFIX}_kv_isl_blocks_total", "Total request prefix blocks", registry=self.registry
        )
        # resilience counters (robustness.counters): mirrored on refresh so
        # one scrape shows recovery activity next to worker load.  Gauges
        # because a mirror needs .set() (same rationale as above), but they
        # keep the canonical *_total names the frontend exposition uses.
        self.resilience = {
            name: Gauge(name, help_text, registry=self.registry)
            for name, help_text in robustness_counters.HELP.items()
        }
        # planner autopilot state (planner/state.py events on the component
        # bus): latest decision targets, per-pool observed capacity, and the
        # worst burn rate the planner consumed — WHY the fleet is its size
        self.planner_target = Gauge(
            "dyn_planner_target_replicas",
            "Replica target from the planner's latest executed decision",
            ["pool"], registry=self.registry,
        )
        self.planner_capacity = Gauge(
            "dyn_planner_observed_capacity_tok_s",
            "Planner's observed per-replica capacity estimate (EWMA at "
            "saturation; 0 until measured)",
            ["pool"], registry=self.registry,
        )
        self.planner_burn = Gauge(
            "dyn_planner_burn_rate_input",
            "Worst per-objective SLO burn rate the planner consumed for its "
            "latest decision",
            registry=self.registry,
        )
        # fleet topology plane (topology/): map shape + link measurements,
        # mirrored from the service's own TopologyWatcher (or an attached
        # map).  Families always exist — zeros until cards are published.
        self.topology_nodes = Gauge(
            "dyn_topology_nodes",
            "Workers with a published topology card",
            registry=self.registry,
        )
        self.topology_links = Gauge(
            "dyn_topology_links",
            "Pairwise links in the fleet topology map by hop class",
            ["hop"], registry=self.registry,
        )
        self.topology_probe_rtt = Gauge(
            "dyn_topology_probe_rtt_seconds",
            "Probe round-trip EWMA by hop class",
            ["hop"], registry=self.registry,
        )
        self.topology_probe_bandwidth = Gauge(
            "dyn_topology_probe_bandwidth_bps",
            "Measured link bandwidth EWMA by hop class",
            ["hop"], registry=self.registry,
        )
        self.topology_map_age = Gauge(
            "dyn_topology_map_age_seconds",
            "Seconds since the topology map last changed",
            registry=self.registry,
        )
        self.topology_worker_info = Gauge(
            "dyn_topology_worker_info",
            "Per-worker placement facts (value always 1; slice and inbound "
            "hop class ride as labels)",
            ["worker", "slice", "hop"], registry=self.registry,
        )
        self._seen_topology_workers: set[tuple[str, str, str]] = set()
        self._topology = None          # TopologyMap (attached or watched)
        self._topology_watcher = None  # owned TopologyWatcher, when started
        from dynamo_tpu.topology.metrics import HOP_CLASSES

        for hop in HOP_CLASSES:
            self.topology_links.labels(hop).set(0)
            self.topology_probe_rtt.labels(hop).set(0)
            self.topology_probe_bandwidth.labels(hop).set(0)
        self._planner_event: PlannerStateEvent | None = None
        self._planner_sub = None
        self._planner_task: asyncio.Task | None = None
        self._hit_sub = None
        self._hit_task: asyncio.Task | None = None
        self._runner: web.AppRunner | None = None

    def attach_topology(self, topo_map) -> None:
        """Mirror an externally-owned TopologyMap (fleet/test harnesses)
        instead of watching the control plane for cards ourselves."""
        self._topology = topo_map

    async def start(self) -> None:
        await self.aggregator.start()
        from dynamo_tpu.utils import knobs

        if self._topology is None and knobs.get("DYN_TOPO"):
            from dynamo_tpu.topology import TopologyWatcher

            self._topology_watcher = TopologyWatcher(self.component.runtime)
            await self._topology_watcher.start()
            self._topology = self._topology_watcher.map
        bus = self.component.runtime.plane.bus
        self._hit_sub = await bus.subscribe(self.component.event_subject(KV_HIT_RATE_SUBJECT))
        self._hit_task = spawn_logged(self._hit_loop())
        self._planner_sub = await bus.subscribe(
            self.component.event_subject(PLANNER_STATE_EVENT)
        )
        self._planner_task = spawn_logged(self._planner_loop())

        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:
            self.port = s.getsockname()[1]
            break
        logger.info("metrics service on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        await self.aggregator.stop()
        if self._topology_watcher is not None:
            await self._topology_watcher.stop()
            self._topology_watcher = None
        if self._hit_sub is not None:
            await self._hit_sub.unsubscribe()
        if self._hit_task is not None:
            self._hit_task.cancel()
        if self._planner_sub is not None:
            await self._planner_sub.unsubscribe()
        if self._planner_task is not None:
            self._planner_task.cancel()
        if self._runner is not None:
            await self._runner.cleanup()

    async def _hit_loop(self) -> None:
        async for msg in self._hit_sub:
            try:
                event = KvHitRateEvent.from_json(msg.payload)
            except Exception:  # noqa: BLE001
                continue
            self.hit_blocks.inc(event.overlap_blocks)
            self.isl_blocks.inc(max(event.isl_blocks, 0))

    async def _planner_loop(self) -> None:
        async for msg in self._planner_sub:
            try:
                self._planner_event = PlannerStateEvent.from_json(msg.payload)
            except Exception:  # noqa: BLE001
                continue

    def _refresh_topology(self) -> None:
        from dynamo_tpu.topology.metrics import HOP_CLASSES, hop_summaries

        topo = self._topology
        summaries = hop_summaries(topo)
        self.topology_nodes.set(len(topo.nodes) if topo is not None else 0)
        self.topology_map_age.set(topo.age_s() if topo is not None else 0.0)
        for hop in HOP_CLASSES:
            self.topology_links.labels(hop).set(summaries[hop]["links"])
            self.topology_probe_rtt.labels(hop).set(summaries[hop]["rtt_s"])
            self.topology_probe_bandwidth.labels(hop).set(summaries[hop]["bps"])
        # per-worker placement info series (value 1, facts in the labels) —
        # the dyn_top SLICE/HOP column reads these
        current: set[tuple[str, str, str]] = set()
        if topo is not None:
            for wid, card in topo.nodes.items():
                key = (
                    f"{wid:x}",
                    card.slice_label or "-",
                    topo.inbound_hop(wid) or "-",
                )
                self.topology_worker_info.labels(*key).set(1)
                current.add(key)
        for key in self._seen_topology_workers - current:
            try:
                self.topology_worker_info.remove(*key)
            except KeyError:
                pass
        self._seen_topology_workers = current

    def _refresh(self) -> None:
        self._refresh_topology()
        ev = self._planner_event
        if ev is not None:
            self.planner_target.labels("prefill").set(ev.target_prefill)
            self.planner_target.labels("decode").set(ev.target_decode)
            self.planner_capacity.labels("prefill").set(ev.observed_prefill_tok_s)
            self.planner_capacity.labels("decode").set(ev.observed_decode_tok_s)
            self.planner_burn.set(ev.burn_rate_input)
        for name, value in robustness_counters.snapshot().items():
            gauge = self.resilience.get(name)
            if gauge is not None:
                gauge.set(value)
        snapshot = self.aggregator.snapshot()
        live = {f"{wid:x}" for wid in snapshot.workers}
        # drop gauges for workers that fell out of the snapshot (lease
        # lost / TTL expired) — stale values must not look alive forever
        for label in self._seen_workers - live:
            for g in self._worker_gauges:
                try:
                    g.remove(label)
                except KeyError:
                    pass
        for label, phase in list(self._seen_phases):
            if label not in live:
                try:
                    self.phase_seconds.remove(label, phase)
                except KeyError:
                    pass
                self._seen_phases.discard((label, phase))
        for label, reason in list(self._seen_fallback_reasons):
            if label not in live:
                try:
                    self.unified_fallbacks.remove(label, reason)
                except KeyError:
                    pass
                self._seen_fallback_reasons.discard((label, reason))
        for label, reason in list(self._seen_flight_dumps):
            if label not in live:
                try:
                    self.flight_last_dump.remove(label, reason)
                except KeyError:
                    pass
                self._seen_flight_dumps.discard((label, reason))
        for label, tier in list(self._seen_tiers):
            if label not in live:
                for g in (
                    self.offload_blocks, self.offload_blocks_used,
                    self.offload_blocks_pinned,
                ):
                    try:
                        g.remove(label, tier)
                    except KeyError:
                        pass
                self._seen_tiers.discard((label, tier))
        self._seen_workers = live
        for wid, m in snapshot.workers.items():
            label = f"{wid:x}"
            self.kv_active.labels(label).set(m.kv_active_blocks)
            self.kv_total.labels(label).set(m.kv_total_blocks)
            self.cache_usage.labels(label).set(m.gpu_cache_usage_perc)
            self.waiting.labels(label).set(m.num_requests_waiting)
            self.running.labels(label).set(m.num_requests_running)
            self.batch_occupancy.labels(label).set(m.batch_occupancy_perc)
            self.preemptions.labels(label).set(m.num_preemptions_total)
            self.unified_windows.labels(label).set(m.decode_windows_unified_total)
            self.admission_drains.labels(label).set(m.admission_drains_total)
            reasons_now = set(m.unified_fallbacks or {})
            for reason, count in (m.unified_fallbacks or {}).items():
                self.unified_fallbacks.labels(label, reason).set(count)
                self._seen_fallback_reasons.add((label, reason))
            # a worker restart can clear a fallback reason (e.g. the knob
            # flipped): drop its stale series like the phase gauges do
            for seen_label, reason in list(self._seen_fallback_reasons):
                if seen_label == label and reason not in reasons_now:
                    try:
                        self.unified_fallbacks.remove(label, reason)
                    except KeyError:
                        pass
                    self._seen_fallback_reasons.discard((label, reason))
            self.prefix_hits.labels(label).set(m.prefix_hits_total)
            self.prefix_cached_tokens.labels(label).set(m.prefix_cached_tokens_total)
            self.spec_accepted.labels(label).set(m.spec_accepted_tokens_total)
            self.mfu.labels(label).set(m.mfu_perc)
            self.bandwidth_util.labels(label).set(m.bandwidth_util_perc)
            self.goodput.labels(label).set(m.goodput_tokens_per_second)
            self.prefill_rate.labels(label).set(m.prefill_tokens_per_second)
            self.prefill_tokens.labels(label).set(m.prefill_tokens_total)
            self.decode_tokens.labels(label).set(m.decode_tokens_total)
            self.tokens_emitted.labels(label).set(m.tokens_emitted_total)
            self.preempted_tokens.labels(label).set(m.preempted_tokens_total)
            self.spec_rejected.labels(label).set(m.spec_rejected_tokens_total)
            self.wasted_tokens.labels(label).set(m.wasted_tokens_total)
            self.prefetch_hits.labels(label).set(m.prefetch_hits_total)
            self.prefetch_misses.labels(label).set(m.prefetch_misses_total)
            self.prefetch_stale.labels(label).set(m.prefetch_stale_total)
            self.prefetch_hidden.labels(label).set(m.prefetch_hidden_seconds_total)
            self.disagg_remote_prefills.labels(label).set(
                m.disagg_remote_prefills_total
            )
            self.disagg_local_prefills.labels(label).set(
                m.disagg_local_prefills_total
            )
            self.disagg_prefill_timeouts.labels(label).set(
                m.disagg_prefill_timeouts_total
            )
            self.disagg_transfer_bytes.labels(label).set(
                m.disagg_kv_transfer_bytes_total
            )
            self.disagg_transfer_seconds.labels(label).set(
                m.disagg_kv_transfer_seconds_total
            )
            self.disagg_transfer_hidden.labels(label).set(
                m.disagg_kv_transfer_hidden_seconds_total
            )
            self.disagg_transfer_parts.labels(label).set(
                m.disagg_kv_transfer_parts_total
            )
            self.disagg_hidden_ratio.labels(label).set(
                m.disagg_transfer_hidden_ratio
            )
            self.disagg_bandwidth.labels(label).set(m.kv_transfer_bandwidth_bps)
            self.flight_records.labels(label).set(m.flight_records_total)
            self.flight_dropped.labels(label).set(m.flight_dropped_total)
            self.flight_dumps.labels(label).set(m.flight_dumps_total)
            self.flight_buffer.labels(label).set(m.flight_buffer_bytes)
            reason_now = m.flight_last_dump_reason or ""
            if reason_now:
                self.flight_last_dump.labels(label, reason_now).set(1)
                self._seen_flight_dumps.add((label, reason_now))
            # only the LATEST dump reason may stand per worker — a newer
            # trigger replaces the old series instead of accumulating
            for seen_label, reason in list(self._seen_flight_dumps):
                if seen_label == label and reason != reason_now:
                    try:
                        self.flight_last_dump.remove(label, reason)
                    except KeyError:
                        pass
                    self._seen_flight_dumps.discard((label, reason))
            for tier, row in (m.offload_tiers or {}).items():
                self.offload_blocks.labels(label, tier).set(row.get("blocks", 0))
                self.offload_blocks_used.labels(label, tier).set(row.get("used", 0))
                self.offload_blocks_pinned.labels(label, tier).set(
                    row.get("pinned", 0)
                )
                self._seen_tiers.add((label, tier))
            phases_now = set(m.phase_seconds or {})
            for phase, seconds in (m.phase_seconds or {}).items():
                self.phase_seconds.labels(label, phase).set(seconds)
                self._seen_phases.add((label, phase))
            # a worker that restarted with a different mode (e.g. overlap
            # toggled) stops reporting some phases: drop their stale series
            # instead of freezing pre-restart cumulative values forever
            for seen_label, phase in list(self._seen_phases):
                if seen_label == label and phase not in phases_now:
                    try:
                        self.phase_seconds.remove(label, phase)
                    except KeyError:
                        pass
                    self._seen_phases.discard((label, phase))

    async def _metrics(self, request: web.Request) -> web.Response:
        self._refresh()
        return web.Response(body=generate_latest(self.registry), content_type="text/plain")


async def amain(args) -> int:
    configure_logging()
    runtime = await DistributedRuntime.create(
        RuntimeConfig(control_plane=args.control_plane)
    )
    component = runtime.namespace(args.namespace).component(args.component)
    service = MetricsService(component, host=args.host, port=args.port)
    await service.start()
    await runtime.wait_for_shutdown()
    await service.stop()
    await runtime.close()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--control-plane", default="127.0.0.1:2379")
    parser.add_argument("--namespace", default="dynamo")
    parser.add_argument("--component", default="backend")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9091)
    return asyncio.run(amain(parser.parse_args()))


if __name__ == "__main__":
    raise SystemExit(main())
