"""Standalone deployable service components (reference: components/ —
router, metrics, planner binaries)."""
