"""Standalone KV-aware router component.

Hosts a KvRouter behind a control-plane endpoint: ``generate`` takes
``{"token_ids": [...]}`` and streams back the selected ``worker_id`` +
matched prefix blocks (reference: components/router/src/main.rs — the
router-as-a-service deployment shape, used when routing decisions are made
outside the frontend process).

Run: ``python -m dynamo_tpu.components.router_service --control-plane H:P``
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.observability import get_recorder
from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger("components.router")


class RouterEngine:
    """AsyncEngine answering scheduling queries.  Worker membership comes
    from the watch-backed Client view (no control-plane scan per request)."""

    def __init__(self, kv_router: KvRouter, client: Client):
        self.kv_router = kv_router
        self.client = client

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        token_ids = request.data.get("token_ids", [])
        span = get_recorder().start(
            "router.schedule", getattr(request.ctx, "trace", None),
            component="router_service",
        )
        try:
            worker_id, matched = await self.kv_router.schedule(
                token_ids, self.client.instance_ids
            )
        except BaseException as exc:
            if span is not None:
                span.end(status="error", error=repr(exc))
            raise
        if span is not None:
            span.end(worker=f"{worker_id:x}", overlap_blocks=matched)

        async def gen():
            yield {"worker_id": worker_id, "overlap_blocks": matched}

        return ResponseStream(gen(), request.ctx)


async def serve_router(
    runtime: DistributedRuntime,
    *,
    namespace: str = "dynamo",
    component: str = "backend",
    endpoint: str = "generate",
    block_size: int = 16,
):
    """Start the router service; returns (EndpointService, KvRouter, Client).

    ``Client.start`` awaits the instance watch's initial snapshot, so by the
    time the endpoint is served the worker view is populated."""
    backend_component = runtime.namespace(namespace).component(component)
    kv_router = KvRouter(backend_component, block_size=block_size)
    await kv_router.start()
    client = Client(runtime, backend_component.endpoint(endpoint))
    await client.start()
    engine = RouterEngine(kv_router, client)
    router_ep = runtime.namespace(namespace).component("router").endpoint("generate")
    service = await router_ep.serve(engine)
    return service, kv_router, client


async def amain(args) -> int:
    configure_logging()
    runtime = await DistributedRuntime.create(RuntimeConfig(control_plane=args.control_plane))
    service, kv_router, client = await serve_router(
        runtime, namespace=args.namespace, component=args.component,
        block_size=args.kv_block_size,
    )
    logger.info("router service up")
    await runtime.wait_for_shutdown()
    await service.shutdown()
    await client.close()
    await kv_router.stop()
    await runtime.close()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--control-plane", default="127.0.0.1:2379")
    parser.add_argument("--namespace", default="dynamo")
    parser.add_argument("--component", default="backend")
    parser.add_argument("--kv-block-size", type=int, default=16)
    return asyncio.run(amain(parser.parse_args()))


if __name__ == "__main__":
    raise SystemExit(main())
