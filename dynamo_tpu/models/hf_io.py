"""Shared HF-checkpoint IO for the model families.

Reads sharded ``*.safetensors`` into one name→array dict (the reference
delegates weight IO to its engines; here every family maps HF names onto
its layer-stacked pytree — llama.py, mixtral.py, deepseek.py
``load_hf_weights``)."""

from __future__ import annotations

from pathlib import Path

import numpy as np


def read_safetensors(model_dir: str | Path) -> dict[str, np.ndarray]:
    from safetensors import safe_open

    model_dir = Path(model_dir)
    files = sorted(model_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no safetensors in {model_dir}")
    tensors: dict[str, np.ndarray] = {}
    for file in files:
        with safe_open(str(file), framework="np") as f:
            for name in f.keys():
                tensors[name] = f.get_tensor(name)
    return tensors
