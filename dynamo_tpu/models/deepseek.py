"""DeepSeek-class model: Multi-head Latent Attention (MLA) + fine-grained
MoE (DeepSeek-V2/V3/R1 geometries).

The reference's flagship wide-EP deployment is DeepSeek-R1 served through
SGLang+DeepEP across 48+ GPUs (reference: examples/sglang/README.md:105,
container/Dockerfile.sglang-deepep); here the model is native to the TPU
engine and its parallelism is sharding annotations over mesh axes ``tp``
(attention heads, shared-expert FFN) and ``ep`` (routed experts) — GSPMD
emits the collectives.

MLA, TPU-first:
- The KV cache stores only the **compressed latent** per token: ``c_kv``
  (kv_lora_rank wide) plus the shared rope key (qk_rope_head_dim wide) —
  e.g. 512+64 floats/token vs 2*8*128 for Llama-70B-class GQA, a ~4.5x
  HBM saving that directly raises achievable batch (decode on TPU is HBM
  bandwidth-bound).
- Decode attends **in latent space** ("absorbed" form): q_nope is folded
  through the k up-projection once per step (one small einsum), scores are
  taken against the latent cache directly, and the context is decompressed
  through the v up-projection after the softmax — no per-token K/V
  decompression, so the cache read stays at latent width.
- Prefill decompresses K/V for the current chunk only (dense causal
  attention on the MXU) while writing latents to the paged cache.

Cache layout reuses the engine's {"k", "v"} pytree so paged bookkeeping,
extract/inject and disagg KV shipping work unchanged:
    k: [layers, num_blocks, block_size, 1, kv_lora_rank]   (latent)
    v: [layers, num_blocks, block_size, 1, qk_rope_head_dim] (rope key)

Routing: V2-style renormalized softmax top-k, or V3/R1 aux-free sigmoid
routing (e_score_correction_bias steers selection only, group-limited
top-k) behind ``scoring_func="sigmoid"``.  Long context: YaRN rope scaling
via the HF ``rope_scaling`` dict, including the mscale attention-temperature
correction (``attn_scale``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from dynamo_tpu.ops.attention import NEG_INF, write_decode_kv, write_prefill_kv
from dynamo_tpu.ops.moe import moe_ffn
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.attention import position_major_to_batch
from dynamo_tpu.ops.quant import mm
from dynamo_tpu.ops.rope import apply_rope, rope_table


@dataclass(frozen=True)
class DeepseekConfig:
    vocab_size: int = 102400
    hidden_size: int = 2048
    num_layers: int = 27
    num_heads: int = 16
    # MLA geometry
    q_lora_rank: int = 0              # 0 = direct q projection (V2-Lite)
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    # FFN geometry
    intermediate_size: int = 10944    # dense layers
    first_k_dense: int = 1            # leading dense (non-MoE) layers
    moe_intermediate_size: int = 1408  # per routed expert
    num_experts: int = 64
    experts_per_token: int = 6
    n_shared_experts: int = 2
    routed_scaling_factor: float = 1.0
    capacity_factor: float = 2.0
    # V3/R1 aux-free routing: sigmoid scores + e_score_correction_bias +
    # group-limited top-k; V2 uses plain renormalized softmax
    scoring_func: str = "softmax"     # "softmax" | "sigmoid"
    n_group: int = 1
    topk_group: int = 1
    norm_topk_prob: bool = True
    # common
    max_position_embeddings: int = 163840
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    # HF rope_scaling dict; "yarn" also corrects the attention temperature
    # (mscale) — see attn_scale
    rope_scaling: Any = None
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def attn_scale(self) -> float:
        from dynamo_tpu.ops.rope import yarn_mscale

        m = yarn_mscale(self.rope_scaling)
        return (self.qk_head_dim ** -0.5) * m * m

    @property
    def num_moe_layers(self) -> int:
        return self.num_layers - self.first_k_dense

    @classmethod
    def from_hf_config(cls, config: dict | str | Path) -> "DeepseekConfig":
        if not isinstance(config, dict):
            config = json.loads(Path(config).read_text())
        return cls(
            vocab_size=config["vocab_size"],
            hidden_size=config["hidden_size"],
            num_layers=config["num_hidden_layers"],
            num_heads=config["num_attention_heads"],
            q_lora_rank=config.get("q_lora_rank") or 0,
            kv_lora_rank=config["kv_lora_rank"],
            qk_nope_head_dim=config["qk_nope_head_dim"],
            qk_rope_head_dim=config["qk_rope_head_dim"],
            v_head_dim=config["v_head_dim"],
            intermediate_size=config["intermediate_size"],
            first_k_dense=config.get("first_k_dense_replace", 0),
            moe_intermediate_size=config.get("moe_intermediate_size", 0)
            or config["intermediate_size"],
            num_experts=config.get("n_routed_experts", 0) or 1,
            experts_per_token=config.get("num_experts_per_tok", 1) or 1,
            n_shared_experts=config.get("n_shared_experts", 0) or 0,
            routed_scaling_factor=config.get("routed_scaling_factor", 1.0),
            scoring_func=config.get("scoring_func", "softmax"),
            n_group=config.get("n_group", 1) or 1,
            topk_group=config.get("topk_group", 1) or 1,
            norm_topk_prob=config.get("norm_topk_prob", True),
            max_position_embeddings=config.get("max_position_embeddings", 4096),
            rms_norm_eps=config.get("rms_norm_eps", 1e-6),
            rope_theta=config.get("rope_theta", 10000.0),
            rope_scaling=config.get("rope_scaling"),
            tie_word_embeddings=config.get("tie_word_embeddings", False),
        )

    # --- presets ----------------------------------------------------------
    @classmethod
    def deepseek_v2_lite(cls) -> "DeepseekConfig":
        return cls()  # the defaults above are the 16B V2-Lite geometry

    @classmethod
    def deepseek_v3(cls) -> "DeepseekConfig":
        """671B/R1 geometry (config shape only; serving it needs multi-host)."""
        return cls(
            vocab_size=129280, hidden_size=7168, num_layers=61, num_heads=128,
            q_lora_rank=1536, kv_lora_rank=512, qk_nope_head_dim=128,
            qk_rope_head_dim=64, v_head_dim=128, intermediate_size=18432,
            first_k_dense=3, moe_intermediate_size=2048, num_experts=256,
            experts_per_token=8, n_shared_experts=1, routed_scaling_factor=2.5,
            scoring_func="sigmoid", n_group=8, topk_group=4,
        )

    @classmethod
    def tiny_mla(cls, vocab_size: int = 512) -> "DeepseekConfig":
        """Test geometry: runs on the CPU mesh; exercises q-lora, dense+MoE
        layer mix, and ep/tp-shardable expert counts."""
        return cls(
            vocab_size=vocab_size, hidden_size=64, num_layers=3, num_heads=4,
            q_lora_rank=32, kv_lora_rank=32, qk_nope_head_dim=16,
            qk_rope_head_dim=8, v_head_dim=16, intermediate_size=128,
            first_k_dense=1, moe_intermediate_size=48, num_experts=4,
            experts_per_token=2, n_shared_experts=1, capacity_factor=4.0,
            max_position_embeddings=2048, tie_word_embeddings=True,
            dtype=jnp.float32,
        )


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def _attn_params(cfg: DeepseekConfig, keys, n: int) -> dict:
    h = cfg.hidden_size
    hd_q = cfg.num_heads * cfg.qk_head_dim

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(cfg.dtype)

    params = {
        "attn_norm": jnp.ones((n, h), cfg.dtype),
        "w_dkv": norm_init(keys[0], (n, h, cfg.kv_lora_rank + cfg.qk_rope_head_dim), h),
        "kv_norm": jnp.ones((n, cfg.kv_lora_rank), cfg.dtype),
        "w_uk": norm_init(
            keys[1], (n, cfg.kv_lora_rank, cfg.num_heads * cfg.qk_nope_head_dim),
            cfg.kv_lora_rank,
        ),
        "w_uv": norm_init(
            keys[2], (n, cfg.kv_lora_rank, cfg.num_heads * cfg.v_head_dim),
            cfg.kv_lora_rank,
        ),
        "wo": norm_init(keys[3], (n, cfg.num_heads * cfg.v_head_dim, h),
                        cfg.num_heads * cfg.v_head_dim),
    }
    if cfg.q_lora_rank:
        params["w_dq"] = norm_init(keys[4], (n, h, cfg.q_lora_rank), h)
        params["q_norm"] = jnp.ones((n, cfg.q_lora_rank), cfg.dtype)
        params["w_uq"] = norm_init(keys[5], (n, cfg.q_lora_rank, hd_q), cfg.q_lora_rank)
    else:
        params["wq"] = norm_init(keys[4], (n, h, hd_q), h)
    return params


def init_params(cfg: DeepseekConfig, rng: jax.Array) -> dict:
    h = cfg.hidden_size
    kd, km = cfg.first_k_dense, cfg.num_moe_layers
    keys = jax.random.split(rng, 24)

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(cfg.dtype)

    params: dict = {
        "embed": norm_init(keys[0], (cfg.vocab_size, h), 1.0),
        "final_norm": jnp.ones((h,), cfg.dtype),
    }
    if kd:
        i = cfg.intermediate_size
        dense = _attn_params(cfg, keys[1:7], kd)
        dense.update(
            mlp_norm=jnp.ones((kd, h), cfg.dtype),
            w_gate=norm_init(keys[7], (kd, h, i), h),
            w_up=norm_init(keys[8], (kd, h, i), h),
            w_down=norm_init(keys[9], (kd, i, h), i),
        )
        params["dense_layers"] = dense
    if km:
        mi, e = cfg.moe_intermediate_size, cfg.num_experts
        si = cfg.n_shared_experts * mi
        moe = _attn_params(cfg, keys[10:16], km)
        moe.update(
            mlp_norm=jnp.ones((km, h), cfg.dtype),
            w_router=norm_init(keys[16], (km, h, e), h),
            **(
                {"router_bias": jnp.zeros((km, e), jnp.float32)}
                if cfg.scoring_func == "sigmoid" else {}
            ),
            w_gate=norm_init(keys[17], (km, e, h, mi), h),
            w_up=norm_init(keys[18], (km, e, h, mi), h),
            w_down=norm_init(keys[19], (km, e, mi, h), mi),
        )
        if si:
            moe.update(
                ws_gate=norm_init(keys[20], (km, h, si), h),
                ws_up=norm_init(keys[21], (km, h, si), h),
                ws_down=norm_init(keys[22], (km, si, h), si),
            )
        params["moe_layers"] = moe
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm_init(keys[23], (h, cfg.vocab_size), h)
    return params


def _attn_specs(cfg: DeepseekConfig) -> dict:
    specs = {
        "attn_norm": P(None, None),
        "w_dkv": P(None, None, None),   # latent path replicated (MQA-like)
        "kv_norm": P(None, None),
        "w_uk": P(None, None, "tp"),    # head-sharded up-projections
        "w_uv": P(None, None, "tp"),
        "wo": P(None, "tp", None),      # row-parallel → all-reduce
    }
    if cfg.q_lora_rank:
        specs["w_dq"] = P(None, None, None)
        specs["q_norm"] = P(None, None)
        specs["w_uq"] = P(None, None, "tp")
    else:
        specs["wq"] = P(None, None, "tp")
    return specs


def param_specs(cfg: DeepseekConfig) -> dict:
    specs: dict = {
        "embed": P(None, None),
        "final_norm": P(None),
    }
    if cfg.first_k_dense:
        dense = _attn_specs(cfg)
        dense.update(
            mlp_norm=P(None, None),
            w_gate=P(None, None, "tp"),
            w_up=P(None, None, "tp"),
            w_down=P(None, "tp", None),
        )
        specs["dense_layers"] = dense
    if cfg.num_moe_layers:
        moe = _attn_specs(cfg)
        moe.update(
            mlp_norm=P(None, None),
            w_router=P(None, None, None),
            **(
                {"router_bias": P(None, None)}
                if cfg.scoring_func == "sigmoid" else {}
            ),
            # routed experts over 'ep', within-expert FFN over 'tp'
            w_gate=P(None, "ep", None, "tp"),
            w_up=P(None, "ep", None, "tp"),
            w_down=P(None, "ep", "tp", None),
        )
        if cfg.n_shared_experts:
            moe.update(
                ws_gate=P(None, None, "tp"),
                ws_up=P(None, None, "tp"),
                ws_down=P(None, "tp", None),
            )
        specs["moe_layers"] = moe
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


# ---------------------------------------------------------------------------
# KV cache: latent + rope-key, tiny per token
# ---------------------------------------------------------------------------


def init_kv_cache(cfg: DeepseekConfig, num_blocks: int, block_size: int, dtype=None):
    dtype = dtype or cfg.dtype
    return {
        "k": jnp.zeros((cfg.num_layers, num_blocks, block_size, 1, cfg.kv_lora_rank), dtype),
        "v": jnp.zeros((cfg.num_layers, num_blocks, block_size, 1, cfg.qk_rope_head_dim), dtype),
    }


def kv_cache_specs(cfg: DeepseekConfig) -> dict:
    # the latent is shared across heads — replicate across tp (it is ~4x
    # smaller than a GQA cache even unsharded)
    return {"k": P(None, None, None, None, None), "v": P(None, None, None, None, None)}


def make_rope_tables(cfg: DeepseekConfig):
    # DeepSeek applies the YaRN temperature on the softmax scale
    # (attn_scale = mscale**2 / sqrt(d)), not baked into the tables
    return rope_table(
        cfg.max_position_embeddings, cfg.qk_rope_head_dim, cfg.rope_theta,
        scaling=cfg.rope_scaling, yarn_apply_attention_factor=False,
    )


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------


def _project_q(w, x, cfg: DeepseekConfig):
    """x [t, h] → q [t, heads, qk_head_dim] (optionally through the q-lora
    bottleneck)."""
    t = x.shape[0]
    if cfg.q_lora_rank:
        q = mm(rms_norm(mm(x, w["w_dq"]), w["q_norm"], cfg.rms_norm_eps), w["w_uq"])
    else:
        q = mm(x, w["wq"])
    return q.reshape(t, cfg.num_heads, cfg.qk_head_dim)


def _latent_kv(w, x, cfg: DeepseekConfig):
    """x [t, h] → (c_kv [t, r] normalized, k_rope [t, rope_dim] un-roped)."""
    dkv = mm(x, w["w_dkv"])
    c_kv = rms_norm(dkv[:, : cfg.kv_lora_rank], w["kv_norm"], cfg.rms_norm_eps)
    k_rope = dkv[:, cfg.kv_lora_rank :]
    return c_kv, k_rope


def _mla_prefill_attn(w, x, cfg: DeepseekConfig, positions, seq_len, k_layer, v_layer,
                      block_ids, cos, sin):
    """Dense causal MLA attention for one prefill chunk; writes latents to
    the paged cache.  Returns (attn_out [s, h], (k_layer, v_layer))."""
    s = x.shape[0]
    H = cfg.num_heads
    q = _project_q(w, x, cfg)
    q_nope, q_rope = q[..., : cfg.qk_nope_head_dim], q[..., cfg.qk_nope_head_dim :]
    q_rope = apply_rope(q_rope, positions, cos, sin)

    c_kv, k_rope = _latent_kv(w, x, cfg)
    k_rope = apply_rope(k_rope[:, None, :], positions, cos, sin)[:, 0]

    k_layer, v_layer = write_prefill_kv(
        k_layer, v_layer, c_kv[:, None, :], k_rope[:, None, :], block_ids, seq_len
    )

    # decompress K/V for the in-chunk dense attention (prefill is
    # compute-bound; this keeps the big matmuls on the MXU)
    w_uk = w["w_uk"].reshape(cfg.kv_lora_rank, H, cfg.qk_nope_head_dim)
    w_uv = w["w_uv"].reshape(cfg.kv_lora_rank, H, cfg.v_head_dim)
    k_nope = jnp.einsum("tr,rhn->thn", c_kv, w_uk)
    v = jnp.einsum("tr,rhv->thv", c_kv, w_uv)

    scale = jnp.float32(cfg.attn_scale)
    logits = (
        jnp.einsum("qhn,khn->hqk", q_nope.astype(jnp.float32), k_nope.astype(jnp.float32))
        + jnp.einsum("qhp,kp->hqk", q_rope.astype(jnp.float32), k_rope.astype(jnp.float32))
    ) * scale
    pos = jnp.arange(s)
    mask = (pos[None, :] <= pos[:, None]) & (pos[None, :] < seq_len)  # [q, k]
    logits = jnp.where(mask[None], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("hqk,khv->qhv", weights, v.astype(jnp.float32)).astype(cfg.dtype)
    return mm(out.reshape(s, -1), w["wo"]), (k_layer, v_layer)


def _mla_prefill_attn_with_prefix(
    w, x, cfg: DeepseekConfig, positions, tail_len, start_pos, k_layer, v_layer,
    full_block_ids, tail_block_ids, cos, sin,
):
    """Continued MLA prefill: the tail's queries attend to the resident
    prefix LATENTS (absorbed form — scores in latent space, context
    decompressed once) jointly with the in-chunk dense attention under one
    softmax; only the tail's latents are written.  Enables prefix-cache
    reuse and chunked prefill for the MLA family."""
    s = x.shape[0]
    H = cfg.num_heads
    q = _project_q(w, x, cfg)
    q_nope, q_rope = q[..., : cfg.qk_nope_head_dim], q[..., cfg.qk_nope_head_dim :]
    q_rope = apply_rope(q_rope, positions, cos, sin)

    c_kv, k_rope = _latent_kv(w, x, cfg)
    k_rope = apply_rope(k_rope[:, None, :], positions, cos, sin)[:, 0]

    # gather the resident prefix BEFORE writing the tail
    block_size = k_layer.shape[1]
    t_pref = full_block_ids.shape[0] * block_size
    ck_pref = k_layer[full_block_ids].reshape(t_pref, cfg.kv_lora_rank)
    kr_pref = v_layer[full_block_ids].reshape(t_pref, cfg.qk_rope_head_dim)

    k_layer, v_layer = write_prefill_kv(
        k_layer, v_layer, c_kv[:, None, :], k_rope[:, None, :], tail_block_ids, tail_len
    )

    w_uk = w["w_uk"].reshape(cfg.kv_lora_rank, H, cfg.qk_nope_head_dim)
    w_uv = w["w_uv"].reshape(cfg.kv_lora_rank, H, cfg.v_head_dim)
    scale = jnp.float32(cfg.attn_scale)

    # prefix scores, absorbed: q_lat·ck + q_rope·kr (identical math to
    # decompressing the prefix keys, without materializing them per head)
    q_lat = jnp.einsum(
        "qhn,rhn->qhr", q_nope.astype(jnp.float32), w_uk.astype(jnp.float32)
    )
    sp = (
        jnp.einsum("qhr,tr->hqt", q_lat, ck_pref.astype(jnp.float32))
        + jnp.einsum("qhp,tp->hqt", q_rope.astype(jnp.float32), kr_pref.astype(jnp.float32))
    ) * scale
    pref_valid = jnp.arange(t_pref)[None, :] < start_pos  # [1, Tp]
    sp = jnp.where(pref_valid[None], sp, NEG_INF)

    # in-chunk dense scores (decompressed, as in _mla_prefill_attn)
    k_nope = jnp.einsum("tr,rhn->thn", c_kv, w_uk)
    sc = (
        jnp.einsum("qhn,khn->hqk", q_nope.astype(jnp.float32), k_nope.astype(jnp.float32))
        + jnp.einsum("qhp,kp->hqk", q_rope.astype(jnp.float32), k_rope.astype(jnp.float32))
    ) * scale
    pos = jnp.arange(s)
    chunk_mask = (pos[None, :] <= pos[:, None]) & (pos[None, :] < tail_len)
    sc = jnp.where(chunk_mask[None], sc, NEG_INF)

    # one softmax across prefix + chunk keys
    logits = jnp.concatenate([sp, sc], axis=-1)  # [H, s, Tp + s]
    weights = jax.nn.softmax(logits, axis=-1)
    wp, wc = weights[..., :t_pref], weights[..., t_pref:]

    # prefix context in latent space, decompressed once; chunk context dense
    ctx_lat = jnp.einsum("hqt,tr->qhr", wp, ck_pref.astype(jnp.float32))
    out_pref = jnp.einsum("qhr,rhv->qhv", ctx_lat, w_uv.astype(jnp.float32))
    v_chunk = jnp.einsum("tr,rhv->thv", c_kv, w_uv)
    out_chunk = jnp.einsum("hqk,khv->qhv", wc, v_chunk.astype(jnp.float32))
    out = (out_pref + out_chunk).astype(cfg.dtype)
    return mm(out.reshape(s, -1), w["wo"]), (k_layer, v_layer)


def _mla_decode_attn(w, x, cfg: DeepseekConfig, positions, k_layer, v_layer,
                     block_tables, context_lens, slot_ids, cos, sin,
                     attention: str = "jax"):
    """Absorbed-form batched decode attention against the latent cache.

    ``attention="pallas"`` runs the MLA paged-attention kernel
    (ops/pallas/mla_attention.py): page latents stream VMEM-ward via the
    block table with online softmax — no [B, maxb*bs, R] gather
    materialized in HBM.  The XLA gather path is the portable fallback.
    """
    b = x.shape[0]
    H = cfg.num_heads
    q = _project_q(w, x, cfg)
    q_nope, q_rope = q[..., : cfg.qk_nope_head_dim], q[..., cfg.qk_nope_head_dim :]
    q_rope = apply_rope(q_rope[:, None], positions[:, None], cos, sin)[:, 0]

    c_kv_new, k_rope_new = _latent_kv(w, x, cfg)
    k_rope_new = apply_rope(k_rope_new[:, None, None, :], positions[:, None], cos, sin)[:, 0]
    k_layer, v_layer = write_decode_kv(
        k_layer, v_layer, c_kv_new[:, None, :], k_rope_new, slot_ids
    )

    # absorb q through the k up-projection: scores live in latent space
    w_uk = w["w_uk"].reshape(cfg.kv_lora_rank, H, cfg.qk_nope_head_dim)
    w_uv = w["w_uv"].reshape(cfg.kv_lora_rank, H, cfg.v_head_dim)
    q_lat = jnp.einsum("bhn,rhn->bhr", q_nope.astype(jnp.float32), w_uk.astype(jnp.float32))

    num_blocks, block_size = k_layer.shape[0], k_layer.shape[1]
    scale = float(cfg.attn_scale)

    if attention in ("pallas", "pallas_interpret"):
        from dynamo_tpu.ops.pallas.mla_attention import mla_paged_attention_decode

        ctx = mla_paged_attention_decode(
            q_lat, q_rope,
            k_layer.reshape(num_blocks, block_size, cfg.kv_lora_rank),
            v_layer.reshape(num_blocks, block_size, cfg.qk_rope_head_dim),
            block_tables, context_lens,
            scale=scale, interpret=attention == "pallas_interpret",
        )
    else:
        max_blocks = block_tables.shape[1]
        length = max_blocks * block_size
        ck = k_layer[block_tables].reshape(b, length, cfg.kv_lora_rank)
        kr = v_layer[block_tables].reshape(b, length, cfg.qk_rope_head_dim)
        logits = (
            jnp.einsum("bhr,btr->bht", q_lat, ck.astype(jnp.float32))
            + jnp.einsum("bhp,btp->bht", q_rope.astype(jnp.float32), kr.astype(jnp.float32))
        ) * scale
        valid = jnp.arange(length)[None, :] < context_lens[:, None]
        logits = jnp.where(valid[:, None, :], logits, NEG_INF)
        weights = jax.nn.softmax(logits, axis=-1)
        # context in latent space
        ctx = jnp.einsum("bht,btr->bhr", weights, ck.astype(jnp.float32))
    # decompress through the v up-projection
    out = jnp.einsum("bhr,rhv->bhv", ctx, w_uv.astype(jnp.float32)).astype(cfg.dtype)
    return mm(out.reshape(b, -1), w["wo"]), (k_layer, v_layer)


def _mla_unified_attn(w, x, cfg: DeepseekConfig, positions, token_pos,
                      token_lane, token_slot, k_layer, v_layer, block_tables,
                      page_phys, page_lane, page_ord, page_count, cos, sin,
                      attention: str = "jax", tb_tokens: int = 8,
                      pages_per_step: int = 1):
    """Absorbed-form ragged unified-batch MLA attention: the flat token
    axis carries chunked-prefill spans + decode tokens, every token writes
    its latent before anyone reads, scores stay in latent space per token.
    ``attention="pallas"`` runs the packed-lane ragged MLA kernel; the XLA
    twin (ops/attention.ragged_mla_paged_attention) is the fallback."""
    t = x.shape[0]
    H = cfg.num_heads
    q = _project_q(w, x, cfg)
    q_nope, q_rope = q[..., : cfg.qk_nope_head_dim], q[..., cfg.qk_nope_head_dim :]
    q_rope = apply_rope(q_rope, positions, cos, sin)

    c_kv, k_rope = _latent_kv(w, x, cfg)
    k_rope = apply_rope(k_rope[:, None, :], positions, cos, sin)
    k_layer, v_layer = write_decode_kv(
        k_layer, v_layer, c_kv[:, None, :], k_rope, token_slot
    )

    w_uk = w["w_uk"].reshape(cfg.kv_lora_rank, H, cfg.qk_nope_head_dim)
    w_uv = w["w_uv"].reshape(cfg.kv_lora_rank, H, cfg.v_head_dim)
    q_lat = jnp.einsum(
        "thn,rhn->thr", q_nope.astype(jnp.float32), w_uk.astype(jnp.float32)
    )

    num_blocks, block_size = k_layer.shape[0], k_layer.shape[1]
    scale = float(cfg.attn_scale)
    ck3 = k_layer.reshape(num_blocks, block_size, cfg.kv_lora_rank)
    kr3 = v_layer.reshape(num_blocks, block_size, cfg.qk_rope_head_dim)

    if attention in ("pallas", "pallas_interpret"):
        from dynamo_tpu.ops.pallas import ragged_mla_attention

        ctx = ragged_mla_attention(
            q_lat, q_rope, ck3, kr3, token_lane, token_pos,
            page_phys, page_lane, page_ord, page_count,
            scale=scale, tb_tokens=tb_tokens, pages_per_step=pages_per_step,
            interpret=attention == "pallas_interpret",
        )
    else:
        from dynamo_tpu.ops.attention import ragged_mla_paged_attention

        ctx = ragged_mla_paged_attention(
            q_lat, q_rope, ck3, kr3, block_tables, token_lane, token_pos,
            scale=scale,
        )
    out = jnp.einsum("thr,rhv->thv", ctx, w_uv.astype(jnp.float32)).astype(cfg.dtype)
    return mm(out.reshape(t, -1), w["wo"]), (k_layer, v_layer)


def _mla_window_attn(w, x, cfg: DeepseekConfig, positions, k_layer, v_layer,
                     block_tables, context_lens, flat_slots, cos, sin,
                     b: int, w_len: int, attention: str = "jax"):
    """Multi-query absorbed-form attention for speculative verification:
    w window queries per lane against the latent cache.
    ``attention="pallas"`` runs the MLA window kernel (W queries folded
    into the head axis, latent pages streamed once for all W positions);
    the XLA gather path is the portable fallback.
    ``x`` is position-major flat [w*b, h] (see mixtral_forward_verify on
    why dispatch order matters for the MoE layers)."""
    H = cfg.num_heads

    def to_bw(t, *tail):
        return position_major_to_batch(t, w_len, b, *tail)

    q = _project_q(w, x, cfg)                    # [w*b, H, qk_head_dim]
    q = to_bw(q, H, cfg.qk_head_dim)             # [b, w, H, d]
    q_nope, q_rope = q[..., : cfg.qk_nope_head_dim], q[..., cfg.qk_nope_head_dim :]
    q_rope = apply_rope(q_rope, positions, cos, sin)  # [b, w, H, p]

    c_kv_new, k_rope_new = _latent_kv(w, x, cfg)  # [w*b, r], [w*b, p]
    k_rope_bw = to_bw(k_rope_new, cfg.qk_rope_head_dim)[:, :, None, :]  # [b, w, 1, p]
    k_rope_bw = apply_rope(k_rope_bw, positions, cos, sin)
    k_layer, v_layer = write_decode_kv(
        k_layer, v_layer,
        c_kv_new[:, None, :],
        k_rope_bw.transpose(1, 0, 2, 3).reshape(w_len * b, 1, -1),
        flat_slots,
    )

    w_uk = w["w_uk"].reshape(cfg.kv_lora_rank, H, cfg.qk_nope_head_dim)
    w_uv = w["w_uv"].reshape(cfg.kv_lora_rank, H, cfg.v_head_dim)
    q_lat = jnp.einsum(
        "bwhn,rhn->bwhr", q_nope.astype(jnp.float32), w_uk.astype(jnp.float32)
    )

    num_blocks, block_size = k_layer.shape[0], k_layer.shape[1]
    if attention in ("pallas", "pallas_interpret"):
        from dynamo_tpu.ops.pallas.mla_attention import (
            mla_paged_window_attention_decode,
        )

        ctx = mla_paged_window_attention_decode(
            q_lat, q_rope,
            k_layer.reshape(num_blocks, block_size, cfg.kv_lora_rank),
            v_layer.reshape(num_blocks, block_size, cfg.qk_rope_head_dim),
            block_tables, context_lens,
            scale=float(cfg.attn_scale),
            interpret=attention == "pallas_interpret",
        )
    else:
        max_blocks = block_tables.shape[1]
        length = max_blocks * block_size
        ck = k_layer[block_tables].reshape(b, length, cfg.kv_lora_rank)
        kr = v_layer[block_tables].reshape(b, length, cfg.qk_rope_head_dim)
        logits = (
            jnp.einsum("bwhr,btr->bhwt", q_lat, ck.astype(jnp.float32))
            + jnp.einsum("bwhp,btp->bhwt", q_rope.astype(jnp.float32), kr.astype(jnp.float32))
        ) * float(cfg.attn_scale)
        q_pos = context_lens[:, None] - w_len + jnp.arange(w_len)[None, :]   # [b, w]
        kv_pos = jnp.arange(length)[None, None, :]                            # [1, 1, t]
        mask = kv_pos <= q_pos[:, :, None]                                    # [b, w, t]
        logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
        weights = jax.nn.softmax(logits, axis=-1)
        ctx = jnp.einsum("bhwt,btr->bwhr", weights, ck.astype(jnp.float32))
    out = jnp.einsum("bwhr,rhv->bwhv", ctx, w_uv.astype(jnp.float32)).astype(cfg.dtype)
    flat = out.transpose(1, 0, 2, 3).reshape(w_len * b, -1)
    return mm(flat, w["wo"]), (k_layer, v_layer)


def _dense_mlp(w, x):
    return mm(jax.nn.silu(mm(x, w["w_gate"])) * mm(x, w["w_up"]), w["w_down"])


def _moe_mlp(w, x, cfg: DeepseekConfig):
    routed = moe_ffn(
        x, w["w_router"], w["w_gate"], w["w_up"], w["w_down"],
        top_k=cfg.experts_per_token, capacity_factor=cfg.capacity_factor,
        router_bias=w.get("router_bias"),
        scoring="sigmoid_noaux" if cfg.scoring_func == "sigmoid" else "softmax",
        n_group=cfg.n_group, topk_group=cfg.topk_group,
        norm_topk_prob=cfg.norm_topk_prob,
    )
    out = routed * jnp.asarray(cfg.routed_scaling_factor, routed.dtype)
    if cfg.n_shared_experts:
        out = out + mm(jax.nn.silu(mm(x, w["ws_gate"])) * mm(x, w["ws_up"]), w["ws_down"])
    return out


def _run_stack(params_key, mlp_fn, x, cache_k, cache_v, attn_fn, cfg):
    """Scan one homogeneous layer stack, threading its cache slice."""

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
        attn_out, (k_layer, v_layer) = attn_fn(w, attn_in, k_layer, v_layer)
        x = x + attn_out
        mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
        x = x + mlp_fn(w, mlp_in)
        return x, (k_layer, v_layer)

    return jax.lax.scan(layer, x, (params_key, cache_k, cache_v))


def _forward(params, cfg: DeepseekConfig, x, kv_cache, attn_fn):
    """Shared trunk: dense stack then MoE stack, cache split on the layer
    axis and re-concatenated."""
    kd = cfg.first_k_dense
    k_cache, v_cache = kv_cache["k"], kv_cache["v"]
    new_k_parts, new_v_parts = [], []
    if kd:
        x, (nk, nv) = _run_stack(
            params["dense_layers"], lambda w, t: _dense_mlp(w, t),
            x, k_cache[:kd], v_cache[:kd], attn_fn, cfg,
        )
        new_k_parts.append(nk)
        new_v_parts.append(nv)
    if cfg.num_moe_layers:
        x, (nk, nv) = _run_stack(
            params["moe_layers"], lambda w, t: _moe_mlp(w, t, cfg),
            x, k_cache[kd:], v_cache[kd:], attn_fn, cfg,
        )
        new_k_parts.append(nk)
        new_v_parts.append(nv)
    new_cache = {
        "k": jnp.concatenate(new_k_parts) if len(new_k_parts) > 1 else new_k_parts[0],
        "v": jnp.concatenate(new_v_parts) if len(new_v_parts) > 1 else new_v_parts[0],
    }
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return x, new_cache


def _logits(params, cfg, x):
    if cfg.tie_word_embeddings:
        return x @ params["embed"].T.astype(x.dtype)
    return mm(x, params["lm_head"])


def deepseek_forward_prefill(
    params, cfg: DeepseekConfig, token_ids, kv_cache, block_ids, seq_len, start_pos,
    cos, sin,
):
    """Single-sequence prefill → (last-token logits [vocab], new cache)."""
    s = token_ids.shape[0]
    x = params["embed"][token_ids].astype(cfg.dtype)
    positions = start_pos + jnp.arange(s, dtype=jnp.int32)

    def attn(w, attn_in, k_layer, v_layer):
        return _mla_prefill_attn(
            w, attn_in, cfg, positions, seq_len, k_layer, v_layer, block_ids, cos, sin
        )

    x, new_cache = _forward(params, cfg, x, kv_cache, attn)
    last = x[jnp.maximum(seq_len - 1, 0)]
    logits = _logits(params, cfg, last[None])[0]
    return logits.astype(jnp.float32), new_cache


def deepseek_forward_prefill_with_prefix(
    params, cfg: DeepseekConfig, token_ids, kv_cache, full_block_ids,
    tail_block_ids, tail_len, start_pos, cos, sin,
):
    """Continued prefill over a reused prefix for the MLA family (same
    contract as llama_forward_prefill_with_prefix)."""
    s = token_ids.shape[0]
    x = params["embed"][token_ids].astype(cfg.dtype)
    positions = start_pos + jnp.arange(s, dtype=jnp.int32)

    def attn(w, attn_in, k_layer, v_layer):
        return _mla_prefill_attn_with_prefix(
            w, attn_in, cfg, positions, tail_len, start_pos, k_layer, v_layer,
            full_block_ids, tail_block_ids, cos, sin,
        )

    x, new_cache = _forward(params, cfg, x, kv_cache, attn)
    last = x[jnp.maximum(tail_len - 1, 0)]
    logits = _logits(params, cfg, last[None])[0]
    return logits.astype(jnp.float32), new_cache


def deepseek_forward_decode(
    params, cfg: DeepseekConfig, token_ids, kv_cache, block_tables, context_lens,
    slot_ids, cos, sin, *, attention: str = "jax",
):
    """Batched single-token decode → (logits [batch, vocab], new cache).
    MLA decode runs the absorbed latent path; ``attention="pallas"``
    dispatches the MLA paged-attention kernel, anything else the XLA
    gather fallback."""
    x = params["embed"][token_ids].astype(cfg.dtype)
    positions = jnp.maximum(context_lens - 1, 0)

    def attn(w, attn_in, k_layer, v_layer):
        return _mla_decode_attn(
            w, attn_in, cfg, positions, k_layer, v_layer,
            block_tables, context_lens, slot_ids, cos, sin,
            attention=attention,
        )

    x, new_cache = _forward(params, cfg, x, kv_cache, attn)
    logits = _logits(params, cfg, x)
    return logits.astype(jnp.float32), new_cache


def deepseek_forward_unified(
    params,
    cfg: DeepseekConfig,
    token_ids,      # [T] int32 — flat ragged token batch
    kv_cache,
    block_tables,   # [lanes, max_blocks] int32
    context_lens,   # [lanes] int32 incl. each lane's span end
    token_pos,      # [T] int32 absolute position (-1 = pad)
    token_slot,     # [T] int32 flat cache slot (OOB = pad)
    token_lane,     # [T] int32 owning lane (OOB = pad)
    page_phys,      # [T // tb_tokens, PS] int32 (pack_page_meta)
    page_lane,      # [T // tb_tokens, PS] int32 owning lane (-1 pad)
    page_ord,       # [T // tb_tokens, PS] int32 page ordinal
    page_count,     # [T // tb_tokens] int32 live worklist entries
    sample_rows,    # [lanes] int32 flat index of span's LAST token
    cos,
    sin,
    *,
    attention: str = "jax",     # "jax" | "pallas" | "pallas_interpret"
    tb_tokens: int = 8,
    pages_per_step: int = 1,
):
    """Ragged unified-batch forward for the MLA family: mixed spans +
    decode tokens in one launch against the latent cache (the llama
    unified contract).  Every token writes its compressed latent + rope
    key at its cache slot before attention reads, so span tokens see
    their own in-window predecessors through the cache; the MoE stack
    routes per token exactly as in the mixtral unified forward."""
    x = params["embed"][token_ids].astype(cfg.dtype)
    positions = jnp.maximum(token_pos, 0)

    def attn(w, attn_in, k_layer, v_layer):
        return _mla_unified_attn(
            w, attn_in, cfg, positions, token_pos, token_lane, token_slot,
            k_layer, v_layer, block_tables, page_phys, page_lane, page_ord,
            page_count, cos, sin, attention=attention, tb_tokens=tb_tokens,
            pages_per_step=pages_per_step,
        )

    x, new_cache = _forward(params, cfg, x, kv_cache, attn)
    rows = x[sample_rows]  # [lanes, h] — junk for hole lanes, caller-gated
    logits = _logits(params, cfg, rows)
    return logits.astype(jnp.float32), new_cache


def deepseek_forward_verify(
    params, cfg: DeepseekConfig, token_ids, kv_cache, block_tables,
    context_lens, slot_ids, cos, sin, *, attention: str = "jax",
):
    """Speculative-verification forward for the MLA family (contract:
    llama_forward_verify).  Window tokens run position-major (expert
    capacity priority, see mixtral_forward_verify)."""
    b, w_len = token_ids.shape
    x = params["embed"][token_ids.T.reshape(-1)].astype(cfg.dtype)
    positions = jnp.maximum(
        context_lens[:, None] - w_len + jnp.arange(w_len)[None, :], 0
    )
    flat_slots = slot_ids.T.reshape(-1)

    def attn(w, attn_in, k_layer, v_layer):
        return _mla_window_attn(
            w, attn_in, cfg, positions, k_layer, v_layer, block_tables,
            context_lens, flat_slots, cos, sin, b, w_len, attention=attention,
        )

    x, new_cache = _forward(params, cfg, x, kv_cache, attn)
    logits = _logits(params, cfg, x)
    logits = logits.reshape(w_len, b, -1).transpose(1, 0, 2)
    return logits.astype(jnp.float32), new_cache


# ------------------------------------------------------------------ weights


def load_hf_weights(cfg: DeepseekConfig, model_dir) -> dict:
    """Load HF DeepSeek-V2/V3 safetensors into the dense/moe layer-stacked
    pytree.  MLA projections split and transpose:
    ``kv_b_proj [H*(nope+v), R]`` splits into ``w_uk [R, H*nope]`` and
    ``w_uv [R, H*v]`` (per-head row grouping), the latent down-projection
    ``kv_a_proj_with_mqa`` transposes into ``w_dkv [h, R+P]``."""
    import numpy as np

    from dynamo_tpu.models.hf_io import read_safetensors

    tensors = read_safetensors(model_dir)

    def get(name: str, transpose: bool = False):
        t = tensors[name]
        if transpose:
            t = t.T
        return np.asarray(t)

    H, nope, v_dim, r = (
        cfg.num_heads, cfg.qk_nope_head_dim, cfg.v_head_dim, cfg.kv_lora_rank
    )

    def deinterleave(cols: "np.ndarray") -> "np.ndarray":
        """HF DeepSeek stores rope feature dims interleaved (the official
        modeling code de-interleaves activations before rotate-half; vLLM
        loads with is_neox_style=False).  Our apply_rope is split-half
        (NeoX), so bake the permutation into the projection's rope output
        columns once at load time."""
        return np.concatenate([cols[..., 0::2], cols[..., 1::2]], axis=-1)

    def fix_q_rope(mat: "np.ndarray") -> "np.ndarray":
        """mat [in, H*qk_head]: de-interleave each head's rope slice."""
        shaped = mat.reshape(mat.shape[0], H, nope + cfg.qk_rope_head_dim).copy()
        shaped[..., nope:] = deinterleave(shaped[..., nope:])
        return shaped.reshape(mat.shape[0], -1)

    def attn_leaves(i: int) -> dict:
        p = f"model.layers.{i}.self_attn"
        kv_b = get(f"{p}.kv_b_proj.weight")          # [H*(nope+v), R]
        kv_b = kv_b.reshape(H, nope + v_dim, r)
        w_uk = kv_b[:, :nope, :].transpose(2, 0, 1).reshape(r, H * nope)
        w_uv = kv_b[:, nope:, :].transpose(2, 0, 1).reshape(r, H * v_dim)
        w_dkv = get(f"{p}.kv_a_proj_with_mqa.weight", True).copy()
        w_dkv[:, r:] = deinterleave(w_dkv[:, r:])  # rope key columns
        out = {
            "attn_norm": get(f"model.layers.{i}.input_layernorm.weight"),
            "w_dkv": w_dkv,
            "kv_norm": get(f"{p}.kv_a_layernorm.weight"),
            "w_uk": w_uk,
            "w_uv": w_uv,
            "wo": get(f"{p}.o_proj.weight", True),
            "mlp_norm": get(f"model.layers.{i}.post_attention_layernorm.weight"),
        }
        if cfg.q_lora_rank:
            out["w_dq"] = get(f"{p}.q_a_proj.weight", True)
            out["q_norm"] = get(f"{p}.q_a_layernorm.weight")
            out["w_uq"] = fix_q_rope(get(f"{p}.q_b_proj.weight", True))
        else:
            out["wq"] = fix_q_rope(get(f"{p}.q_proj.weight", True))
        return out

    def stack(dicts: list[dict]) -> dict:
        return {
            # e_score_correction_bias must stay fp32: bf16 rounding flips
            # near-tied expert selections vs the reference
            k: jnp.asarray(
                np.stack([d[k] for d in dicts]),
                jnp.float32 if k == "router_bias" else cfg.dtype,
            )
            for k in dicts[0]
        }

    dense, moe = [], []
    for i in range(cfg.num_layers):
        leaves = attn_leaves(i)
        mlp = f"model.layers.{i}.mlp"
        if i < cfg.first_k_dense:
            leaves.update(
                w_gate=get(f"{mlp}.gate_proj.weight", True),
                w_up=get(f"{mlp}.up_proj.weight", True),
                w_down=get(f"{mlp}.down_proj.weight", True),
            )
            dense.append(leaves)
        else:
            if cfg.scoring_func == "sigmoid":
                leaves["router_bias"] = get(f"{mlp}.gate.e_score_correction_bias")
            leaves.update(
                w_router=get(f"{mlp}.gate.weight", True),
                w_gate=np.stack([
                    get(f"{mlp}.experts.{e}.gate_proj.weight", True)
                    for e in range(cfg.num_experts)
                ]),
                w_up=np.stack([
                    get(f"{mlp}.experts.{e}.up_proj.weight", True)
                    for e in range(cfg.num_experts)
                ]),
                w_down=np.stack([
                    get(f"{mlp}.experts.{e}.down_proj.weight", True)
                    for e in range(cfg.num_experts)
                ]),
            )
            if cfg.n_shared_experts:
                leaves.update(
                    ws_gate=get(f"{mlp}.shared_experts.gate_proj.weight", True),
                    ws_up=get(f"{mlp}.shared_experts.up_proj.weight", True),
                    ws_down=get(f"{mlp}.shared_experts.down_proj.weight", True),
                )
            moe.append(leaves)

    params: dict = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), cfg.dtype),
        "final_norm": jnp.asarray(get("model.norm.weight"), cfg.dtype),
    }
    if dense:
        params["dense_layers"] = stack(dense)
    if moe:
        params["moe_layers"] = stack(moe)
    if not cfg.tie_word_embeddings and "lm_head.weight" in tensors:
        params["lm_head"] = jnp.asarray(get("lm_head.weight", True), cfg.dtype)
    return params
