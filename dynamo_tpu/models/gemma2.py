"""Gemma-2 model family (TPU-first, layer-scanned).

What distinguishes Gemma-2 from the llama-geometry families
(reference serves it through its engines' model zoos; HF architecture
``Gemma2ForCausalLM``):

- **Alternating local/global attention**: even-indexed layers use a
  sliding window, odd-indexed layers full attention.  The layer stack
  still runs as ONE ``lax.scan``: a per-layer int32 window array threads
  through the scan and the attention ops mask with a traced window
  (``<= 0`` = full attention, ops/attention.py ``_window_mask``) — no
  unrolling, one compiled layer body.
- **Logit soft-capping**: attention logits pass through
  ``cap * tanh(x / cap)`` (attn_logit_softcapping, 50.0) and final LM
  logits likewise (final_logit_softcapping, 30.0).
- **Sandwich norms**: each sub-block is wrapped pre AND post
  (input_layernorm / post_attention_layernorm around attention,
  pre_feedforward_layernorm / post_feedforward_layernorm around the MLP),
  with the post-norm applied to the block output before the residual add.
- **Query scaling** by ``query_pre_attn_scalar**-0.5`` instead of
  ``head_dim**-0.5``.
- Gemma-1 quirks carry over: GeGLU MLP, sqrt(hidden) embedding scale,
  (1 + w) RMSNorm weights (baked to ``1 + w`` at load).

Serving notes: the paged decode path uses the JAX attention op (the
Pallas kernel has no per-layer window plumbing yet — ``attention=`` is
accepted and ignored); sequence parallelism is fenced by the engine's
``sliding_window`` sp-mesh guard.  Speculative decoding IS supported:
``gemma2_forward_verify`` threads the per-layer traced windows plus the
attn softcap and query scale through ``window_attention``, spec-vs-plain
token-exactness pinned by test.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.attention import (
    _apply_softcap,
    dense_causal_attention,
    gather_prefix_kv,
    paged_decode_attention,
    prefill_attention_with_prefix,
    window_attention,
    write_decode_kv,
    write_prefill_kv,
)
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.quant import mm
from dynamo_tpu.ops.rope import apply_rope, rope_table

P = jax.sharding.PartitionSpec


@dataclass(frozen=True)
class Gemma2Config:
    vocab_size: int = 256000
    hidden_size: int = 2304
    intermediate_size: int = 9216
    num_layers: int = 26
    num_heads: int = 8
    num_kv_heads: int = 4
    head_dim: int = 256
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    rope_scaling: Any = None          # gemma-2 ships none; kept for rope_table
    sliding_window: int = 4096        # even-indexed layers only
    query_pre_attn_scalar: float = 256.0
    attn_logit_softcap: float = 50.0
    final_logit_softcap: float = 30.0
    tie_word_embeddings: bool = True  # always, in every released checkpoint
    dtype: Any = jnp.bfloat16

    @property
    def embed_scale(self) -> float:
        return float(self.hidden_size) ** 0.5

    def layer_windows(self) -> jnp.ndarray:
        """Per-layer attention window, int32 [L]: the sliding window on
        even layers, 0 (= full attention) on odd layers — HF Gemma-2's
        ``layer_types`` pattern (sliding_attention first)."""
        idx = jnp.arange(self.num_layers, dtype=jnp.int32)
        return jnp.where(idx % 2 == 0, jnp.int32(self.sliding_window), 0)

    @classmethod
    def from_hf_config(cls, config: dict | str | Path) -> "Gemma2Config":
        if not isinstance(config, dict):
            config = json.loads(Path(config).read_text())
        heads = config["num_attention_heads"]
        return cls(
            vocab_size=config["vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["intermediate_size"],
            num_layers=config["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=config.get("num_key_value_heads", heads),
            head_dim=config.get("head_dim") or config["hidden_size"] // heads,
            max_position_embeddings=config.get("max_position_embeddings", 8192),
            rms_norm_eps=config.get("rms_norm_eps", 1e-6),
            rope_theta=config.get("rope_theta", 10000.0),
            rope_scaling=config.get("rope_scaling"),
            sliding_window=config.get("sliding_window", 4096),
            query_pre_attn_scalar=float(
                config.get("query_pre_attn_scalar")
                or config["hidden_size"] // heads
            ),
            attn_logit_softcap=config.get("attn_logit_softcapping", 50.0),
            final_logit_softcap=config.get("final_logit_softcapping", 30.0),
        )

    @classmethod
    def tiny(cls) -> "Gemma2Config":
        """Test geometry: small enough for CPU oracles, 4 layers so both
        attention patterns appear twice."""
        return cls(
            vocab_size=480, hidden_size=64, intermediate_size=128,
            num_layers=4, num_heads=4, num_kv_heads=2, head_dim=16,
            max_position_embeddings=128, sliding_window=8,
            query_pre_attn_scalar=16.0,
        )


def init_params(cfg: Gemma2Config, rng: jax.Array) -> dict:
    keys = jax.random.split(rng, 9)
    h, i, l_ = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    qd, kvd = cfg.num_heads * cfg.head_dim, cfg.num_kv_heads * cfg.head_dim

    def norm_init(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(cfg.dtype)

    return {
        "embed": norm_init(keys[0], (cfg.vocab_size, h), 1.0),
        "final_norm": jnp.ones((h,), cfg.dtype),
        "layers": {
            "attn_norm": jnp.ones((l_, h), cfg.dtype),
            "post_attn_norm": jnp.ones((l_, h), cfg.dtype),
            "mlp_norm": jnp.ones((l_, h), cfg.dtype),
            "post_mlp_norm": jnp.ones((l_, h), cfg.dtype),
            "wq": norm_init(keys[1], (l_, h, qd), h),
            "wk": norm_init(keys[2], (l_, h, kvd), h),
            "wv": norm_init(keys[3], (l_, h, kvd), h),
            "wo": norm_init(keys[4], (l_, qd, h), qd),
            "w_gate": norm_init(keys[5], (l_, h, i), h),
            "w_up": norm_init(keys[6], (l_, h, i), h),
            "w_down": norm_init(keys[7], (l_, i, h), i),
        },
    }


def param_specs(cfg: Gemma2Config) -> dict:
    """Same TP/PP story as the llama family: heads sharded on 'tp',
    stacked layer axis on 'pp' (models/llama.py param_specs)."""
    norm = P("pp", None)
    return {
        "embed": P(None, None),
        "final_norm": P(None),
        "layers": {
            "attn_norm": norm, "post_attn_norm": norm,
            "mlp_norm": norm, "post_mlp_norm": norm,
            "wq": P("pp", None, "tp"), "wk": P("pp", None, "tp"),
            "wv": P("pp", None, "tp"), "wo": P("pp", "tp", None),
            "w_gate": P("pp", None, "tp"), "w_up": P("pp", None, "tp"),
            "w_down": P("pp", "tp", None),
        },
    }


def make_rope_tables(cfg: Gemma2Config):
    return rope_table(
        cfg.max_position_embeddings, cfg.head_dim, cfg.rope_theta,
        scaling=cfg.rope_scaling,
    )


def _embed(params, cfg: Gemma2Config, token_ids) -> jnp.ndarray:
    x = params["embed"][token_ids].astype(cfg.dtype)
    return x * jnp.asarray(cfg.embed_scale, cfg.dtype)


def _geglu(x, w):
    act = jax.nn.gelu(mm(x, w["w_gate"]), approximate=True)
    return mm(act * mm(x, w["w_up"]), w["w_down"])


def _qkv(attn_in, w, cfg: Gemma2Config):
    s = attn_in.shape[0]
    q = mm(attn_in, w["wq"]).reshape(s, cfg.num_heads, cfg.head_dim)
    k = mm(attn_in, w["wk"]).reshape(s, cfg.num_kv_heads, cfg.head_dim)
    v = mm(attn_in, w["wv"]).reshape(s, cfg.num_kv_heads, cfg.head_dim)
    return q, k, v


def _final_logits(params, cfg: Gemma2Config, x) -> jnp.ndarray:
    logits = (x @ params["embed"].T.astype(x.dtype)).astype(jnp.float32)
    # HF semantics: null/0 capping = no capping (guard both; 0 would be a
    # divide-by-zero into NaN logits)
    if not cfg.final_logit_softcap:
        return logits
    return _apply_softcap(logits, cfg.final_logit_softcap)


def _attn_kwargs(cfg: Gemma2Config, window) -> dict:
    return {
        "sliding_window": window,
        # HF semantics: null/0 capping = no capping
        "logit_softcap": cfg.attn_logit_softcap or None,
        "query_scale": float(cfg.query_pre_attn_scalar) ** -0.5,
    }


def gemma2_forward_prefill(
    params: dict,
    cfg: Gemma2Config,
    token_ids: jnp.ndarray,   # [seq_pad] int32
    kv_cache: dict,           # {"k","v"}: [L, N, bs, kvh, d]
    block_ids: jnp.ndarray,   # [max_blocks] int32
    seq_len: jnp.ndarray,     # scalar int32
    start_pos: jnp.ndarray,   # scalar int32 (chunked prefill offset)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> tuple[jnp.ndarray, dict]:
    """Single-sequence prefill.  Returns (last-token logits [vocab], cache).

    start_pos > 0 (an intermediate-chunk continuation) is served by
    gemma2_forward_prefill_with_prefix; this entry handles whole prompts.
    """
    s = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)
    positions = start_pos + jnp.arange(s, dtype=jnp.int32)
    eps = cfg.rms_norm_eps

    def layer(x, layer_in):
        w, window, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        k_layer, v_layer = write_prefill_kv(
            k_layer, v_layer, k, v, block_ids, seq_len
        )
        attn = dense_causal_attention(
            q[None], k[None], v[None], seq_len[None],
            **_attn_kwargs(cfg, window),
        )[0]
        attn = mm(attn.reshape(s, -1), w["wo"])
        x = x + rms_norm(attn, w["post_attn_norm"], eps)
        mlp = _geglu(rms_norm(x, w["mlp_norm"], eps), w)
        x = x + rms_norm(mlp, w["post_mlp_norm"], eps)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x,
        (params["layers"], cfg.layer_windows(), kv_cache["k"], kv_cache["v"]),
    )
    x = rms_norm(x, params["final_norm"], eps)
    last = x[jnp.maximum(seq_len - 1, 0)]
    logits = _final_logits(params, cfg, last[None])[0]
    return logits, {"k": new_k, "v": new_v}


def gemma2_forward_prefill_with_prefix(
    params: dict,
    cfg: Gemma2Config,
    token_ids: jnp.ndarray,       # [tail_pad] int32
    kv_cache: dict,
    full_block_ids: jnp.ndarray,  # [max_blocks] int32 (prefix + tail)
    tail_block_ids: jnp.ndarray,  # [max_blocks] int32 (from first tail block)
    tail_len: jnp.ndarray,        # scalar int32
    start_pos: jnp.ndarray,       # scalar int32 (cached prefix length)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> tuple[jnp.ndarray, dict]:
    """Continued prefill over a resident prefix (prefix-cache hits and
    chunked prefill) — same contract as the llama-family twin."""
    s = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)
    positions = start_pos + jnp.arange(s, dtype=jnp.int32)
    eps = cfg.rms_norm_eps

    def layer(x, layer_in):
        w, window, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        k_prefix, v_prefix = gather_prefix_kv(k_layer, v_layer, full_block_ids)
        k_layer, v_layer = write_prefill_kv(
            k_layer, v_layer, k, v, tail_block_ids, tail_len
        )
        attn = prefill_attention_with_prefix(
            q, k, v, k_prefix, v_prefix, start_pos, tail_len,
            **_attn_kwargs(cfg, window),
        )
        attn = mm(attn.reshape(s, -1), w["wo"])
        x = x + rms_norm(attn, w["post_attn_norm"], eps)
        mlp = _geglu(rms_norm(x, w["mlp_norm"], eps), w)
        x = x + rms_norm(mlp, w["post_mlp_norm"], eps)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x,
        (params["layers"], cfg.layer_windows(), kv_cache["k"], kv_cache["v"]),
    )
    x = rms_norm(x, params["final_norm"], eps)
    last = x[jnp.maximum(tail_len - 1, 0)]
    logits = _final_logits(params, cfg, last[None])[0]
    return logits, {"k": new_k, "v": new_v}


def gemma2_forward_decode(
    params: dict,
    cfg: Gemma2Config,
    token_ids: jnp.ndarray,     # [batch] int32
    kv_cache: dict,
    block_tables: jnp.ndarray,  # [batch, max_blocks] int32
    context_lens: jnp.ndarray,  # [batch] int32 (length INCLUDING this token)
    slot_ids: jnp.ndarray,      # [batch] int32 flat slot for this token
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    attention: str = "jax",     # accepted for engine compat; the JAX path
                                # is used regardless (no per-layer window
                                # plumbing in the Pallas kernel yet)
) -> tuple[jnp.ndarray, dict]:
    """Batched single-token decode.  Returns (logits [batch, vocab], cache)."""
    del attention
    b = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)  # [b, h]
    positions = jnp.maximum(context_lens - 1, 0)
    eps = cfg.rms_norm_eps

    def layer(x, layer_in):
        w, window, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        k_layer, v_layer = write_decode_kv(k_layer, v_layer, k, v, slot_ids)
        attn = paged_decode_attention(
            q, k_layer, v_layer, block_tables, context_lens,
            **_attn_kwargs(cfg, window),
        )
        attn = mm(attn.reshape(b, -1), w["wo"])
        x = x + rms_norm(attn, w["post_attn_norm"], eps)
        mlp = _geglu(rms_norm(x, w["mlp_norm"], eps), w)
        x = x + rms_norm(mlp, w["post_mlp_norm"], eps)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x,
        (params["layers"], cfg.layer_windows(), kv_cache["k"], kv_cache["v"]),
    )
    x = rms_norm(x, params["final_norm"], eps)
    logits = _final_logits(params, cfg, x)
    return logits, {"k": new_k, "v": new_v}


def gemma2_forward_verify(
    params: dict,
    cfg: Gemma2Config,
    token_ids: jnp.ndarray,     # [batch, w] int32 — last accepted + drafts
    kv_cache: dict,
    block_tables: jnp.ndarray,  # [batch, max_blocks] int32
    context_lens: jnp.ndarray,  # [batch] int32 INCLUDING the window's last
    slot_ids: jnp.ndarray,      # [batch, w] int32 flat slots per position
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    attention: str = "jax",     # accepted for engine compat; windowed
                                # layers always take the XLA verify path
) -> tuple[jnp.ndarray, dict]:
    """Speculative-verification forward: score all w window positions in
    one pass (logits [batch, w, vocab]) — same contract as
    llama_forward_verify, with each layer's traced window masking its
    verify queries (ops/attention.window_attention sliding_window)."""
    b, w_len = token_ids.shape
    x = _embed(params, cfg, token_ids.reshape(-1))  # [b*w, h]
    positions = jnp.maximum(
        context_lens[:, None] - w_len + jnp.arange(w_len)[None, :], 0
    )
    flat_slots = slot_ids.reshape(-1)
    eps = cfg.rms_norm_eps

    def layer(x, layer_in):
        w, window, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(
            q.reshape(b, w_len, cfg.num_heads, cfg.head_dim), positions,
            cos, sin,
        )
        k = apply_rope(
            k.reshape(b, w_len, cfg.num_kv_heads, cfg.head_dim), positions,
            cos, sin,
        )
        v = v.reshape(b, w_len, cfg.num_kv_heads, cfg.head_dim)
        k_layer, v_layer = write_decode_kv(
            k_layer, v_layer,
            k.reshape(b * w_len, cfg.num_kv_heads, cfg.head_dim),
            v.reshape(b * w_len, cfg.num_kv_heads, cfg.head_dim), flat_slots,
        )
        attn = window_attention(
            "jax", q, k_layer, v_layer, block_tables, context_lens,
            **_attn_kwargs(cfg, window),
        )
        x = x + rms_norm(
            mm(attn.reshape(b * w_len, -1), w["wo"]), w["post_attn_norm"], eps
        )
        mlp = _geglu(rms_norm(x, w["mlp_norm"], eps), w)
        x = x + rms_norm(mlp, w["post_mlp_norm"], eps)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x,
        (params["layers"], cfg.layer_windows(), kv_cache["k"], kv_cache["v"]),
    )
    x = rms_norm(x, params["final_norm"], eps)
    logits = _final_logits(params, cfg, x).reshape(b, w_len, -1)
    return logits, {"k": new_k, "v": new_v}


# ---------------------------------------------------------------------------
# HF weight loading
# ---------------------------------------------------------------------------

_HF_LAYER_MAP = {
    "attn_norm": "model.layers.{i}.input_layernorm.weight",
    "post_attn_norm": "model.layers.{i}.post_attention_layernorm.weight",
    "mlp_norm": "model.layers.{i}.pre_feedforward_layernorm.weight",
    "post_mlp_norm": "model.layers.{i}.post_feedforward_layernorm.weight",
    "wq": "model.layers.{i}.self_attn.q_proj.weight",
    "wk": "model.layers.{i}.self_attn.k_proj.weight",
    "wv": "model.layers.{i}.self_attn.v_proj.weight",
    "wo": "model.layers.{i}.self_attn.o_proj.weight",
    "w_gate": "model.layers.{i}.mlp.gate_proj.weight",
    "w_up": "model.layers.{i}.mlp.up_proj.weight",
    "w_down": "model.layers.{i}.mlp.down_proj.weight",
}

_NORM_LEAVES = ("attn_norm", "post_attn_norm", "mlp_norm", "post_mlp_norm")


def load_hf_weights(cfg: Gemma2Config, model_dir: str | Path, *,
                    tensors: dict | None = None) -> dict:
    """Gemma checkpoints store RMSNorm weights as w with runtime (1 + w):
    bake the +1 once (same trick as gemma-1, models/llama.py)."""
    if tensors is None:
        from dynamo_tpu.models.hf_io import read_safetensors

        tensors = read_safetensors(model_dir)
    if "lm_head.weight" in tensors:
        # every released Gemma-2 ties the unembedding; a finetune shipping
        # a trained lm_head would be silently mis-projected by the tied
        # path — refuse loudly instead
        raise ValueError(
            "gemma2 checkpoint ships lm_head.weight (untied unembedding); "
            "this family implements the tied projection only"
        )

    def get(name: str, transpose: bool = False):
        t = tensors[name]
        if transpose:
            t = t.T
        return jnp.asarray(t, cfg.dtype)

    plus_one = lambda t: (t.astype(jnp.float32) + 1.0).astype(t.dtype)  # noqa: E731
    layers: dict[str, list] = {k: [] for k in _HF_LAYER_MAP}
    for i in range(cfg.num_layers):
        for ours, theirs in _HF_LAYER_MAP.items():
            t = get(theirs.format(i=i), transpose=ours.startswith("w"))
            if ours in _NORM_LEAVES:
                t = plus_one(t)
            layers[ours].append(t)
    return {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": plus_one(get("model.norm.weight")),
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
    }
