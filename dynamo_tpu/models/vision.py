"""Vision encoder (ViT) + LLaVA-style projector for multimodal serving.

The encode-worker model behind ``examples/multimodal`` (reference:
examples/multimodal/components/encode_worker.py:61 — there a HF CLIP/SigLIP
encoder inside the engine; here a native JAX ViT, TPU-first):

- patchify as reshape + one big matmul (the conv-as-matmul form the MXU
  wants — no image-space convolution loops);
- layer weights stacked on a leading axis and iterated with ``lax.scan``
  (one compiled block body, like the llama trunk);
- pre-LN transformer blocks, fp32 softmax/norms, GELU MLP;
- 2-layer GELU projector into the LLM hidden space (LLaVA-style), so the
  output splices directly into ``llama_forward_prefill_embeds``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.norms import layer_norm


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 336
    patch_size: int = 14
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    mlp_dim: int = 4096
    projector_dim: int = 4096       # LLM hidden size
    layer_norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def from_hf_config(
        cls, config: dict | str | Path, *, llm_hidden_size: int | None = None
    ) -> "VisionConfig":
        """Accepts a LLaVA-style multimodal config (``vision_config`` +
        ``text_config``) or a bare CLIP/SigLIP vision_config dict.

        ``projector_dim`` is the LLM's hidden size (the projector output
        must splice into the text model's embedding stream), so it comes
        from ``text_config.hidden_size`` — NOT the vision tower's
        ``projection_dim``, which is CLIP's contrastive embedding width.
        Pass ``llm_hidden_size`` explicitly when supplying a bare
        vision_config."""
        if not isinstance(config, dict):
            config = json.loads(Path(config).read_text())
        vision = config.get("vision_config", config)
        if llm_hidden_size is None:
            text = config.get("text_config")
            if isinstance(text, dict) and "hidden_size" in text:
                llm_hidden_size = text["hidden_size"]
            elif "vision_config" in config and "hidden_size" in config:
                # older LLaVA layout: the top level IS the LM config
                llm_hidden_size = config["hidden_size"]
            else:
                llm_hidden_size = 4096
        return cls(
            image_size=vision.get("image_size", 336),
            patch_size=vision.get("patch_size", 14),
            hidden_size=vision.get("hidden_size", 1024),
            num_layers=vision.get("num_hidden_layers", 24),
            num_heads=vision.get("num_attention_heads", 16),
            mlp_dim=vision.get("intermediate_size", 4096),
            projector_dim=llm_hidden_size,
        )

    @classmethod
    def tiny(cls) -> "VisionConfig":
        """Test geometry (runs on CPU meshes)."""
        return cls(
            image_size=16, patch_size=8, hidden_size=32, num_layers=2,
            num_heads=2, mlp_dim=64, projector_dim=64, dtype=jnp.float32,
        )


def init_vit_params(cfg: VisionConfig, rng: jax.Array) -> dict:
    keys = jax.random.split(rng, 10)
    h, m, l_ = cfg.hidden_size, cfg.mlp_dim, cfg.num_layers
    patch_dim = cfg.patch_size * cfg.patch_size * 3

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(cfg.dtype)

    return {
        "patch_proj": norm_init(keys[0], (patch_dim, h), patch_dim),
        "pos_embed": norm_init(keys[1], (cfg.num_patches, h), h),
        "layers": {
            "ln1_w": jnp.ones((l_, h), cfg.dtype),
            "ln1_b": jnp.zeros((l_, h), cfg.dtype),
            "wq": norm_init(keys[2], (l_, h, h), h),
            "wk": norm_init(keys[3], (l_, h, h), h),
            "wv": norm_init(keys[4], (l_, h, h), h),
            "wo": norm_init(keys[5], (l_, h, h), h),
            "ln2_w": jnp.ones((l_, h), cfg.dtype),
            "ln2_b": jnp.zeros((l_, h), cfg.dtype),
            "w1": norm_init(keys[6], (l_, h, m), h),
            "b1": jnp.zeros((l_, m), cfg.dtype),
            "w2": norm_init(keys[7], (l_, m, h), m),
            "b2": jnp.zeros((l_, h), cfg.dtype),
        },
        "final_ln_w": jnp.ones((h,), cfg.dtype),
        "final_ln_b": jnp.zeros((h,), cfg.dtype),
        "proj_w1": norm_init(keys[8], (h, cfg.projector_dim), h),
        "proj_b1": jnp.zeros((cfg.projector_dim,), cfg.dtype),
        "proj_w2": norm_init(keys[9], (cfg.projector_dim, cfg.projector_dim), cfg.projector_dim),
        "proj_b2": jnp.zeros((cfg.projector_dim,), cfg.dtype),
    }


def patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """[B, H, W, 3] → [B, num_patches, patch*patch*3] (reshape only)."""
    b, hgt, wid, c = images.shape
    gh, gw = hgt // patch, wid // patch
    x = images.reshape(b, gh, patch, gw, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, patch * patch * c)


def vit_encode_video(
    params: dict,
    cfg: VisionConfig,
    frames: jnp.ndarray,
    *,
    temporal_pool: int = 2,
) -> jnp.ndarray:
    """[T, H, W, 3] video frames → [ceil(T/pool) * num_patches, projector_dim].

    LLaVA-video-style: every frame runs the SAME ViT+projector as a batch
    (one compiled program, frames on the batch axis — the MXU-friendly
    form), then groups of ``temporal_pool`` consecutive frames mean-pool
    per patch position to bound the token budget before the embeddings
    splice into the text stream (reference: the multimodal video variants
    under examples/multimodal/ — video frames → encode worker → embedding
    transfer to the LLM worker)."""
    if temporal_pool < 1:
        raise ValueError(f"temporal_pool must be >= 1, got {temporal_pool}")
    t = frames.shape[0]
    per_frame = vit_encode(params, cfg, frames)  # [T, P, D]
    if temporal_pool > 1:
        pad = (-t) % temporal_pool
        if pad:
            # pad by repeating the last frame so partial tail groups pool
            # over real content
            per_frame = jnp.concatenate(
                [per_frame, jnp.repeat(per_frame[-1:], pad, axis=0)], axis=0
            )
        groups = per_frame.reshape(
            -1, temporal_pool, cfg.num_patches, cfg.projector_dim
        )
        per_frame = groups.mean(axis=1)
    return per_frame.reshape(-1, cfg.projector_dim)


def vit_encode(params: dict, cfg: VisionConfig, images: jnp.ndarray) -> jnp.ndarray:
    """[B, H, W, 3] images → [B, num_patches, projector_dim] embeddings."""
    b = images.shape[0]
    x = patchify(images.astype(cfg.dtype), cfg.patch_size) @ params["patch_proj"]
    x = x + params["pos_embed"]
    scale = 1.0 / jnp.sqrt(jnp.float32(cfg.head_dim))

    def block(x, w):
        attn_in = layer_norm(x, w["ln1_w"], w["ln1_b"], cfg.layer_norm_eps)
        q = (attn_in @ w["wq"]).reshape(b, -1, cfg.num_heads, cfg.head_dim)
        k = (attn_in @ w["wk"]).reshape(b, -1, cfg.num_heads, cfg.head_dim)
        v = (attn_in @ w["wv"]).reshape(b, -1, cfg.num_heads, cfg.head_dim)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
        weights = jax.nn.softmax(logits * scale, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32))
        x = x + attn.reshape(b, -1, cfg.hidden_size).astype(cfg.dtype) @ w["wo"]
        mlp_in = layer_norm(x, w["ln2_w"], w["ln2_b"], cfg.layer_norm_eps)
        x = x + jax.nn.gelu(mlp_in @ w["w1"] + w["b1"]) @ w["w2"] + w["b2"]
        return x, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], cfg.layer_norm_eps)
    # LLaVA-style 2-layer GELU projector into the LLM hidden space
    x = jax.nn.gelu(x @ params["proj_w1"] + params["proj_b1"])
    x = x @ params["proj_w2"] + params["proj_b2"]
    return x.astype(jnp.float32)
