"""Llama-family model (Llama 2/3, DeepSeek-R1-Distill-Llama, Qwen2-class
geometries via config).

TPU-first design decisions:
- layer weights stacked on a leading axis and iterated with ``lax.scan`` —
  one compiled layer body regardless of depth (fast compile, small HLO);
- tensor parallelism by sharding annotation only: params carry
  ``PartitionSpec``s over mesh axis ``tp``; XLA/GSPMD inserts the
  all-reduces (no hand-written collectives in the model);
- paged KV cache (``[layers, num_blocks, block_size, kv_heads, head_dim]``)
  threaded through prefill/decode as scan-carried state;
- bf16 params/activations, fp32 softmax/norms.

The reference has no model code (engines own it); this replaces the
vLLM/TRT-LLM model layer for the native TPU engine (SURVEY.md §2.3).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dynamo_tpu.ops.attention import (
    dense_causal_attention,
    gather_prefix_kv,
    paged_decode_attention,
    paged_window_attention,  # noqa: F401 — re-exported for tests
    prefill_attention_with_prefix,
    ragged_paged_attention,
    window_attention,
    write_decode_kv,
    write_prefill_kv,
)
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.quant import mm
from dynamo_tpu.ops.rope import apply_rope, rope_table


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_position_embeddings: int = 131072
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    # qkv projection biases (Qwen2-family geometry; llama proper has none)
    attention_bias: bool = False
    # per-head RMSNorm on q/k after projection, before rope (Qwen3 geometry)
    qk_norm: bool = False
    # HF rope_scaling dict: "linear" | "llama3" | "yarn" (ops/rope.py)
    rope_scaling: Any = None
    # Mistral-style sliding-window attention: each token attends at most
    # the last `sliding_window` positions (None = full attention).  v1
    # keeps all KV blocks resident (correctness first); freeing blocks
    # that scrolled out of the window is a future memory optimization.
    sliding_window: int | None = None
    # MLP gate activation: "silu" (llama/qwen/mistral) or "gelu_tanh"
    # (gemma GeGLU)
    mlp_activation: str = "silu"
    # input-embedding scale (gemma multiplies by sqrt(hidden_size) at the
    # input ONLY — the tied unembedding stays unscaled, so this cannot be
    # baked into the weights)
    embed_scale: float = 1.0
    dtype: Any = jnp.bfloat16

    @classmethod
    def from_hf_config(cls, config: dict | str | Path) -> "LlamaConfig":
        if not isinstance(config, dict):
            config = json.loads(Path(config).read_text())
        heads = config["num_attention_heads"]
        return cls(
            vocab_size=config["vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["intermediate_size"],
            num_layers=config["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=config.get("num_key_value_heads", heads),
            head_dim=config.get("head_dim") or config["hidden_size"] // heads,
            max_position_embeddings=config.get("max_position_embeddings", 4096),
            rms_norm_eps=config.get("rms_norm_eps", 1e-5),
            rope_theta=config.get("rope_theta", 10000.0),
            tie_word_embeddings=config.get("tie_word_embeddings", False),
            attention_bias=config.get("attention_bias", False),
            qk_norm=config.get("qk_norm", config.get("model_type") == "qwen3"),
            rope_scaling=config.get("rope_scaling"),
            # qwen2-family checkpoints ship sliding_window alongside
            # use_sliding_window: false — only honor the window when HF
            # transformers would (otherwise full attention + Pallas kernel)
            sliding_window=cls._resolve_sliding_window(config),
        )

    @staticmethod
    def _resolve_sliding_window(config: dict) -> int | None:
        """Match HF transformers' per-layer window semantics, uniformly.

        qwen2-family configs pair ``sliding_window`` with
        ``use_sliding_window`` and ``max_window_layers``: layers with index
        >= max_window_layers use the window, layers below it use full
        attention.  This model applies ONE attention pattern to every layer
        (the layer body is a single ``lax.scan``), so:
        - use_sliding_window false, or max_window_layers >= num layers
          (no layer windowed): full attention everywhere;
        - max_window_layers <= 0 (every layer windowed), or the key absent
          (mistral-style configs window every layer): uniform window;
        - a genuine mixed split: refuse loudly rather than compute wrong
          logits on the full-attention layers.
        """
        window = config.get("sliding_window") or None
        if window is None or not config.get("use_sliding_window", True):
            return None
        mwl = config.get("max_window_layers")
        if mwl is None or mwl <= 0:
            return window
        if mwl >= config["num_hidden_layers"]:
            return None
        raise NotImplementedError(
            f"per-layer sliding-window split (max_window_layers={mwl} < "
            f"num_hidden_layers={config['num_hidden_layers']}) is not "
            "supported: every layer shares one attention pattern"
        )

    # --- presets (geometries for serving + bench; weights are loaded or
    # random-initialized — no checkpoints ship with the framework) ---------
    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def llama3_70b(cls) -> "LlamaConfig":
        return cls(hidden_size=8192, intermediate_size=28672, num_layers=80, num_heads=64)

    @classmethod
    def llama32_3b(cls) -> "LlamaConfig":
        return cls(
            hidden_size=3072, intermediate_size=8192, num_layers=28, num_heads=24,
            num_kv_heads=8, head_dim=128, rope_theta=500000.0, tie_word_embeddings=True,
        )

    @classmethod
    def llama32_1b(cls) -> "LlamaConfig":
        return cls(
            hidden_size=2048, intermediate_size=8192, num_layers=16, num_heads=32,
            num_kv_heads=8, head_dim=64, rope_theta=500000.0, tie_word_embeddings=True,
        )

    @classmethod
    def tiny(cls, vocab_size: int = 512) -> "LlamaConfig":
        """Test geometry: 2 layers, 4 heads — runs on the CPU mesh."""
        return cls(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128, num_layers=2,
            num_heads=4, num_kv_heads=2, head_dim=16, max_position_embeddings=2048,
            rope_theta=10000.0, tie_word_embeddings=True, dtype=jnp.float32,
        )


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def init_params(cfg: LlamaConfig, rng: jax.Array) -> dict:
    """Random-init parameter pytree (layer-stacked)."""
    keys = jax.random.split(rng, 12)
    h, i, l_ = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    qd, kvd = cfg.num_heads * cfg.head_dim, cfg.num_kv_heads * cfg.head_dim

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(cfg.dtype)

    params = {
        "embed": norm_init(keys[0], (cfg.vocab_size, h), 1.0),
        "final_norm": jnp.ones((h,), cfg.dtype),
        "layers": {
            "attn_norm": jnp.ones((l_, h), cfg.dtype),
            "wq": norm_init(keys[1], (l_, h, qd), h),
            "wk": norm_init(keys[2], (l_, h, kvd), h),
            "wv": norm_init(keys[3], (l_, h, kvd), h),
            "wo": norm_init(keys[4], (l_, qd, h), qd),
            "mlp_norm": jnp.ones((l_, h), cfg.dtype),
            "w_gate": norm_init(keys[5], (l_, h, i), h),
            "w_up": norm_init(keys[6], (l_, h, i), h),
            "w_down": norm_init(keys[7], (l_, i, h), i),
        },
    }
    if cfg.attention_bias:
        params["layers"]["bq"] = jnp.zeros((l_, qd), cfg.dtype)
        params["layers"]["bk"] = jnp.zeros((l_, kvd), cfg.dtype)
        params["layers"]["bv"] = jnp.zeros((l_, kvd), cfg.dtype)
    if cfg.qk_norm:
        params["layers"]["q_norm"] = jnp.ones((l_, cfg.head_dim), cfg.dtype)
        params["layers"]["k_norm"] = jnp.ones((l_, cfg.head_dim), cfg.dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm_init(keys[8], (h, cfg.vocab_size), h)
    return params


def param_specs(cfg: LlamaConfig) -> dict:
    """PartitionSpecs over mesh axes: 'tp' shards heads/vocab within a
    layer, 'pp' shards the stacked layer axis into pipeline stages (a no-op
    on pp=1 meshes).  GSPMD derives the collectives; this is the whole
    TP implementation, and the pipeline runner consumes the same pp-sharded
    leaves via shard_map (parallel/pipeline.py)."""
    specs = {
        "embed": P("tp", None),          # vocab-sharded
        "final_norm": P(None),
        "layers": {
            "attn_norm": P("pp", None),
            "wq": P("pp", None, "tp"),   # head-sharded
            "wk": P("pp", None, "tp"),
            "wv": P("pp", None, "tp"),
            "wo": P("pp", "tp", None),   # row-parallel → all-reduce
            "mlp_norm": P("pp", None),
            "w_gate": P("pp", None, "tp"),
            "w_up": P("pp", None, "tp"),
            "w_down": P("pp", "tp", None),
        },
    }
    if cfg.attention_bias:
        specs["layers"]["bq"] = P("pp", "tp")
        specs["layers"]["bk"] = P("pp", "tp")
        specs["layers"]["bv"] = P("pp", "tp")
    if cfg.qk_norm:
        specs["layers"]["q_norm"] = P("pp", None)
        specs["layers"]["k_norm"] = P("pp", None)
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")  # vocab-sharded logits
    return specs


def kv_cache_spec() -> P:
    """KV cache: layer axis on 'pp' (pipeline stages), kv heads on 'tp'."""
    return P("pp", None, None, "tp", None)


def init_kv_cache(cfg: LlamaConfig, num_blocks: int, block_size: int, dtype=None):
    shape = (cfg.num_layers, num_blocks, block_size, cfg.num_kv_heads, cfg.head_dim)
    dtype = dtype or cfg.dtype
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------


def _embed(params, cfg: LlamaConfig, token_ids) -> jnp.ndarray:
    x = params["embed"][token_ids].astype(cfg.dtype)
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    return x


def _mlp(x, gate, up, down, activation: str = "silu"):
    if activation == "gelu_tanh":  # gemma GeGLU (HF gelu_pytorch_tanh)
        act = jax.nn.gelu(mm(x, gate), approximate=True)
    elif activation == "silu":
        act = jax.nn.silu(mm(x, gate))
    else:
        # a typo'd activation must not silently run silu into wrong logits
        raise ValueError(f"unknown mlp_activation {activation!r}")
    return mm(act * mm(x, up), down)


def _qkv(attn_in, w, cfg: LlamaConfig):
    """Project+bias+head-split (+ Qwen3 per-head q/k RMSNorm, pre-rope);
    shared by prefill/decode/trunk.  Projections run through ``mm`` so
    int8-quantized weights (ops/quant.py) drop in transparently."""
    s = attn_in.shape[0]
    q_proj = mm(attn_in, w["wq"])
    k_proj = mm(attn_in, w["wk"])
    v_proj = mm(attn_in, w["wv"])
    if cfg.attention_bias:
        q_proj, k_proj, v_proj = q_proj + w["bq"], k_proj + w["bk"], v_proj + w["bv"]
    q = q_proj.reshape(s, cfg.num_heads, cfg.head_dim)
    k = k_proj.reshape(s, cfg.num_kv_heads, cfg.head_dim)
    v = v_proj.reshape(s, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, w["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, w["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def llama_forward_trunk(
    params: dict,
    cfg: LlamaConfig,
    token_ids: jnp.ndarray,  # [seq_pad] int32
    seq_len: jnp.ndarray,    # scalar int32
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> jnp.ndarray:
    """Trunk-only forward (no KV cache, no LM head): final hidden states
    [seq_pad, hidden].  Used by the embedding engine."""
    s = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)
    positions = jnp.arange(s, dtype=jnp.int32)

    def layer(x, w):
        attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        attn = dense_causal_attention(
            q[None], k[None], v[None], seq_len[None],
            sliding_window=cfg.sliding_window,
        )[0]
        x = x + mm(attn.reshape(s, -1), w["wo"])
        mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(mlp_in, w["w_gate"], w["w_up"], w["w_down"], cfg.mlp_activation)
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


def _logits(params, cfg, x):
    if cfg.tie_word_embeddings:
        return x @ params["embed"].T.astype(x.dtype)
    return mm(x, params["lm_head"])


def llama_forward_prefill(
    params: dict,
    cfg: LlamaConfig,
    token_ids: jnp.ndarray,   # [seq_pad] int32
    kv_cache: dict,           # {"k","v"}: [L, N, bs, kvh, d]
    block_ids: jnp.ndarray,   # [max_blocks] int32
    seq_len: jnp.ndarray,     # scalar int32: valid tokens
    start_pos: jnp.ndarray,   # scalar int32: absolute position offset (chunked prefill)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    sp_mesh=None,
) -> tuple[jnp.ndarray, dict]:
    """Single-sequence prefill.  Returns (last-token logits [vocab], new cache).

    ``sp_mesh``: a mesh whose ``sp`` axis shards the sequence — prefill
    attention runs as ring attention (ops/ring_attention.py), K/V chunks
    rotating over ICI, enabling prompts beyond one chip's activation memory
    (sequence/context parallelism; the reference has none, SURVEY.md §2.5)."""
    x = _embed(params, cfg, token_ids)  # [s, h]
    return llama_forward_prefill_embeds(
        params, cfg, x, kv_cache, block_ids, seq_len, start_pos, cos, sin,
        sp_mesh=sp_mesh,
    )


def llama_forward_prefill_embeds(
    params: dict,
    cfg: LlamaConfig,
    input_embeds: jnp.ndarray,  # [seq_pad, hidden] — e.g. image patches + text
    kv_cache: dict,
    block_ids: jnp.ndarray,
    seq_len: jnp.ndarray,
    start_pos: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    sp_mesh=None,
) -> tuple[jnp.ndarray, dict]:
    """Prefill from pre-computed input embeddings (multimodal prompts:
    vision-encoder patch embeddings concatenated with text token
    embeddings, LLaVA-style).  ``sp_mesh``: see llama_forward_prefill."""
    s = input_embeds.shape[0]
    x = input_embeds.astype(cfg.dtype)
    positions = start_pos + jnp.arange(s, dtype=jnp.int32)

    if sp_mesh is not None:
        if cfg.sliding_window is not None:
            # ring attention has no sliding-window mask: shards would
            # silently compute full attention (the engine fences this too,
            # but direct model-level callers deserve the same guard)
            raise NotImplementedError(
                "sequence parallelism does not compose with sliding-window "
                "attention: ring attention computes the full causal mask"
            )
        from dynamo_tpu.ops.ring_attention import ring_attention

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        k_layer, v_layer = write_prefill_kv(k_layer, v_layer, k, v, block_ids, seq_len)
        if sp_mesh is not None:
            attn = ring_attention(q[None], k[None], v[None], seq_len, sp_mesh)[0]
        else:
            attn = dense_causal_attention(
                q[None], k[None], v[None], seq_len[None],
                sliding_window=cfg.sliding_window,
            )[0]
        x = x + mm(attn.reshape(s, -1), w["wo"])
        mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(mlp_in, w["w_gate"], w["w_up"], w["w_down"], cfg.mlp_activation)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv_cache["k"], kv_cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    last = x[jnp.maximum(seq_len - 1, 0)]
    logits = _logits(params, cfg, last[None])[0]
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def llama_forward_prefill_with_prefix(
    params: dict,
    cfg: LlamaConfig,
    token_ids: jnp.ndarray,       # [tail_pad] int32 — the uncached tail
    kv_cache: dict,
    full_block_ids: jnp.ndarray,  # [max_blocks] int32 — whole table (prefix+tail)
    tail_block_ids: jnp.ndarray,  # [max_blocks] int32 — table from the first tail block
    tail_len: jnp.ndarray,        # scalar int32: valid tail tokens
    start_pos: jnp.ndarray,       # scalar int32: cached prefix length (block-aligned)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    sp_mesh=None,
) -> tuple[jnp.ndarray, dict]:
    """Continued prefill over a reused prefix: the tail's queries attend to
    the resident prefix KV (gathered from the paged cache) plus themselves,
    and only the tail's K/V are written.  Serves both prefix-cache hits and
    chunked prefill (reference intent: vLLM prefix caching / chunked
    prefill; block reuse lib/llm/src/block_manager/pool.rs:447-466).

    ``sp_mesh``: the tail attends via ring attention over the ``sp`` axis
    while each shard merges the replicated resident prefix into its online
    softmax (ops/ring_attention.ring_attention_with_prefix) — prefix
    caching and chunked prefill compose with sequence parallelism."""
    s = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)
    positions = start_pos + jnp.arange(s, dtype=jnp.int32)

    if sp_mesh is not None:
        if cfg.sliding_window is not None:
            raise NotImplementedError(
                "sequence parallelism does not compose with sliding-window "
                "attention: ring attention computes the full causal mask"
            )
        from dynamo_tpu.ops.ring_attention import ring_attention_with_prefix

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        # gather the resident prefix BEFORE writing the tail (the mask in
        # the attention op drops everything past start_pos anyway)
        k_prefix, v_prefix = gather_prefix_kv(k_layer, v_layer, full_block_ids)
        k_layer, v_layer = write_prefill_kv(k_layer, v_layer, k, v, tail_block_ids, tail_len)
        if sp_mesh is not None:
            attn = ring_attention_with_prefix(
                q[None], k[None], v[None], k_prefix[None], v_prefix[None],
                start_pos, tail_len, sp_mesh,
            )[0]
        else:
            attn = prefill_attention_with_prefix(
                q, k, v, k_prefix, v_prefix, start_pos, tail_len,
                sliding_window=cfg.sliding_window,
            )
        x = x + mm(attn.reshape(s, -1), w["wo"])
        mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(mlp_in, w["w_gate"], w["w_up"], w["w_down"], cfg.mlp_activation)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv_cache["k"], kv_cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    last = x[jnp.maximum(tail_len - 1, 0)]
    logits = _logits(params, cfg, last[None])[0]
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def llama_forward_decode(
    params: dict,
    cfg: LlamaConfig,
    token_ids: jnp.ndarray,     # [batch] int32 — last sampled token per seq
    kv_cache: dict,
    block_tables: jnp.ndarray,  # [batch, max_blocks] int32
    context_lens: jnp.ndarray,  # [batch] int32 length INCLUDING this token
    slot_ids: jnp.ndarray,      # [batch] int32 flat cache slot for this token
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    attention: str = "jax",     # "jax" | "pallas" | "pallas_interpret"
    tp_mesh=None,
) -> tuple[jnp.ndarray, dict]:
    """Batched single-token decode.  Returns (logits [batch, vocab], cache).

    ``attention="pallas"`` uses the Pallas paged-attention kernel (no
    materialized page gather); with ``tp_mesh`` the kernel runs under
    shard_map per tp shard — queries sharded on the head axis, cache on the
    kv-head axis (head order is kv-major, so contiguous head chunks align
    with their kv heads) — and GSPMD handles everything around it.
    "jax" is the portable gather-based fallback.
    """
    b = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)  # [b, h]
    positions = jnp.maximum(context_lens - 1, 0)      # this token's position

    def attend(q, k_layer, v_layer):
        if attention.startswith("pallas"):
            from dynamo_tpu.ops.pallas import paged_attention_decode

            interpret = attention == "pallas_interpret"
            if tp_mesh is not None and tp_mesh.shape.get("tp", 1) > 1:
                kernel = jax.shard_map(
                    lambda q_, k_, v_, bt, cl: paged_attention_decode(
                        q_, k_, v_, bt, cl, interpret=interpret,
                        sliding_window=cfg.sliding_window,
                    ),
                    mesh=tp_mesh,
                    in_specs=(
                        P(None, "tp", None),        # q: heads sharded
                        P(None, None, "tp", None),  # cache: kv heads sharded
                        P(None, None, "tp", None),
                        P(),
                        P(),
                    ),
                    out_specs=P(None, "tp", None),
                    check_vma=False,  # pallas_call outputs carry no vma info
                )
                return kernel(q, k_layer, v_layer, block_tables, context_lens)
            return paged_attention_decode(
                q, k_layer, v_layer, block_tables, context_lens,
                interpret=interpret, sliding_window=cfg.sliding_window,
            )
        return paged_decode_attention(
            q, k_layer, v_layer, block_tables, context_lens,
            sliding_window=cfg.sliding_window,
        )

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(attn_in, w, cfg)
        # apply_rope expects a seq axis: insert and drop it
        q = apply_rope(q[:, None], positions[:, None], cos, sin)[:, 0]
        k = apply_rope(k[:, None], positions[:, None], cos, sin)[:, 0]
        k_layer, v_layer = write_decode_kv(k_layer, v_layer, k, v, slot_ids)
        attn = attend(q, k_layer, v_layer)
        x = x + mm(attn.reshape(b, -1), w["wo"])
        mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(mlp_in, w["w_gate"], w["w_up"], w["w_down"], cfg.mlp_activation)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv_cache["k"], kv_cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _logits(params, cfg, x)
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def llama_forward_unified(
    params: dict,
    cfg: LlamaConfig,
    token_ids: jnp.ndarray,     # [T] int32 — flat ragged token batch
    kv_cache: dict,
    block_tables: jnp.ndarray,  # [lanes, max_blocks] int32
    context_lens: jnp.ndarray,  # [lanes] int32 incl. each lane's span end
    token_pos: jnp.ndarray,     # [T] int32 absolute position (-1 = pad)
    token_slot: jnp.ndarray,    # [T] int32 flat cache slot (OOB = pad)
    token_lane: jnp.ndarray,    # [T] int32 owning lane (OOB = pad)
    page_phys: jnp.ndarray,     # [T // tb_tokens, PS] int32 (pack_page_meta)
    page_lane: jnp.ndarray,     # [T // tb_tokens, PS] int32 owning lane (-1 pad)
    page_ord: jnp.ndarray,      # [T // tb_tokens, PS] int32 page ordinal
    page_count: jnp.ndarray,    # [T // tb_tokens] int32 live worklist entries
    sample_rows: jnp.ndarray,   # [lanes] int32 flat index of span's LAST token
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    attention: str = "jax",     # "jax" | "pallas" | "pallas_interpret"
    tb_tokens: int = 8,
    pages_per_step: int = 1,
) -> tuple[jnp.ndarray, dict]:
    """Ragged unified-batch forward: one launch computes chunked-prefill
    spans AND decode tokens from different sequences, each token at its own
    absolute position (Ragged Paged Attention, arxiv 2604.15464).  Every
    token's K/V scatters into its cache slot like decode, attention reads
    the paged cache per lane (resident prefixes included — this path also
    subsumes the continued-prefill-with-prefix program), and the logits are
    gathered at each lane's LAST span row: [lanes, vocab], one sample row
    per sequence regardless of how many tokens it contributed.  One weight
    stream from HBM serves the whole mixed batch — the dispatch-count win
    that removes the engine's prefill/decode phase split."""
    t = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)  # [t, h]
    positions = jnp.maximum(token_pos, 0)

    def attend(q, k_layer, v_layer):
        if attention.startswith("pallas"):
            from dynamo_tpu.ops.pallas import (
                ragged_paged_attention as ragged_kernel,
            )

            return ragged_kernel(
                q, k_layer, v_layer, token_lane, token_pos,
                page_phys, page_lane, page_ord, page_count,
                tb_tokens=tb_tokens,
                pages_per_step=pages_per_step,
                interpret=attention == "pallas_interpret",
                sliding_window=cfg.sliding_window,
            )
        return ragged_paged_attention(
            q, k_layer, v_layer, block_tables, context_lens, token_lane,
            token_pos, sliding_window=cfg.sliding_window,
        )

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        # every token writes before anyone reads: span tokens see their own
        # in-window predecessors through the cache (pads scatter-drop)
        k_layer, v_layer = write_decode_kv(k_layer, v_layer, k, v, token_slot)
        attn = attend(q, k_layer, v_layer)
        x = x + mm(attn.reshape(t, -1), w["wo"])
        mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(mlp_in, w["w_gate"], w["w_up"], w["w_down"], cfg.mlp_activation)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv_cache["k"], kv_cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    rows = x[sample_rows]  # [lanes, h] — junk for hole lanes, caller-gated
    logits = _logits(params, cfg, rows)
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def llama_forward_verify(
    params: dict,
    cfg: LlamaConfig,
    token_ids: jnp.ndarray,     # [batch, w] int32 — window: last accepted
                                # token then draft tokens
    kv_cache: dict,
    block_tables: jnp.ndarray,  # [batch, max_blocks] int32
    context_lens: jnp.ndarray,  # [batch] int32 INCLUDING the window's last token
    slot_ids: jnp.ndarray,      # [batch, w] int32 flat cache slots per position
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    attention: str = "jax",     # "jax" | "pallas" | "pallas_interpret"
) -> tuple[jnp.ndarray, dict]:
    """Speculative-verification forward: score all w window positions in one
    pass (logits [batch, w, vocab]).  The whole window's K/V is written like
    decode; rejected positions' cache entries are overwritten when the
    sequence continues (slots derive from the accepted length).  One weight
    stream from HBM scores w tokens — the bandwidth economics of
    speculative decoding on TPU.  ``attention="pallas"`` runs the
    multi-query paged kernel (no materialized page gather)."""
    b, w_len = token_ids.shape
    x = _embed(params, cfg, token_ids.reshape(-1))  # [b*w, h]
    positions = jnp.maximum(
        context_lens[:, None] - w_len + jnp.arange(w_len)[None, :], 0
    )  # [b, w]
    flat_slots = slot_ids.reshape(-1)

    def attend(q, k_layer, v_layer):
        return window_attention(
            attention, q, k_layer, v_layer, block_tables, context_lens,
            sliding_window=cfg.sliding_window,
        )

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q.reshape(b, w_len, cfg.num_heads, cfg.head_dim), positions, cos, sin)
        k = apply_rope(k.reshape(b, w_len, cfg.num_kv_heads, cfg.head_dim), positions, cos, sin)
        v = v.reshape(b, w_len, cfg.num_kv_heads, cfg.head_dim)
        k_layer, v_layer = write_decode_kv(
            k_layer, v_layer, k.reshape(b * w_len, cfg.num_kv_heads, cfg.head_dim),
            v.reshape(b * w_len, cfg.num_kv_heads, cfg.head_dim), flat_slots,
        )
        attn = attend(q, k_layer, v_layer)
        x = x + mm(attn.reshape(b * w_len, -1), w["wo"])
        mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(mlp_in, w["w_gate"], w["w_up"], w["w_down"], cfg.mlp_activation)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv_cache["k"], kv_cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _logits(params, cfg, x).reshape(b, w_len, -1)
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def llama_forward_decode_pp(
    params: dict,
    cfg: LlamaConfig,
    token_ids: jnp.ndarray,
    kv_cache: dict,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    slot_ids: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    pp_mesh,
    microbatches: int | None = None,
) -> tuple[jnp.ndarray, dict]:
    """Batched decode with the layer stack pipelined over the ``pp`` mesh
    axis (parallel/pipeline.py): stage s holds layers [s*L/S, (s+1)*L/S)
    and their KV-cache slice; microbatches stream through the stages over
    ICI.  Embedding and the LM head run replicated outside the pipeline.
    Matches llama_forward_decode exactly (same layer body)."""
    b = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)
    positions = jnp.maximum(context_lens - 1, 0)

    def body(x_mb, aux_mb, w, layer_cache):
        k_layer, v_layer = layer_cache
        pos_mb, slots_mb, tables_mb, lens_mb = aux_mb
        attn_in = rms_norm(x_mb, w["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q[:, None], pos_mb[:, None], cos, sin)[:, 0]
        k = apply_rope(k[:, None], pos_mb[:, None], cos, sin)[:, 0]
        k_layer, v_layer = write_decode_kv(k_layer, v_layer, k, v, slots_mb)
        attn = paged_decode_attention(
            q, k_layer, v_layer, tables_mb, lens_mb,
            sliding_window=cfg.sliding_window,
        )
        x_mb = x_mb + mm(attn.reshape(x_mb.shape[0], -1), w["wo"])
        mlp_in = rms_norm(x_mb, w["mlp_norm"], cfg.rms_norm_eps)
        x_mb = x_mb + _mlp(mlp_in, w["w_gate"], w["w_up"], w["w_down"], cfg.mlp_activation)
        return x_mb, (k_layer, v_layer)

    from dynamo_tpu.parallel.pipeline import pipeline_layer_stack

    x, (new_k, new_v) = pipeline_layer_stack(
        body, x, (positions, slot_ids, block_tables, context_lens),
        params["layers"], (kv_cache["k"], kv_cache["v"]), pp_mesh,
        microbatches=microbatches,
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _logits(params, cfg, x)
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def phi3_config_from_hf(config: dict | str | Path) -> LlamaConfig:
    """Phi-3 = llama math with FUSED checkpoint tensors (qkv_proj,
    gate_up_proj — split in phi3_load_hf_weights) and an always-on
    sliding window.  The 128k 'longrope' variants are refused loudly:
    ops/rope.py has no longrope schedule yet."""
    if not isinstance(config, dict):
        config = json.loads(Path(config).read_text())
    scaling = config.get("rope_scaling") or {}
    kind = scaling.get("rope_type") or scaling.get("type")
    if kind in ("longrope", "su"):
        raise NotImplementedError(
            "phi3 longrope scaling is not implemented; the 4k-context "
            "variants (rope_scaling: null) are supported"
        )
    return LlamaConfig.from_hf_config(config)


def phi3_load_hf_weights(cfg: LlamaConfig, model_dir: str | Path) -> dict:
    """Split Phi-3's fused qkv_proj [q+k+v, h] and gate_up_proj [2i, h]
    into the standard per-projection names, then delegate to the base
    loader — the stacking/transpose/tie logic must not fork."""
    from dynamo_tpu.models.hf_io import read_safetensors

    tensors = dict(read_safetensors(model_dir))
    qd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    inter = cfg.intermediate_size
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}"
        qkv = tensors.pop(f"{p}.self_attn.qkv_proj.weight")
        tensors[f"{p}.self_attn.q_proj.weight"] = qkv[:qd]
        tensors[f"{p}.self_attn.k_proj.weight"] = qkv[qd : qd + kvd]
        tensors[f"{p}.self_attn.v_proj.weight"] = qkv[qd + kvd :]
        gate_up = tensors.pop(f"{p}.mlp.gate_up_proj.weight")
        tensors[f"{p}.mlp.gate_proj.weight"] = gate_up[:inter]
        tensors[f"{p}.mlp.up_proj.weight"] = gate_up[inter:]
    return load_hf_weights(cfg, model_dir, tensors=tensors)


def gemma_config_from_hf(config: dict | str | Path) -> LlamaConfig:
    """Gemma-1 = llama skeleton + GeGLU MLP, sqrt(hidden) input-embedding
    scale, and (1+w) RMSNorm weights (baked at load time,
    gemma_load_hf_weights).  Gemma always ties embeddings."""
    if not isinstance(config, dict):
        config = json.loads(Path(config).read_text())
    act = config.get("hidden_activation") or config.get("hidden_act") or "gelu_pytorch_tanh"
    if act not in ("gelu", "gelu_pytorch_tanh"):
        raise ValueError(f"unexpected gemma activation {act!r}")
    # delegate the shared fields (rope scaling, windows, biases, defaults)
    # and override only the gemma deltas — a field added to from_hf_config
    # must not silently go missing here
    import dataclasses

    return dataclasses.replace(
        LlamaConfig.from_hf_config(config),
        tie_word_embeddings=True,
        mlp_activation="gelu_tanh",
        embed_scale=float(config["hidden_size"]) ** 0.5,
    )


def gemma_load_hf_weights(cfg: LlamaConfig, model_dir: str | Path) -> dict:
    """Gemma checkpoints store RMSNorm weights as w with runtime (1 + w):
    bake the +1 in once so every forward path runs unchanged."""
    params = load_hf_weights(cfg, model_dir)
    plus_one = lambda t: (t.astype(jnp.float32) + 1.0).astype(t.dtype)  # noqa: E731
    layers = dict(params["layers"])
    layers["attn_norm"] = plus_one(layers["attn_norm"])
    layers["mlp_norm"] = plus_one(layers["mlp_norm"])
    return {**params, "layers": layers, "final_norm": plus_one(params["final_norm"])}


def make_rope_tables(cfg: LlamaConfig) -> tuple[jnp.ndarray, jnp.ndarray]:
    return rope_table(
        cfg.max_position_embeddings, cfg.head_dim, cfg.rope_theta,
        scaling=cfg.rope_scaling,
    )


# ---------------------------------------------------------------------------
# HF weight loading (safetensors) — for real checkpoints when present
# ---------------------------------------------------------------------------

_HF_LAYER_MAP = {
    "attn_norm": "model.layers.{i}.input_layernorm.weight",
    "wq": "model.layers.{i}.self_attn.q_proj.weight",
    "wk": "model.layers.{i}.self_attn.k_proj.weight",
    "wv": "model.layers.{i}.self_attn.v_proj.weight",
    "wo": "model.layers.{i}.self_attn.o_proj.weight",
    "mlp_norm": "model.layers.{i}.post_attention_layernorm.weight",
    "w_gate": "model.layers.{i}.mlp.gate_proj.weight",
    "w_up": "model.layers.{i}.mlp.up_proj.weight",
    "w_down": "model.layers.{i}.mlp.down_proj.weight",
}


def load_hf_weights(
    cfg: LlamaConfig, model_dir: str | Path, *, tensors: dict | None = None
) -> dict:
    """Load and stack HF llama safetensors into our layer-stacked pytree.
    (HF stores projections as [out, in]; ours are [in, out] → transpose.)
    ``tensors`` overrides the on-disk read for loaders that pre-process the
    checkpoint (phi3 splits its fused tensors, then delegates here)."""
    if tensors is None:
        from dynamo_tpu.models.hf_io import read_safetensors

        tensors = read_safetensors(model_dir)

    def get(name: str, transpose: bool = False):
        t = tensors[name]
        if transpose:
            t = t.T
        return jnp.asarray(t, cfg.dtype)

    layer_map = dict(_HF_LAYER_MAP)
    if cfg.attention_bias:
        layer_map.update(
            bq="model.layers.{i}.self_attn.q_proj.bias",
            bk="model.layers.{i}.self_attn.k_proj.bias",
            bv="model.layers.{i}.self_attn.v_proj.bias",
        )
    if cfg.qk_norm:
        layer_map.update(
            q_norm="model.layers.{i}.self_attn.q_norm.weight",
            k_norm="model.layers.{i}.self_attn.k_norm.weight",
        )
    layers: dict[str, list] = {k: [] for k in layer_map}
    for i in range(cfg.num_layers):
        for ours, theirs in layer_map.items():
            transpose = ours.startswith("w")
            layers[ours].append(get(theirs.format(i=i), transpose))
    params = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.norm.weight"),
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
    }
    if not cfg.tie_word_embeddings and "lm_head.weight" in tensors:
        params["lm_head"] = get("lm_head.weight", transpose=True)
    return params
