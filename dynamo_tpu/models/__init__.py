"""JAX model definitions, built TPU-first.

Functional param-pytree models (no framework state): stacked layer weights
scanned with ``lax.scan`` for fast compiles, PartitionSpec sharding for
pjit/GSPMD tensor parallelism, paged KV cache threaded through the forwards.
"""

from dynamo_tpu.models.llama import LlamaConfig, llama_forward_decode, llama_forward_prefill

__all__ = ["LlamaConfig", "llama_forward_decode", "llama_forward_prefill"]
