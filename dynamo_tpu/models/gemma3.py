"""Gemma-3 text model family (TPU-first, layer-scanned).

Builds on the Gemma-2 machinery (sandwich norms, GeGLU, sqrt(hidden)
embed scale, (1+w) RMSNorm, per-layer traced attention windows through
one ``lax.scan``) with Gemma-3's changes:

- **5:1 local/global pattern**: five sliding-window layers then one
  full-attention layer (HF ``layer_types``), vs Gemma-2's 1:1.
- **Dual rope bases**: local layers use ``rope_local_base_freq`` (10k),
  global layers ``rope_theta`` (1M, optionally ``rope_scaling``-stretched
  on long-context checkpoints).  The engine threads ONE (cos, sin) pair
  sliced to ``[:max_len]``, so both tables pack along the feature axis
  ([max_len, head_dim] = local_half ++ global_half) and each scanned
  layer selects its half by a per-layer flag.
- **Per-head q/k RMSNorm** ((1 + w) convention, baked at load) instead of
  Gemma-2's logit soft-capping (no attn or final capping).

Multimodal Gemma-3 checkpoints (``model_type: gemma3`` with a nested
``text_config`` + vision tower) parse their text config.  The family
ships ``forward_prefill_embeds`` (LLaVA-style embedding splicing), so
the engine's multimodal path can feed it encoder output — the generic
ViT tower in ``models/vision.py`` works today; Gemma's own SigLIP tower
weights are not loaded (a checkpoint's vision half is ignored).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp

from dynamo_tpu.models.gemma2 import _geglu
from dynamo_tpu.ops.attention import (
    dense_causal_attention,
    gather_prefix_kv,
    paged_decode_attention,
    prefill_attention_with_prefix,
    window_attention,
    write_decode_kv,
    write_prefill_kv,
)
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.quant import mm
from dynamo_tpu.ops.rope import apply_rope, rope_table

P = jax.sharding.PartitionSpec


@dataclass(frozen=True)
class Gemma3Config:
    vocab_size: int = 262208
    hidden_size: int = 2560
    intermediate_size: int = 10240
    num_layers: int = 34
    num_heads: int = 8
    num_kv_heads: int = 4
    head_dim: int = 256
    max_position_embeddings: int = 131072
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1e6           # global layers
    rope_local_theta: float = 10000.0  # sliding layers
    rope_scaling: Any = None           # applies to the GLOBAL table only
    sliding_window: int = 4096
    query_pre_attn_scalar: float = 256.0
    # per-layer pattern: True = full attention (HF layer_types); default
    # built by __post_init__ as every 6th layer global
    global_layers: tuple = field(default=())
    tie_word_embeddings: bool = True
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if not self.global_layers:
            object.__setattr__(
                self, "global_layers",
                tuple((i + 1) % 6 == 0 for i in range(self.num_layers)),
            )
        if len(self.global_layers) != self.num_layers:
            raise ValueError(
                f"global_layers has {len(self.global_layers)} entries for "
                f"{self.num_layers} layers"
            )

    @property
    def embed_scale(self) -> float:
        return float(self.hidden_size) ** 0.5

    def layer_windows(self) -> jnp.ndarray:
        """Per-layer attention window, int32 [L]: 0 (= full) on global
        layers, the sliding window elsewhere."""
        flags = jnp.asarray(self.global_layers, bool)
        return jnp.where(flags, 0, jnp.int32(self.sliding_window))

    def layer_global_flags(self) -> jnp.ndarray:
        return jnp.asarray(self.global_layers, bool)

    @classmethod
    def from_hf_config(cls, config: dict | str | Path) -> "Gemma3Config":
        if not isinstance(config, dict):
            config = json.loads(Path(config).read_text())
        if "text_config" in config:  # multimodal wrapper (model_type gemma3)
            config = config["text_config"]
        heads = config.get("num_attention_heads", 8)
        layer_types = config.get("layer_types")
        n_layers = config["num_hidden_layers"]
        global_layers = (
            tuple(t == "full_attention" for t in layer_types)
            if layer_types else ()
        )
        return cls(
            vocab_size=config["vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["intermediate_size"],
            num_layers=n_layers,
            num_heads=heads,
            num_kv_heads=config.get("num_key_value_heads", heads),
            head_dim=config.get("head_dim") or config["hidden_size"] // heads,
            max_position_embeddings=config.get(
                "max_position_embeddings", 131072
            ),
            rms_norm_eps=config.get("rms_norm_eps", 1e-6),
            rope_theta=config.get("rope_theta", 1e6),
            rope_local_theta=config.get("rope_local_base_freq", 10000.0),
            rope_scaling=config.get("rope_scaling"),
            sliding_window=config.get("sliding_window", 4096),
            query_pre_attn_scalar=float(
                config.get("query_pre_attn_scalar")
                or config["hidden_size"] // heads
            ),
            global_layers=global_layers,
        )

    @classmethod
    def tiny(cls) -> "Gemma3Config":
        """Test geometry: 7 layers so the 5:1 pattern includes one global
        layer (index 5) plus two more local ones."""
        return cls(
            vocab_size=480, hidden_size=64, intermediate_size=128,
            num_layers=7, num_heads=4, num_kv_heads=2, head_dim=16,
            max_position_embeddings=128, sliding_window=8,
            query_pre_attn_scalar=16.0,
        )


def init_params(cfg: Gemma3Config, rng: jax.Array) -> dict:
    keys = jax.random.split(rng, 9)
    h, i, l_ = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    qd, kvd = cfg.num_heads * cfg.head_dim, cfg.num_kv_heads * cfg.head_dim

    def norm_init(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(cfg.dtype)

    return {
        "embed": norm_init(keys[0], (cfg.vocab_size, h), 1.0),
        "final_norm": jnp.ones((h,), cfg.dtype),
        "layers": {
            "attn_norm": jnp.ones((l_, h), cfg.dtype),
            "post_attn_norm": jnp.ones((l_, h), cfg.dtype),
            "mlp_norm": jnp.ones((l_, h), cfg.dtype),
            "post_mlp_norm": jnp.ones((l_, h), cfg.dtype),
            "q_norm": jnp.ones((l_, cfg.head_dim), cfg.dtype),
            "k_norm": jnp.ones((l_, cfg.head_dim), cfg.dtype),
            "wq": norm_init(keys[1], (l_, h, qd), h),
            "wk": norm_init(keys[2], (l_, h, kvd), h),
            "wv": norm_init(keys[3], (l_, h, kvd), h),
            "wo": norm_init(keys[4], (l_, qd, h), qd),
            "w_gate": norm_init(keys[5], (l_, h, i), h),
            "w_up": norm_init(keys[6], (l_, h, i), h),
            "w_down": norm_init(keys[7], (l_, i, h), i),
        },
    }


def param_specs(cfg: Gemma3Config) -> dict:
    norm = P("pp", None)
    return {
        "embed": P(None, None),
        "final_norm": P(None),
        "layers": {
            "attn_norm": norm, "post_attn_norm": norm,
            "mlp_norm": norm, "post_mlp_norm": norm,
            "q_norm": norm, "k_norm": norm,
            "wq": P("pp", None, "tp"), "wk": P("pp", None, "tp"),
            "wv": P("pp", None, "tp"), "wo": P("pp", "tp", None),
            "w_gate": P("pp", None, "tp"), "w_up": P("pp", None, "tp"),
            "w_down": P("pp", "tp", None),
        },
    }


def make_rope_tables(cfg: Gemma3Config):
    """Both bases packed along the feature axis: [max_pos, head_dim] =
    local half ++ global half (each [max_pos, head_dim//2]).  The engine
    slices positions ([:max_len]) without knowing about the packing;
    layers split and select their half (see _rope_halves)."""
    cos_l, sin_l = rope_table(
        cfg.max_position_embeddings, cfg.head_dim, cfg.rope_local_theta
    )
    cos_g, sin_g = rope_table(
        cfg.max_position_embeddings, cfg.head_dim, cfg.rope_theta,
        scaling=cfg.rope_scaling,
    )
    return (
        jnp.concatenate([cos_l, cos_g], axis=-1),
        jnp.concatenate([sin_l, sin_g], axis=-1),
    )


def _rope_halves(cos, sin, is_global):
    """Select a layer's (cos, sin) from the packed dual tables by the
    traced per-layer flag."""
    half = cos.shape[-1] // 2
    c = jnp.where(is_global, cos[..., half:], cos[..., :half])
    s = jnp.where(is_global, sin[..., half:], sin[..., :half])
    return c, s


def _embed(params, cfg: Gemma3Config, token_ids) -> jnp.ndarray:
    x = params["embed"][token_ids].astype(cfg.dtype)
    return x * jnp.asarray(cfg.embed_scale, cfg.dtype)


def _qkv(attn_in, w, cfg: Gemma3Config):
    s = attn_in.shape[0]
    q = mm(attn_in, w["wq"]).reshape(s, cfg.num_heads, cfg.head_dim)
    k = mm(attn_in, w["wk"]).reshape(s, cfg.num_kv_heads, cfg.head_dim)
    v = mm(attn_in, w["wv"]).reshape(s, cfg.num_kv_heads, cfg.head_dim)
    q = rms_norm(q, w["q_norm"], cfg.rms_norm_eps)
    k = rms_norm(k, w["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _final_logits(params, cfg: Gemma3Config, x) -> jnp.ndarray:
    return (x @ params["embed"].T.astype(x.dtype)).astype(jnp.float32)


def _attn_kwargs(cfg: Gemma3Config, window) -> dict:
    return {
        "sliding_window": window,
        "query_scale": float(cfg.query_pre_attn_scalar) ** -0.5,
    }


def _scan_xs(cfg: Gemma3Config, params: dict, kv_cache: dict):
    return (
        params["layers"], cfg.layer_windows(), cfg.layer_global_flags(),
        kv_cache["k"], kv_cache["v"],
    )


def gemma3_forward_prefill(
    params: dict,
    cfg: Gemma3Config,
    token_ids: jnp.ndarray,   # [seq_pad] int32
    kv_cache: dict,           # {"k","v"}: [L, N, bs, kvh, d]
    block_ids: jnp.ndarray,   # [max_blocks] int32
    seq_len: jnp.ndarray,     # scalar int32
    start_pos: jnp.ndarray,   # scalar int32
    cos: jnp.ndarray,         # packed dual tables (make_rope_tables)
    sin: jnp.ndarray,
) -> tuple[jnp.ndarray, dict]:
    return gemma3_forward_prefill_embeds(
        params, cfg, _embed(params, cfg, token_ids), kv_cache, block_ids,
        seq_len, start_pos, cos, sin,
    )


def gemma3_forward_prefill_embeds(
    params: dict,
    cfg: Gemma3Config,
    input_embeds: jnp.ndarray,  # [seq_pad, hidden] — pre-computed (vision
                                # patches + text embeds via the family's
                                # embed hook, which applies the sqrt scale)
    kv_cache: dict,
    block_ids: jnp.ndarray,
    seq_len: jnp.ndarray,
    start_pos: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> tuple[jnp.ndarray, dict]:
    """Prefill from pre-computed input embeddings (LLaVA-style splicing —
    contract of llama_forward_prefill_embeds)."""
    s = input_embeds.shape[0]
    x = input_embeds.astype(cfg.dtype)
    positions = start_pos + jnp.arange(s, dtype=jnp.int32)
    eps = cfg.rms_norm_eps

    def layer(x, layer_in):
        w, window, is_global, k_layer, v_layer = layer_in
        c, si = _rope_halves(cos, sin, is_global)
        attn_in = rms_norm(x, w["attn_norm"], eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, c, si)
        k = apply_rope(k, positions, c, si)
        k_layer, v_layer = write_prefill_kv(
            k_layer, v_layer, k, v, block_ids, seq_len
        )
        attn = dense_causal_attention(
            q[None], k[None], v[None], seq_len[None],
            **_attn_kwargs(cfg, window),
        )[0]
        attn = mm(attn.reshape(s, -1), w["wo"])
        x = x + rms_norm(attn, w["post_attn_norm"], eps)
        mlp = _geglu(rms_norm(x, w["mlp_norm"], eps), w)
        x = x + rms_norm(mlp, w["post_mlp_norm"], eps)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(layer, x, _scan_xs(cfg, params, kv_cache))
    x = rms_norm(x, params["final_norm"], eps)
    last = x[jnp.maximum(seq_len - 1, 0)]
    logits = _final_logits(params, cfg, last[None])[0]
    return logits, {"k": new_k, "v": new_v}


def gemma3_forward_prefill_with_prefix(
    params: dict,
    cfg: Gemma3Config,
    token_ids: jnp.ndarray,
    kv_cache: dict,
    full_block_ids: jnp.ndarray,
    tail_block_ids: jnp.ndarray,
    tail_len: jnp.ndarray,
    start_pos: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> tuple[jnp.ndarray, dict]:
    s = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)
    positions = start_pos + jnp.arange(s, dtype=jnp.int32)
    eps = cfg.rms_norm_eps

    def layer(x, layer_in):
        w, window, is_global, k_layer, v_layer = layer_in
        c, si = _rope_halves(cos, sin, is_global)
        attn_in = rms_norm(x, w["attn_norm"], eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, c, si)
        k = apply_rope(k, positions, c, si)
        k_prefix, v_prefix = gather_prefix_kv(k_layer, v_layer, full_block_ids)
        k_layer, v_layer = write_prefill_kv(
            k_layer, v_layer, k, v, tail_block_ids, tail_len
        )
        attn = prefill_attention_with_prefix(
            q, k, v, k_prefix, v_prefix, start_pos, tail_len,
            **_attn_kwargs(cfg, window),
        )
        attn = mm(attn.reshape(s, -1), w["wo"])
        x = x + rms_norm(attn, w["post_attn_norm"], eps)
        mlp = _geglu(rms_norm(x, w["mlp_norm"], eps), w)
        x = x + rms_norm(mlp, w["post_mlp_norm"], eps)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(layer, x, _scan_xs(cfg, params, kv_cache))
    x = rms_norm(x, params["final_norm"], eps)
    last = x[jnp.maximum(tail_len - 1, 0)]
    logits = _final_logits(params, cfg, last[None])[0]
    return logits, {"k": new_k, "v": new_v}


def gemma3_forward_decode(
    params: dict,
    cfg: Gemma3Config,
    token_ids: jnp.ndarray,
    kv_cache: dict,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    slot_ids: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    *,
    attention: str = "jax",  # engine compat; JAX path regardless (no
                             # per-layer window plumbing in the kernel)
) -> tuple[jnp.ndarray, dict]:
    del attention
    b = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)
    positions = jnp.maximum(context_lens - 1, 0)
    eps = cfg.rms_norm_eps

    def layer(x, layer_in):
        w, window, is_global, k_layer, v_layer = layer_in
        c, si = _rope_halves(cos, sin, is_global)
        attn_in = rms_norm(x, w["attn_norm"], eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(q, positions, c, si)
        k = apply_rope(k, positions, c, si)
        k_layer, v_layer = write_decode_kv(k_layer, v_layer, k, v, slot_ids)
        attn = paged_decode_attention(
            q, k_layer, v_layer, block_tables, context_lens,
            **_attn_kwargs(cfg, window),
        )
        attn = mm(attn.reshape(b, -1), w["wo"])
        x = x + rms_norm(attn, w["post_attn_norm"], eps)
        mlp = _geglu(rms_norm(x, w["mlp_norm"], eps), w)
        x = x + rms_norm(mlp, w["post_mlp_norm"], eps)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(layer, x, _scan_xs(cfg, params, kv_cache))
    x = rms_norm(x, params["final_norm"], eps)
    logits = _final_logits(params, cfg, x)
    return logits, {"k": new_k, "v": new_v}


def gemma3_forward_verify(
    params: dict,
    cfg: Gemma3Config,
    token_ids: jnp.ndarray,     # [batch, w] int32
    kv_cache: dict,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    slot_ids: jnp.ndarray,      # [batch, w] int32
    cos: jnp.ndarray,           # packed dual tables
    sin: jnp.ndarray,
    *,
    attention: str = "jax",
) -> tuple[jnp.ndarray, dict]:
    """Speculative-verification forward (contract of llama_forward_verify):
    per-layer traced windows and dual-base rope through the verify window."""
    b, w_len = token_ids.shape
    x = _embed(params, cfg, token_ids.reshape(-1))
    positions = jnp.maximum(
        context_lens[:, None] - w_len + jnp.arange(w_len)[None, :], 0
    )
    flat_slots = slot_ids.reshape(-1)
    eps = cfg.rms_norm_eps

    def layer(x, layer_in):
        w, window, is_global, k_layer, v_layer = layer_in
        c, si = _rope_halves(cos, sin, is_global)
        attn_in = rms_norm(x, w["attn_norm"], eps)
        q, k, v = _qkv(attn_in, w, cfg)
        q = apply_rope(
            q.reshape(b, w_len, cfg.num_heads, cfg.head_dim), positions, c, si
        )
        k = apply_rope(
            k.reshape(b, w_len, cfg.num_kv_heads, cfg.head_dim), positions,
            c, si,
        )
        v = v.reshape(b, w_len, cfg.num_kv_heads, cfg.head_dim)
        k_layer, v_layer = write_decode_kv(
            k_layer, v_layer,
            k.reshape(b * w_len, cfg.num_kv_heads, cfg.head_dim),
            v.reshape(b * w_len, cfg.num_kv_heads, cfg.head_dim), flat_slots,
        )
        attn = window_attention(
            "jax", q, k_layer, v_layer, block_tables, context_lens,
            **_attn_kwargs(cfg, window),
        )
        x = x + rms_norm(
            mm(attn.reshape(b * w_len, -1), w["wo"]), w["post_attn_norm"], eps
        )
        mlp = _geglu(rms_norm(x, w["mlp_norm"], eps), w)
        x = x + rms_norm(mlp, w["post_mlp_norm"], eps)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(layer, x, _scan_xs(cfg, params, kv_cache))
    x = rms_norm(x, params["final_norm"], eps)
    logits = _final_logits(params, cfg, x).reshape(b, w_len, -1)
    return logits, {"k": new_k, "v": new_v}


# ---------------------------------------------------------------------------
# HF weight loading
# ---------------------------------------------------------------------------

_HF_LAYER_MAP = {
    "attn_norm": "model.layers.{i}.input_layernorm.weight",
    "post_attn_norm": "model.layers.{i}.post_attention_layernorm.weight",
    "mlp_norm": "model.layers.{i}.pre_feedforward_layernorm.weight",
    "post_mlp_norm": "model.layers.{i}.post_feedforward_layernorm.weight",
    "q_norm": "model.layers.{i}.self_attn.q_norm.weight",
    "k_norm": "model.layers.{i}.self_attn.k_norm.weight",
    "wq": "model.layers.{i}.self_attn.q_proj.weight",
    "wk": "model.layers.{i}.self_attn.k_proj.weight",
    "wv": "model.layers.{i}.self_attn.v_proj.weight",
    "wo": "model.layers.{i}.self_attn.o_proj.weight",
    "w_gate": "model.layers.{i}.mlp.gate_proj.weight",
    "w_up": "model.layers.{i}.mlp.up_proj.weight",
    "w_down": "model.layers.{i}.mlp.down_proj.weight",
}

_NORM_LEAVES = (
    "attn_norm", "post_attn_norm", "mlp_norm", "post_mlp_norm",
    "q_norm", "k_norm",
)


def load_hf_weights(cfg: Gemma3Config, model_dir: str | Path, *,
                    tensors: dict | None = None) -> dict:
    """(1 + w) RMSNorm baking incl. the per-head q/k norms; refuses untied
    unembeddings (same rationale as gemma2)."""
    if tensors is None:
        from dynamo_tpu.models.hf_io import read_safetensors

        tensors = read_safetensors(model_dir)
    # untied-unembedding guard BEFORE any remap filters tensors away: a
    # trained lm_head silently mis-projected through the tied embedding
    # would corrupt every logit with no diagnostic (all spellings: plain
    # text checkpoint, multimodal legacy, multimodal state_dict naming)
    for head in ("lm_head.weight", "language_model.lm_head.weight",
                 "model.language_model.lm_head.weight"):
        if head in tensors:
            raise ValueError(
                f"gemma3 checkpoint ships {head} (untied unembedding); "
                "this family implements the tied projection only"
            )
    if "model.embed_tokens.weight" not in tensors:
        # multimodal checkpoint (Gemma3ForConditionalGeneration): the text
        # half lives under a language_model prefix — serialized as
        # language_model.model.* (save_pretrained legacy mapping) or
        # model.language_model.* (state_dict naming).  Remap to the text
        # layout and drop the vision tower (not loaded by this family).
        for prefix in ("language_model.model.", "model.language_model."):
            if prefix + "embed_tokens.weight" in tensors:
                tensors = {
                    "model." + name[len(prefix):]: t
                    for name, t in tensors.items()
                    if name.startswith(prefix)
                }
                break

    def get(name: str, transpose: bool = False):
        t = tensors[name]
        if transpose:
            t = t.T
        return jnp.asarray(t, cfg.dtype)

    plus_one = lambda t: (t.astype(jnp.float32) + 1.0).astype(t.dtype)  # noqa: E731
    layers: dict[str, list] = {k: [] for k in _HF_LAYER_MAP}
    for i in range(cfg.num_layers):
        for ours, theirs in _HF_LAYER_MAP.items():
            t = get(theirs.format(i=i), transpose=ours.startswith("w"))
            if ours in _NORM_LEAVES:
                t = plus_one(t)
            layers[ours].append(t)
    return {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": plus_one(get("model.norm.weight")),
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
    }
