"""Mixtral-class sparse-MoE model (Mixtral 8x7B geometry and kin).

Same attention trunk as the llama family; the dense MLP is replaced by a
top-2-of-E MoE (dynamo_tpu/ops/moe.py).  Expert parallelism is sharding
annotation only: expert-stacked weights carry ``P(None, "ep", ...)`` and
GSPMD emits the dispatch/combine all-to-alls over ICI.

(The reference serves wide-EP MoE through SGLang+DeepEP —
examples/sglang/README.md:105; here the MoE engine is native.)
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.ops.attention import (
    dense_causal_attention,
    gather_prefix_kv,
    paged_decode_attention,
    position_major_to_batch,
    prefill_attention_with_prefix,
    ragged_paged_attention,
    window_attention,
    write_decode_kv,
    write_prefill_kv,
)
from dynamo_tpu.ops.moe import moe_ffn
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.quant import mm
from dynamo_tpu.ops.rope import apply_rope


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 2.0
    # expert FFN width; 0 = same as intermediate_size (Mixtral proper).
    # Qwen3-MoE configs carry a distinct moe_intermediate_size.
    moe_intermediate_size: int = 0
    # renormalize top-k router weights (Mixtral yes; some Qwen3-MoE
    # variants disable it)
    norm_topk_prob: bool = True

    def __post_init__(self):
        # inherited field from LlamaConfig that NO mixtral-family forward
        # honors (prefill/decode/verify all run full attention) — refuse
        # rather than silently ignoring the window; from_hf_config parses
        # the HF window fields specifically so this fires on checkpoints
        if self.sliding_window is not None:
            raise NotImplementedError(
                "mixtral-family attention has no sliding-window mask"
            )

    @property
    def expert_intermediate_size(self) -> int:
        return self.moe_intermediate_size or self.intermediate_size

    @classmethod
    def mixtral_8x7b(cls) -> "MixtralConfig":
        return cls(
            vocab_size=32_000, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
            max_position_embeddings=32768, rope_theta=1e6,
            num_experts=8, experts_per_token=2,
        )

    @classmethod
    def tiny_moe(cls, vocab_size: int = 512) -> "MixtralConfig":
        return cls(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=96,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            max_position_embeddings=2048, rope_theta=10000.0,
            tie_word_embeddings=True, dtype=jnp.float32,
            num_experts=4, experts_per_token=2, capacity_factor=4.0,
        )

    @classmethod
    def from_hf_config(cls, config: dict | str | Path) -> "MixtralConfig":
        if not isinstance(config, dict):
            config = json.loads(Path(config).read_text())
        heads = config["num_attention_heads"]
        return cls(
            vocab_size=config["vocab_size"],
            hidden_size=config["hidden_size"],
            intermediate_size=config["intermediate_size"],
            num_layers=config["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=config.get("num_key_value_heads", heads),
            head_dim=config.get("head_dim") or config["hidden_size"] // heads,
            max_position_embeddings=config.get("max_position_embeddings", 4096),
            rms_norm_eps=config.get("rms_norm_eps", 1e-5),
            rope_theta=config.get("rope_theta", 1e6),
            num_experts=config.get("num_local_experts", 0)
            or config.get("num_experts", 8),
            experts_per_token=config.get("num_experts_per_tok", 2),
            moe_intermediate_size=config.get("moe_intermediate_size", 0) or 0,
            norm_topk_prob=config.get("norm_topk_prob", True),
            tie_word_embeddings=config.get("tie_word_embeddings", False),
            rope_scaling=config.get("rope_scaling"),
            qk_norm=config.get(
                "qk_norm", config.get("model_type") == "qwen3_moe"
            ),
            # parsed with HF's use_sliding_window/max_window_layers
            # semantics; a genuinely-windowed MoE checkpoint then hits the
            # __post_init__ refusal instead of silently running full
            # attention
            sliding_window=cls._resolve_sliding_window(config),
        )


def init_params(cfg: MixtralConfig, rng: jax.Array) -> dict:
    keys = jax.random.split(rng, 12)
    h, i, l_, e = (
        cfg.hidden_size, cfg.expert_intermediate_size, cfg.num_layers, cfg.num_experts
    )
    qd, kvd = cfg.num_heads * cfg.head_dim, cfg.num_kv_heads * cfg.head_dim

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(cfg.dtype)

    params = {
        "embed": norm_init(keys[0], (cfg.vocab_size, h), 1.0),
        "final_norm": jnp.ones((h,), cfg.dtype),
        "layers": {
            "attn_norm": jnp.ones((l_, h), cfg.dtype),
            "wq": norm_init(keys[1], (l_, h, qd), h),
            "wk": norm_init(keys[2], (l_, h, kvd), h),
            "wv": norm_init(keys[3], (l_, h, kvd), h),
            "wo": norm_init(keys[4], (l_, qd, h), qd),
            "mlp_norm": jnp.ones((l_, h), cfg.dtype),
            "w_router": norm_init(keys[5], (l_, h, e), h),
            "w_gate": norm_init(keys[6], (l_, e, h, i), h),
            "w_up": norm_init(keys[7], (l_, e, h, i), h),
            "w_down": norm_init(keys[8], (l_, e, i, h), i),
        },
    }
    if cfg.qk_norm:
        params["layers"]["q_norm"] = jnp.ones((l_, cfg.head_dim), cfg.dtype)
        params["layers"]["k_norm"] = jnp.ones((l_, cfg.head_dim), cfg.dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm_init(keys[9], (h, cfg.vocab_size), h)
    return params


def param_specs(cfg: MixtralConfig) -> dict:
    """Experts sharded over 'ep'; within-expert FFN dims over 'tp'; attention
    head-sharded over 'tp' as in the llama family."""
    specs = {
        "embed": P(None, None),
        "final_norm": P(None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(None, None),
            "w_router": P(None, None, None),
            "w_gate": P(None, "ep", None, "tp"),
            "w_up": P(None, "ep", None, "tp"),
            "w_down": P(None, "ep", "tp", None),
        },
    }
    if cfg.qk_norm:
        specs["layers"]["q_norm"] = P(None, None)
        specs["layers"]["k_norm"] = P(None, None)
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def _block(cfg: MixtralConfig, w, x, attn_fn, *, capacity_scale: float = 1.0):
    # capacity_scale: callers that split the batch before routing (the
    # pp-pipelined decode routes per MICROBATCH) scale the factor back up
    # so per-expert capacity matches what full-batch routing would allocate
    attn_in = rms_norm(x, w["attn_norm"], cfg.rms_norm_eps)
    x = x + attn_fn(attn_in)
    mlp_in = rms_norm(x, w["mlp_norm"], cfg.rms_norm_eps)
    moe_out = moe_ffn(
        mlp_in, w["w_router"], w["w_gate"], w["w_up"], w["w_down"],
        top_k=cfg.experts_per_token,
        capacity_factor=cfg.capacity_factor * capacity_scale,
        norm_topk_prob=cfg.norm_topk_prob,
    )
    return x + moe_out


def _prefill_trunk(params, cfg: MixtralConfig, token_ids, kv_cache,
                   positions, cos, sin, attend, last_idx):
    """Shared prefill scaffold: embed → layer scan (qkv+rope handled here,
    the caller supplies only the attention math via ``attend``) → final
    norm → last-token logits.  Keeps the plain and continued-prefill paths
    from drifting apart."""
    s = token_ids.shape[0]
    x = params["embed"][token_ids].astype(cfg.dtype)

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        state = {}

        def attn(attn_in):
            q = mm(attn_in, w["wq"]).reshape(s, cfg.num_heads, cfg.head_dim)
            k = mm(attn_in, w["wk"]).reshape(s, cfg.num_kv_heads, cfg.head_dim)
            v = mm(attn_in, w["wv"]).reshape(s, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm:  # Qwen3-MoE: per-head RMSNorm pre-rope
                q = rms_norm(q, w["q_norm"], cfg.rms_norm_eps)
                k = rms_norm(k, w["k_norm"], cfg.rms_norm_eps)
            q = apply_rope(q, positions, cos, sin)
            k = apply_rope(k, positions, cos, sin)
            attn_out, state["kv"] = attend(q, k, v, k_layer, v_layer)
            return mm(attn_out.reshape(s, -1), w["wo"])

        x = _block(cfg, w, x, attn)
        return x, state["kv"]

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    last = x[jnp.maximum(last_idx - 1, 0)]
    logits = (
        last[None] @ params["embed"].T.astype(x.dtype)
        if cfg.tie_word_embeddings
        else mm(last[None], params["lm_head"])
    )[0]
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def mixtral_forward_prefill(
    params, cfg: MixtralConfig, token_ids, kv_cache, block_ids, seq_len, start_pos, cos, sin
):
    positions = start_pos + jnp.arange(token_ids.shape[0], dtype=jnp.int32)

    def attend(q, k, v, k_layer, v_layer):
        kv = write_prefill_kv(k_layer, v_layer, k, v, block_ids, seq_len)
        out = dense_causal_attention(q[None], k[None], v[None], seq_len[None])[0]
        return out, kv

    return _prefill_trunk(
        params, cfg, token_ids, kv_cache, positions, cos, sin, attend, seq_len
    )


def mixtral_forward_prefill_with_prefix(
    params, cfg: MixtralConfig, token_ids, kv_cache, full_block_ids,
    tail_block_ids, tail_len, start_pos, cos, sin
):
    """Continued prefill over a reused prefix for the MoE family: tail
    queries attend to the resident prefix KV plus themselves, MoE FFN on the
    tail activations only (same contract as
    llama_forward_prefill_with_prefix)."""
    positions = start_pos + jnp.arange(token_ids.shape[0], dtype=jnp.int32)

    def attend(q, k, v, k_layer, v_layer):
        k_prefix, v_prefix = gather_prefix_kv(k_layer, v_layer, full_block_ids)
        kv = write_prefill_kv(k_layer, v_layer, k, v, tail_block_ids, tail_len)
        out = prefill_attention_with_prefix(
            q, k, v, k_prefix, v_prefix, start_pos, tail_len
        )
        return out, kv

    return _prefill_trunk(
        params, cfg, token_ids, kv_cache, positions, cos, sin, attend, tail_len
    )


def mixtral_forward_decode(
    params, cfg: MixtralConfig, token_ids, kv_cache, block_tables, context_lens, slot_ids,
    cos, sin, *, attention: str = "jax",
):
    b = token_ids.shape[0]

    def paged_attn(q, k_layer, v_layer):
        if attention.startswith("pallas"):
            from dynamo_tpu.ops.pallas import paged_attention_decode

            return paged_attention_decode(
                q, k_layer, v_layer, block_tables, context_lens,
                interpret=attention == "pallas_interpret",
            )
        return paged_decode_attention(q, k_layer, v_layer, block_tables, context_lens)

    x = params["embed"][token_ids].astype(cfg.dtype)
    positions = jnp.maximum(context_lens - 1, 0)

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        state = {}

        def attn(attn_in):
            q = mm(attn_in, w["wq"]).reshape(b, cfg.num_heads, cfg.head_dim)
            k = mm(attn_in, w["wk"]).reshape(b, cfg.num_kv_heads, cfg.head_dim)
            v = mm(attn_in, w["wv"]).reshape(b, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm:  # Qwen3-MoE: per-head RMSNorm pre-rope
                q = rms_norm(q, w["q_norm"], cfg.rms_norm_eps)
                k = rms_norm(k, w["k_norm"], cfg.rms_norm_eps)
            q = apply_rope(q[:, None], positions[:, None], cos, sin)[:, 0]
            k = apply_rope(k[:, None], positions[:, None], cos, sin)[:, 0]
            state["kv"] = write_decode_kv(k_layer, v_layer, k, v, slot_ids)
            attn_out = paged_attn(q, state["kv"][0], state["kv"][1])
            return mm(attn_out.reshape(b, -1), w["wo"])

        x = _block(cfg, w, x, attn)
        return x, state["kv"]

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = (
        x @ params["embed"].T.astype(x.dtype)
        if cfg.tie_word_embeddings
        else mm(x, params["lm_head"])
    )
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def mixtral_forward_unified(
    params,
    cfg: MixtralConfig,
    token_ids,      # [T] int32 — flat ragged token batch
    kv_cache,
    block_tables,   # [lanes, max_blocks] int32
    context_lens,   # [lanes] int32 incl. each lane's span end
    token_pos,      # [T] int32 absolute position (-1 = pad)
    token_slot,     # [T] int32 flat cache slot (OOB = pad)
    token_lane,     # [T] int32 owning lane (OOB = pad)
    page_phys,      # [T // tb_tokens, PS] int32 (pack_page_meta)
    page_lane,      # [T // tb_tokens, PS] int32 owning lane (-1 pad)
    page_ord,       # [T // tb_tokens, PS] int32 page ordinal
    page_count,     # [T // tb_tokens] int32 live worklist entries
    sample_rows,    # [lanes] int32 flat index of span's LAST token
    cos,
    sin,
    *,
    attention: str = "jax",     # "jax" | "pallas" | "pallas_interpret"
    tb_tokens: int = 8,
    pages_per_step: int = 1,
):
    """Ragged unified-batch forward for the sparse-MoE family: the llama
    unified contract (mixed chunked-prefill spans + decode tokens, one
    launch, per-token absolute positions) with the dense MLP swapped for
    the top-k MoE FFN.  Expert routing is already per-token (ops/moe.py),
    so it composes with the ragged layout unchanged — each token routes on
    its own activations regardless of which lane owns it, and in the
    no-drop regime capacity_factor is sized for, per-token expert outputs
    are independent of batch composition (the split-vs-unified byte-parity
    contract).  Pad rows route too and are discarded at the sample gather."""
    t = token_ids.shape[0]
    x = params["embed"][token_ids].astype(cfg.dtype)
    positions = jnp.maximum(token_pos, 0)

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        state = {}

        def attn(attn_in):
            q = mm(attn_in, w["wq"]).reshape(t, cfg.num_heads, cfg.head_dim)
            k = mm(attn_in, w["wk"]).reshape(t, cfg.num_kv_heads, cfg.head_dim)
            v = mm(attn_in, w["wv"]).reshape(t, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm:  # Qwen3-MoE: per-head RMSNorm pre-rope
                q = rms_norm(q, w["q_norm"], cfg.rms_norm_eps)
                k = rms_norm(k, w["k_norm"], cfg.rms_norm_eps)
            q = apply_rope(q, positions, cos, sin)
            k = apply_rope(k, positions, cos, sin)
            # every token writes before anyone reads: span tokens see their
            # own in-window predecessors through the cache
            state["kv"] = write_decode_kv(k_layer, v_layer, k, v, token_slot)
            if attention.startswith("pallas"):
                from dynamo_tpu.ops.pallas import (
                    ragged_paged_attention as ragged_kernel,
                )

                attn_out = ragged_kernel(
                    q, state["kv"][0], state["kv"][1], token_lane, token_pos,
                    page_phys, page_lane, page_ord, page_count,
                    tb_tokens=tb_tokens,
                    pages_per_step=pages_per_step,
                    interpret=attention == "pallas_interpret",
                )
            else:
                attn_out = ragged_paged_attention(
                    q, state["kv"][0], state["kv"][1], block_tables,
                    context_lens, token_lane, token_pos,
                )
            return mm(attn_out.reshape(t, -1), w["wo"])

        x = _block(cfg, w, x, attn)
        return x, state["kv"]

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv_cache["k"], kv_cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    rows = x[sample_rows]  # [lanes, h] — junk for hole lanes, caller-gated
    logits = (
        rows @ params["embed"].T.astype(rows.dtype)
        if cfg.tie_word_embeddings
        else mm(rows, params["lm_head"])
    )
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def mixtral_forward_decode_pp(
    params, cfg: MixtralConfig, token_ids, kv_cache, block_tables,
    context_lens, slot_ids, cos, sin, *, pp_mesh, microbatches: int | None = None,
):
    """Batched MoE decode with the layer stack pipelined over the ``pp``
    mesh axis (parallel/pipeline.py), composing with expert parallelism:
    the pp axis is manual inside the pipeline runner's partial-manual
    shard_map while the expert-stacked weights keep their ``P(..., "ep",
    ...)`` shardings — GSPMD inserts the expert all-to-alls INSIDE each
    stage exactly as it does for tp in the llama path
    (llama_forward_decode_pp).  BASELINE.json's Mixtral-on-v5p config
    implies this composition.

    MoE drop semantics vs the non-pp decode: routing runs per MICROBATCH,
    with capacity_factor scaled by the microbatch count so each expert's
    per-call capacity equals what full-batch routing would allocate.
    Tokens therefore only compete for slots within their own microbatch —
    outputs match the plain decode exactly whenever no drops occur (the
    served regime capacity_factor is sized for), and under extreme routing
    skew the pp path drops no earlier than full-batch routing would."""
    b = token_ids.shape[0]
    x = params["embed"][token_ids].astype(cfg.dtype)
    positions = jnp.maximum(context_lens - 1, 0)
    m_count = microbatches or pp_mesh.shape["pp"]

    def body(x_mb, aux_mb, w, layer_cache):
        k_layer, v_layer = layer_cache
        pos_mb, slots_mb, tables_mb, lens_mb = aux_mb
        bmb = x_mb.shape[0]
        state = {}

        def attn(attn_in):
            q = mm(attn_in, w["wq"]).reshape(bmb, cfg.num_heads, cfg.head_dim)
            k = mm(attn_in, w["wk"]).reshape(bmb, cfg.num_kv_heads, cfg.head_dim)
            v = mm(attn_in, w["wv"]).reshape(bmb, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm:
                q = rms_norm(q, w["q_norm"], cfg.rms_norm_eps)
                k = rms_norm(k, w["k_norm"], cfg.rms_norm_eps)
            q = apply_rope(q[:, None], pos_mb[:, None], cos, sin)[:, 0]
            k = apply_rope(k[:, None], pos_mb[:, None], cos, sin)[:, 0]
            state["kv"] = write_decode_kv(k_layer, v_layer, k, v, slots_mb)
            attn_out = paged_decode_attention(
                q, state["kv"][0], state["kv"][1], tables_mb, lens_mb
            )
            return mm(attn_out.reshape(bmb, -1), w["wo"])

        x_mb = _block(cfg, w, x_mb, attn, capacity_scale=float(m_count))
        return x_mb, state["kv"]

    from dynamo_tpu.parallel.pipeline import pipeline_layer_stack

    x, (new_k, new_v) = pipeline_layer_stack(
        body, x, (positions, slot_ids, block_tables, context_lens),
        params["layers"], (kv_cache["k"], kv_cache["v"]), pp_mesh,
        microbatches=microbatches,
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = (
        x @ params["embed"].T.astype(x.dtype)
        if cfg.tie_word_embeddings
        else mm(x, params["lm_head"])
    )
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def mixtral_forward_verify(
    params, cfg: MixtralConfig, token_ids, kv_cache, block_tables,
    context_lens, slot_ids, cos, sin, *, attention: str = "jax",
):
    """Speculative-verification forward for the MoE family: the [b, w]
    window runs through the same attention scaffold as decode (multi-query
    paged window attention) and the MoE FFN sees the window's b*w tokens.
    Contract matches llama_forward_verify.

    Token order is POSITION-major (all lanes' position-0 tokens first):
    expert-capacity slots assign in dispatch order (ops/moe.py), so the
    always-emitted position-0 tokens never lose a slot to a later draft
    position.  MoE parity with plain decode is therefore near-exact but
    not guaranteed under extreme routing skew — capacity grows w-fold with
    the window, yet which tokens drop can differ from the non-speculative
    schedule (a capacity-dropping property, not an acceptance-logic one)."""
    b, w_len = token_ids.shape
    # [b, w] → position-major flat [w*b]
    x = params["embed"][token_ids.T.reshape(-1)].astype(cfg.dtype)
    positions = jnp.maximum(
        context_lens[:, None] - w_len + jnp.arange(w_len)[None, :], 0
    )  # [b, w]
    flat_slots = slot_ids.T.reshape(-1)

    def attend_pages(q, k_layer, v_layer):
        return window_attention(
            attention, q, k_layer, v_layer, block_tables, context_lens
        )

    def to_bw(t, *tail):
        return position_major_to_batch(t, w_len, b, *tail)

    def layer(x, layer_in):
        w, k_layer, v_layer = layer_in
        state = {}

        def attn(attn_in):
            q = to_bw(mm(attn_in, w["wq"]), cfg.num_heads, cfg.head_dim)
            k = to_bw(mm(attn_in, w["wk"]), cfg.num_kv_heads, cfg.head_dim)
            v = to_bw(mm(attn_in, w["wv"]), cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm:
                q = rms_norm(q, w["q_norm"], cfg.rms_norm_eps)
                k = rms_norm(k, w["k_norm"], cfg.rms_norm_eps)
            q = apply_rope(q, positions, cos, sin)
            k = apply_rope(k, positions, cos, sin)
            state["kv"] = write_decode_kv(
                k_layer, v_layer,
                k.transpose(1, 0, 2, 3).reshape(w_len * b, cfg.num_kv_heads, cfg.head_dim),
                v.transpose(1, 0, 2, 3).reshape(w_len * b, cfg.num_kv_heads, cfg.head_dim),
                flat_slots,
            )
            attn_out = attend_pages(q, state["kv"][0], state["kv"][1])  # [b, w, H, D]
            flat = attn_out.transpose(1, 0, 2, 3).reshape(w_len * b, -1)
            return mm(flat, w["wo"])

        x = _block(cfg, w, x, attn)
        return x, state["kv"]

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv_cache["k"], kv_cache["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = (
        x @ params["embed"].T.astype(x.dtype)
        if cfg.tie_word_embeddings
        else mm(x, params["lm_head"])
    )
    logits = logits.reshape(w_len, b, -1).transpose(1, 0, 2)
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


# ------------------------------------------------------------------ weights


def load_hf_weights(cfg: MixtralConfig, model_dir) -> dict:
    """Load and stack HF Mixtral safetensors into the layer-stacked pytree
    (HF projections are [out, in]; ours [in, out] → transpose; experts stack
    on a leading E axis)."""
    import numpy as np

    from dynamo_tpu.models.hf_io import read_safetensors

    tensors = read_safetensors(model_dir)

    def get(name: str, transpose: bool = False):
        t = tensors[name]
        if transpose:
            t = t.T
        return np.asarray(t)

    names = (
        "attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
        "w_router", "w_gate", "w_up", "w_down",
    ) + (("q_norm", "k_norm") if cfg.qk_norm else ())
    layers: dict[str, list] = {k: [] for k in names}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}"
        layers["attn_norm"].append(get(f"{p}.input_layernorm.weight"))
        layers["wq"].append(get(f"{p}.self_attn.q_proj.weight", True))
        layers["wk"].append(get(f"{p}.self_attn.k_proj.weight", True))
        layers["wv"].append(get(f"{p}.self_attn.v_proj.weight", True))
        layers["wo"].append(get(f"{p}.self_attn.o_proj.weight", True))
        layers["mlp_norm"].append(get(f"{p}.post_attention_layernorm.weight"))
        if cfg.qk_norm:
            layers["q_norm"].append(get(f"{p}.self_attn.q_norm.weight"))
            layers["k_norm"].append(get(f"{p}.self_attn.k_norm.weight"))
        if f"{p}.block_sparse_moe.gate.weight" in tensors:
            # Mixtral naming: w1=gate, w3=up, w2=down
            moe_p, hf_names = f"{p}.block_sparse_moe", ("w1", "w3", "w2")
        else:
            # Qwen3-MoE naming: mlp.experts.{e}.gate/up/down_proj
            moe_p, hf_names = f"{p}.mlp", ("gate_proj", "up_proj", "down_proj")
        layers["w_router"].append(get(f"{moe_p}.gate.weight", True))
        for ours, theirs in zip(("w_gate", "w_up", "w_down"), hf_names):
            layers[ours].append(np.stack([
                get(f"{moe_p}.experts.{e}.{theirs}.weight", True)
                for e in range(cfg.num_experts)
            ]))

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), cfg.dtype),
        "final_norm": jnp.asarray(get("model.norm.weight"), cfg.dtype),
        "layers": {
            k: jnp.asarray(np.stack(v), cfg.dtype) for k, v in layers.items()
        },
    }
    if not cfg.tie_word_embeddings and "lm_head.weight" in tensors:
        params["lm_head"] = jnp.asarray(get("lm_head.weight", True), cfg.dtype)
    return params
