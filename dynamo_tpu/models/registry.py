"""Model family registry.

Binds a ``model_type`` (HF config.json naming) to the functional pieces the
engine needs: config parsing, param init, sharding specs, prefill/decode
forwards.  Families registered here are served by the same engine,
scheduler, router and disagg machinery.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable


@dataclass(frozen=True)
class ModelFamily:
    name: str
    config_from_hf: Callable[[Any], Any]
    init_params: Callable
    param_specs: Callable
    forward_prefill: Callable
    forward_decode: Callable


def _llama_family() -> ModelFamily:
    from dynamo_tpu.models import llama

    return ModelFamily(
        name="llama",
        config_from_hf=llama.LlamaConfig.from_hf_config,
        init_params=llama.init_params,
        param_specs=llama.param_specs,
        forward_prefill=llama.llama_forward_prefill,
        forward_decode=llama.llama_forward_decode,
    )


def _qwen2_family() -> ModelFamily:
    # Qwen2/2.5 = llama geometry + attention qkv biases (config flag); the
    # llama implementation handles both (attention_bias).
    from dynamo_tpu.models import llama

    def config_from_hf(config):
        import json

        if not isinstance(config, dict):
            config = json.loads(Path(config).read_text())
        config = dict(config)
        config.setdefault("attention_bias", True)
        return llama.LlamaConfig.from_hf_config(config)

    return ModelFamily(
        name="qwen2",
        config_from_hf=config_from_hf,
        init_params=llama.init_params,
        param_specs=llama.param_specs,
        forward_prefill=llama.llama_forward_prefill,
        forward_decode=llama.llama_forward_decode,
    )


def _mixtral_family() -> ModelFamily:
    from dynamo_tpu.models import mixtral

    return ModelFamily(
        name="mixtral",
        config_from_hf=mixtral.MixtralConfig.from_hf_config,
        init_params=mixtral.init_params,
        param_specs=mixtral.param_specs,
        forward_prefill=mixtral.mixtral_forward_prefill,
        forward_decode=mixtral.mixtral_forward_decode,
    )


_FAMILIES: dict[str, Callable[[], ModelFamily]] = {
    "llama": _llama_family,
    "qwen2": _qwen2_family,
    "qwen3": _qwen2_family,
    "mixtral": _mixtral_family,
}


def get_family(model_type: str) -> ModelFamily:
    factory = _FAMILIES.get(model_type)
    if factory is None:
        raise ValueError(
            f"unknown model family {model_type!r}; known: {sorted(_FAMILIES)}"
        )
    return factory()


def register_family(name: str, factory: Callable[[], ModelFamily]) -> None:
    _FAMILIES[name] = factory
