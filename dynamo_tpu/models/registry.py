"""Model family registry.

Binds a ``model_type`` (HF config.json naming) to the functional pieces the
engine needs: config parsing, param init, sharding specs, prefill/decode
forwards.  Families registered here are served by the same engine,
scheduler, router and disagg machinery.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable


@dataclass(frozen=True)
class ModelFamily:
    name: str
    config_from_hf: Callable[[Any], Any]
    init_params: Callable
    param_specs: Callable
    forward_prefill: Callable
    forward_decode: Callable
    # cache geometry hooks; None = the llama-family GQA paged cache
    # (MLA families override: cache stores compressed latents)
    init_kv_cache: Callable | None = None
    kv_cache_specs: Callable | None = None
    make_rope_tables: Callable | None = None
    # continued prefill over a resident prefix (prefix-cache reuse, chunked
    # prefill); None = the engine disables prefix caching for this family
    forward_prefill_with_prefix: Callable | None = None
    # prefill from precomputed input embeddings (multimodal: vision patches
    # spliced before text); None = no multimodal support for this family
    forward_prefill_embeds: Callable | None = None
    # token-embedding lookup hook: (params, cfg, token_ids) -> [n, hidden].
    # None = raw table lookup.  Families with input-embedding quirks
    # (gemma's sqrt(hidden) scale) set this so generic engine code — the
    # multimodal prefill splices text embeddings itself — stays family-
    # agnostic instead of copying the quirk inline.
    embed: Callable | None = None
    # forward_prefill accepts sp_mesh= (ring-attention sequence parallelism)
    supports_sp: bool = False
    # forward_prefill_with_prefix accepts sp_mesh (ring attention over the
    # tail + merged resident prefix) — what lets prefix caching and
    # chunked prefill compose with a sequence-parallel mesh
    prefix_prefill_accepts_sp: bool = False
    # pipelined decode over the pp mesh axis (parallel/pipeline.py)
    forward_decode_pp: Callable | None = None
    # HF safetensors loader: (cfg, model_dir) -> params pytree
    load_weights: Callable | None = None
    # forward_decode accepts tp_mesh= (shard_map'd pallas attention)
    decode_accepts_tp_mesh: bool = False
    # multi-position verification forward (speculative decoding); None =
    # the engine rejects speculative config for this family
    forward_verify: Callable | None = None
    # ragged unified-batch forward (one launch mixing chunked-prefill spans
    # and decode tokens, ops/pallas/ragged_attention.py); None = the engine
    # keeps the split prefill/decode step for this family
    forward_unified: Callable | None = None
    # param-tree leaf names eligible for weight-only int8 (ops/quant.py);
    # empty = the family's forwards don't route matmuls through quant.mm
    quant_leaves: tuple[str, ...] = ()

    def cache_init(self, cfg, num_blocks: int, block_size: int, dtype=None):
        if self.init_kv_cache is not None:
            return self.init_kv_cache(cfg, num_blocks, block_size, dtype)
        from dynamo_tpu.models import llama

        return llama.init_kv_cache(cfg, num_blocks, block_size, dtype)

    def cache_specs(self, cfg):
        """Pytree of PartitionSpecs matching the cache pytree."""
        if self.kv_cache_specs is not None:
            return self.kv_cache_specs(cfg)
        from dynamo_tpu.models import llama

        spec = llama.kv_cache_spec()
        return {"k": spec, "v": spec}

    def rope_tables(self, cfg):
        if self.make_rope_tables is not None:
            return self.make_rope_tables(cfg)
        from dynamo_tpu.models import llama

        return llama.make_rope_tables(cfg)


# attention projections + FFN/expert banks shared by the llama-like and
# MoE families ([L, E, in, out] expert banks quantize per (layer, expert,
# out-channel) — the scale rule is axis-position based, not rank based);
# small routers and norms stay full-precision
_PROJ_QUANT_LEAVES = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
)


def _llama_like_family(
    name: str, config_tweak=None, *, config_from_hf=None, load_weights=None,
) -> ModelFamily:
    """One ModelFamily construction for every llama-geometry variant.

    ``config_tweak(dict)`` mutates the HF config before parsing (biases,
    qk-norm flags); ``config_from_hf``/``load_weights`` replace the whole
    parse/load step for families with checkpoint quirks (gemma's baked
    (1+w) norms, phi3's fused tensors) so each stays a one-line
    declaration."""
    from dynamo_tpu.models import llama

    def default_config_from_hf(config):
        import json

        if not isinstance(config, dict):
            config = json.loads(Path(config).read_text())
        config = dict(config)
        if config_tweak is not None:
            config_tweak(config)
        return llama.LlamaConfig.from_hf_config(config)

    return ModelFamily(
        name=name,
        config_from_hf=config_from_hf or default_config_from_hf,
        init_params=llama.init_params,
        param_specs=llama.param_specs,
        forward_prefill=llama.llama_forward_prefill,
        forward_decode=llama.llama_forward_decode,
        forward_prefill_with_prefix=llama.llama_forward_prefill_with_prefix,
        forward_prefill_embeds=llama.llama_forward_prefill_embeds,
        embed=llama._embed,
        supports_sp=True,
        prefix_prefill_accepts_sp=True,
        forward_decode_pp=llama.llama_forward_decode_pp,
        load_weights=load_weights or llama.load_hf_weights,
        decode_accepts_tp_mesh=True,
        quant_leaves=_PROJ_QUANT_LEAVES,
        forward_verify=llama.llama_forward_verify,
        forward_unified=llama.llama_forward_unified,
    )


def _llama_family() -> ModelFamily:
    return _llama_like_family("llama")


def _qwen2_family() -> ModelFamily:
    # Qwen2/2.5 = llama geometry + attention qkv biases
    return _llama_like_family(
        "qwen2", lambda c: c.setdefault("attention_bias", True)
    )


def _qwen3_family() -> ModelFamily:
    # Qwen3 = llama geometry + per-head q/k RMSNorm before rope, no biases
    return _llama_like_family("qwen3", lambda c: c.update(qk_norm=True))


def _phi3_family() -> ModelFamily:
    # Phi-3 = llama math with fused checkpoint tensors (split at load) and
    # an always-on sliding window; longrope variants refused at config
    # parse (models/llama.py phi3_* helpers)
    from dynamo_tpu.models import llama

    return _llama_like_family(
        "phi3",
        config_from_hf=llama.phi3_config_from_hf,
        load_weights=llama.phi3_load_hf_weights,
    )


def _gemma_family() -> ModelFamily:
    # Gemma-1 = llama skeleton + GeGLU, sqrt(hidden) embedding scale, and
    # (1+w) RMSNorm baked at load (models/llama.py gemma_* helpers).
    from dynamo_tpu.models import llama

    return _llama_like_family(
        "gemma",
        config_from_hf=llama.gemma_config_from_hf,
        load_weights=llama.gemma_load_hf_weights,
    )


def _gemma2_family() -> ModelFamily:
    # Gemma-2 = alternating local/global attention (per-layer window array
    # through one lax.scan), attn + final logit soft-capping, sandwich
    # norms, query_pre_attn_scalar (models/gemma2.py)
    from dynamo_tpu.models import gemma2

    return ModelFamily(
        name="gemma2",
        config_from_hf=gemma2.Gemma2Config.from_hf_config,
        init_params=gemma2.init_params,
        param_specs=gemma2.param_specs,
        forward_prefill=gemma2.gemma2_forward_prefill,
        forward_decode=gemma2.gemma2_forward_decode,
        forward_prefill_with_prefix=gemma2.gemma2_forward_prefill_with_prefix,
        make_rope_tables=gemma2.make_rope_tables,
        embed=gemma2._embed,
        load_weights=gemma2.load_hf_weights,
        quant_leaves=_PROJ_QUANT_LEAVES,
        forward_verify=gemma2.gemma2_forward_verify,
    )


def _gemma3_family() -> ModelFamily:
    # Gemma-3 text = Gemma-2 machinery + 5:1 local/global pattern, dual
    # rope bases packed along the feature axis, per-head q/k (1+w) norms,
    # no soft-capping (models/gemma3.py).  Multimodal checkpoints parse
    # their text_config; image inputs are rejected (no embeds prefill).
    from dynamo_tpu.models import gemma3

    return ModelFamily(
        name="gemma3",
        config_from_hf=gemma3.Gemma3Config.from_hf_config,
        init_params=gemma3.init_params,
        param_specs=gemma3.param_specs,
        forward_prefill=gemma3.gemma3_forward_prefill,
        forward_decode=gemma3.gemma3_forward_decode,
        forward_prefill_with_prefix=gemma3.gemma3_forward_prefill_with_prefix,
        forward_prefill_embeds=gemma3.gemma3_forward_prefill_embeds,
        make_rope_tables=gemma3.make_rope_tables,
        embed=gemma3._embed,
        load_weights=gemma3.load_hf_weights,
        quant_leaves=_PROJ_QUANT_LEAVES,
        forward_verify=gemma3.gemma3_forward_verify,
    )


def _mixtral_family() -> ModelFamily:
    from dynamo_tpu.models import mixtral

    return ModelFamily(
        name="mixtral",
        config_from_hf=mixtral.MixtralConfig.from_hf_config,
        init_params=mixtral.init_params,
        param_specs=mixtral.param_specs,
        forward_prefill=mixtral.mixtral_forward_prefill,
        forward_decode=mixtral.mixtral_forward_decode,
        forward_prefill_with_prefix=mixtral.mixtral_forward_prefill_with_prefix,
        forward_decode_pp=mixtral.mixtral_forward_decode_pp,
        load_weights=mixtral.load_hf_weights,
        quant_leaves=_PROJ_QUANT_LEAVES,
        forward_verify=mixtral.mixtral_forward_verify,
        forward_unified=mixtral.mixtral_forward_unified,
    )


def _qwen3_moe_family() -> ModelFamily:
    # Qwen3-MoE = Mixtral-style routed experts + per-head q/k RMSNorm
    # (from_hf_config infers qk_norm from model_type, which the registry
    # key guarantees is present on any config routed here)
    from dynamo_tpu.models import mixtral

    return ModelFamily(
        name="qwen3_moe",
        config_from_hf=mixtral.MixtralConfig.from_hf_config,
        init_params=mixtral.init_params,
        param_specs=mixtral.param_specs,
        forward_prefill=mixtral.mixtral_forward_prefill,
        forward_decode=mixtral.mixtral_forward_decode,
        forward_prefill_with_prefix=mixtral.mixtral_forward_prefill_with_prefix,
        forward_decode_pp=mixtral.mixtral_forward_decode_pp,
        load_weights=mixtral.load_hf_weights,
        quant_leaves=_PROJ_QUANT_LEAVES,
        forward_verify=mixtral.mixtral_forward_verify,
        forward_unified=mixtral.mixtral_forward_unified,
    )


def _deepseek_family() -> ModelFamily:
    from dynamo_tpu.models import deepseek

    return ModelFamily(
        name="deepseek",
        config_from_hf=deepseek.DeepseekConfig.from_hf_config,
        init_params=deepseek.init_params,
        param_specs=deepseek.param_specs,
        forward_prefill=deepseek.deepseek_forward_prefill,
        forward_decode=deepseek.deepseek_forward_decode,
        forward_prefill_with_prefix=deepseek.deepseek_forward_prefill_with_prefix,
        load_weights=deepseek.load_hf_weights,
        init_kv_cache=deepseek.init_kv_cache,
        kv_cache_specs=deepseek.kv_cache_specs,
        make_rope_tables=deepseek.make_rope_tables,
        # absorbed-form up-projections (w_uk/w_uv) stay full precision:
        # they are reshaped + consumed inside fp32 einsums
        quant_leaves=(
            "w_dq", "w_uq", "wq", "w_dkv", "wo", "w_gate", "w_up", "w_down",
            "ws_gate", "ws_up", "ws_down", "lm_head",
        ),
        forward_verify=deepseek.deepseek_forward_verify,
        forward_unified=deepseek.deepseek_forward_unified,
    )


_FAMILIES: dict[str, Callable[[], ModelFamily]] = {
    "llama": _llama_family,
    # Mistral = llama geometry + sliding-window attention; the window comes
    # from config.json's sliding_window and threads through the llama
    # forwards (models/llama.py)
    "mistral": _llama_family,
    "qwen2": _qwen2_family,
    "qwen3": _qwen3_family,
    "gemma": _gemma_family,
    "gemma2": _gemma2_family,
    "gemma3": _gemma3_family,
    "gemma3_text": _gemma3_family,
    "phi3": _phi3_family,
    "mixtral": _mixtral_family,
    "qwen3_moe": _qwen3_moe_family,
    # HF model_type keys for the MLA architectures only — classic
    # DeepSeek-MoE ("deepseek") uses conventional attention and would need
    # its own family
    "deepseek_v2": _deepseek_family,
    "deepseek_v3": _deepseek_family,
}


def known_families() -> list[str]:
    return sorted(_FAMILIES)


def get_family(model_type: str) -> ModelFamily:
    factory = _FAMILIES.get(model_type)
    if factory is None:
        raise ValueError(
            f"unknown model family {model_type!r}; known: {sorted(_FAMILIES)}"
        )
    return factory()


def register_family(name: str, factory: Callable[[], ModelFamily]) -> None:
    _FAMILIES[name] = factory
