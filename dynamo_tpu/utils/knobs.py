"""Typed registry and the single read path for ``DYN_*`` environment knobs.

Every environment variable the system consumes is declared here once — name,
type, default, one-line doc, and the docs page that carries its table row —
and read through :func:`get` / :func:`get_raw`.  The ``knob-registry`` pass
of ``scripts/dynlint.py`` enforces the contract statically: a raw
``os.environ`` read of a ``DYN_*`` name anywhere else in the tree is a lint
finding, as is a registered knob missing from the docs, so the knob surface
cannot drift from its documentation again (pre-registry audit: 56 knobs in
code, 45 in docs).

Registrations are *literal* ``register(...)`` calls on purpose: the analyzer
parses this module's AST — no import of the package (and hence no JAX) is
needed to know the registry.

Semantics:

- ``bool`` knobs parse ``1/true/yes/on`` as True and ``0/false/off/no`` (or
  empty) as False; any other token falls back to the default, so e.g.
  ``DYN_CP_RECONNECT=2`` keeps reconnect enabled exactly as before.
- A ``default=None`` bool is tri-state: unset returns ``None`` so the caller
  can distinguish "operator said nothing" from an explicit override
  (``DYN_DECODE_OVERLAP`` / ``DYN_UNIFIED_BATCH`` defer to ``EngineConfig``).
- ``int``/``float`` knobs return the default when unset, empty, or
  unparseable — a malformed knob degrades to the documented default instead
  of crashing a worker at import time.
- ``get(name, env=...)`` accepts an explicit mapping for call sites that
  plan against a *child* process environment (the SDK allocator) and for
  tests.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Mapping

_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "off", "no", "")

OBS = "docs/observability.md"
PERF = "docs/performance.md"
ROBUST = "docs/robustness.md"
ARCH = "docs/architecture.md"


@dataclass(frozen=True)
class Knob:
    name: str
    type: str  # "str" | "int" | "float" | "bool"
    default: Any
    doc: str
    section: str  # docs page that carries this knob's table row


_REGISTRY: dict[str, Knob] = {}


def register(
    name: str, *, type: str = "str", default: Any = None, doc: str = "",
    section: str = OBS,
) -> str:
    """Declare one knob; returns the name so modules can bind constants."""
    if name in _REGISTRY:
        raise ValueError(f"knob {name} registered twice")
    if type not in ("str", "int", "float", "bool"):
        raise ValueError(f"knob {name}: unknown type {type!r}")
    if not doc:
        raise ValueError(f"knob {name}: doc string is required")
    _REGISTRY[name] = Knob(name=name, type=type, default=default, doc=doc, section=section)
    return name


def parse_bool(raw: str | None, default: Any = False) -> Any:
    if raw is None:
        return default
    lowered = raw.strip().lower()
    if lowered in _TRUTHY:
        return True
    if lowered in _FALSY:
        return False
    return default


def get_raw(name: str, *, env: Mapping[str, str] | None = None) -> str | None:
    """The raw string value (or None when unset) of a *registered* knob."""
    if name not in _REGISTRY:
        raise KeyError(f"unregistered knob {name}; declare it in utils/knobs.py")
    source = os.environ if env is None else env
    return source.get(name)


def get(name: str, *, env: Mapping[str, str] | None = None) -> Any:
    """The typed value of a registered knob (default when unset/malformed)."""
    knob = _REGISTRY.get(name)
    if knob is None:
        raise KeyError(f"unregistered knob {name}; declare it in utils/knobs.py")
    source = os.environ if env is None else env
    raw = source.get(name)
    if knob.type == "bool":
        return parse_bool(raw, knob.default)
    if raw is None:
        return knob.default
    if knob.type == "str":
        return raw
    try:
        return int(raw) if knob.type == "int" else float(raw)
    except ValueError:
        return knob.default


def is_set(name: str, *, env: Mapping[str, str] | None = None) -> bool:
    return get_raw(name, env=env) is not None


def all_knobs() -> tuple[Knob, ...]:
    return tuple(_REGISTRY[k] for k in sorted(_REGISTRY))


def knob_table(section: str | None = None) -> str:
    """Markdown table rows for the docs (``scripts/dynlint.py --knob-table``)."""
    rows = ["| knob | type | default | purpose |", "|---|---|---|---|"]
    for knob in all_knobs():
        if section is not None and knob.section != section:
            continue
        default = "unset" if knob.default is None else f"`{knob.default}`"
        rows.append(f"| `{knob.name}` | {knob.type} | {default} | {knob.doc} |")
    return "\n".join(rows)


# ---------------------------------------------------------------------------
# Registry.  Grouped by subsystem; ``section`` names the docs page whose
# table documents the knob (the knob-registry pass checks the name appears
# somewhere under docs/, and --knob-table regenerates the consolidated table).
# ---------------------------------------------------------------------------

# -- logging / tracing / profiling (docs/observability.md) ------------------
K_LOG = register(
    "DYN_LOG", type="str", default="info",
    doc="log filter spec, e.g. `warn,dynamo_tpu.runtime=debug`", section=OBS)
K_LOGGING_JSONL = register(
    "DYN_LOGGING_JSONL", type="bool", default=False,
    doc="emit JSONL log records with structured fields merged in", section=OBS)
K_TRACE_BUFFER = register(
    "DYN_TRACE_BUFFER", type="int", default=4096,
    doc="span ring-buffer size", section=OBS)
K_TRACE_JSONL = register(
    "DYN_TRACE_JSONL", type="str", default=None,
    doc="live JSONL span export path", section=OBS)
K_TRACE_MAX_BYTES = register(
    "DYN_TRACE_MAX_BYTES", type="int", default=0,
    doc="rotate the JSONL span export at this size (0 = unbounded)", section=OBS)
K_PROFILER_PORT = register(
    "DYN_PROFILER_PORT", type="int", default=None,
    doc="serve the jax profiler (TensorBoard/xprof attach) on this port", section=OBS)
K_PROFILER_TRACE_DIR = register(
    "DYN_PROFILER_TRACE_DIR", type="str", default=None,
    doc="capture a device trace of the whole engine serve window here", section=OBS)
K_XPROF_ANNOTATE = register(
    "DYN_XPROF_ANNOTATE", type="bool", default=False,
    doc="wrap hot steps in `jax.profiler.TraceAnnotation`", section=OBS)
K_ENGINE_PHASE_TIMING = register(
    "DYN_ENGINE_PHASE_TIMING", type="bool", default=False,
    doc="host-side decode phase timing in `stats()[\"phase_ms\"]`", section=OBS)

# -- utilization / SLO (docs/observability.md) -------------------------------
K_UTIL_WINDOW_S = register(
    "DYN_UTIL_WINDOW_S", type="float", default=10.0,
    doc="rolling window for MFU/MBU/goodput rates", section=OBS)
K_PEAK_TFLOPS = register(
    "DYN_PEAK_TFLOPS", type="float", default=None,
    doc="hardware peak TFLOP/s for the MFU denominator (overrides the "
        "device-kind table)", section=OBS)
K_PEAK_GBPS = register(
    "DYN_PEAK_GBPS", type="float", default=None,
    doc="hardware peak GB/s for the MBU denominator (overrides the "
        "device-kind table)", section=OBS)
K_SLO_TTFT_S = register(
    "DYN_SLO_TTFT_S", type="float", default=2.0,
    doc="TTFT objective threshold (seconds)", section=OBS)
K_SLO_TTFT_TARGET = register(
    "DYN_SLO_TTFT_TARGET", type="float", default=0.99,
    doc="good fraction required for TTFT", section=OBS)
K_SLO_ITL_S = register(
    "DYN_SLO_ITL_S", type="float", default=0.2,
    doc="inter-token-latency objective threshold (seconds)", section=OBS)
K_SLO_ITL_TARGET = register(
    "DYN_SLO_ITL_TARGET", type="float", default=0.99,
    doc="good fraction required for ITL", section=OBS)
K_SLO_ERROR_TARGET = register(
    "DYN_SLO_ERROR_TARGET", type="float", default=0.999,
    doc="request success-rate objective", section=OBS)
K_SLO_WINDOWS = register(
    "DYN_SLO_WINDOWS", type="str", default="",
    doc="comma-separated burn-rate windows in seconds (default `300,3600`)",
    section=OBS)
K_SLO_SHED_BURN = register(
    "DYN_SLO_SHED_BURN", type="float", default=0.0,
    doc="burn rate above which a saturated admission gate sheds (0 = off)",
    section=OBS)

# -- perf flight recorder (docs/observability.md) ----------------------------
K_FLIGHT = register(
    "DYN_FLIGHT", type="bool", default=True,
    doc="always-on perf flight recorder; `0` is bookkeeping-free (no ring, "
        "no per-step allocations)", section=OBS)
K_FLIGHT_BUFFER_BYTES = register(
    "DYN_FLIGHT_BUFFER_BYTES", type="int", default=262144,
    doc="byte budget of the flight-recorder ring (oldest records evicted "
        "when a new record would exceed it)", section=OBS)
K_FLIGHT_DIR = register(
    "DYN_FLIGHT_DIR", type="str", default=None,
    doc="directory flight dumps are written to (default "
        "`$DYN_CACHE_DIR/flight` or `~/.cache/dynamo_tpu/flight`)", section=OBS)
K_FLIGHT_BURN = register(
    "DYN_FLIGHT_BURN", type="float", default=10.0,
    doc="worst-window SLO burn rate above which the recorder auto-dumps "
        "(0 = never dump on burn)", section=OBS)

# -- perf regression gate (docs/observability.md) ----------------------------
K_PERFGATE_BASELINE = register(
    "DYN_PERFGATE_BASELINE", type="str", default=None,
    doc="explicit PERF_BASELINE.json path for scripts/perfgate.py (default: "
        "the repo-root artifact)", section=OBS)
K_PERFGATE_GIT_DESCRIBE = register(
    "DYN_PERFGATE_GIT_DESCRIBE", type="str", default=None,
    doc="git describe string CI stamps into artifact provenance headers",
    section=OBS)
K_PERFGATE_HOST_CLASS = register(
    "DYN_PERFGATE_HOST_CLASS", type="str", default=None,
    doc="host-class label stamped into artifact provenance (default: the "
        "JAX default backend, `unknown` without JAX)", section=OBS)

# -- engine / kernels (docs/performance.md) ----------------------------------
K_DECODE_OVERLAP = register(
    "DYN_DECODE_OVERLAP", type="bool", default=None,
    doc="override `EngineConfig.decode_overlap` (unset defers to config; "
        "`0` disables the overlapped decode pipeline)", section=PERF)
K_UNIFIED_BATCH = register(
    "DYN_UNIFIED_BATCH", type="bool", default=None,
    doc="override `EngineConfig.unified_batch` (unset defers to config, "
        "which defaults ON for every family with a unified forward; `0` "
        "forces the split prefill/decode step)", section=PERF)
K_KERNEL_PERF = register(
    "DYN_KERNEL_PERF", type="str", default=None,
    doc="explicit path to a KERNEL_PERF.json kernel-choice table (default: "
        "the repo-root artifact, purely advisory)", section=PERF)
K_COMPILE_CACHE_DIR = register(
    "DYN_COMPILE_CACHE_DIR", type="str", default=None,
    doc="persistent JAX compile cache dir (unset: "
        "`~/.cache/dynamo_tpu/jax_cache`; empty string disables; an "
        "explicitly set `jax_compilation_cache_dir` always wins)",
    section=PERF)
K_AUTOTUNE = register(
    "DYN_AUTOTUNE", type="bool", default=True,
    doc="consult KERNEL_PERF.json autotune rows for ragged-kernel tunables "
        "at engine init; `0` keeps the static heuristic defaults",
    section=PERF)
K_AUTOTUNE_TB = register(
    "DYN_AUTOTUNE_TB", type="int", default=None,
    doc="force the ragged kernel's token-block size (overrides tuned rows; "
        "must divide every serving bucket or it falls back with a warning)",
    section=PERF)
K_AUTOTUNE_PAGE_SLOTS = register(
    "DYN_AUTOTUNE_PAGE_SLOTS", type="int", default=None,
    doc="force the packed page-worklist width (overflowing windows repack "
        "at the full-size rung and count in "
        "`stats()[\"unified_ps_overflows_total\"]`)", section=PERF)
K_AUTOTUNE_PAGES_PER_STEP = register(
    "DYN_AUTOTUNE_PAGES_PER_STEP", type="int", default=None,
    doc="force KV pages fetched per ragged/paged grid step (must divide "
        "page_slots)", section=PERF)

# -- predictive prefetch (docs/performance.md) -------------------------------
K_PREFETCH = register(
    "DYN_PREFETCH", type="bool", default=True,
    doc="master prefetch gate; `0` restores demand-driven paging everywhere",
    section=PERF)
K_PREFETCH_TTL = register(
    "DYN_PREFETCH_TTL", type="float", default=30.0,
    doc="seconds before an unexecuted prefetch hint goes stale", section=PERF)
K_PREFETCH_BLOCKS = register(
    "DYN_PREFETCH_BLOCKS", type="int", default=64,
    doc="max blocks paged per engine-loop iteration while serving", section=PERF)
K_PREFETCH_HEADROOM = register(
    "DYN_PREFETCH_HEADROOM", type="float", default=0.05,
    doc="fraction of HBM blocks reserved from prefetch", section=PERF)
K_PREFETCH_HINT_CHARS = register(
    "DYN_PREFETCH_HINT_CHARS", type="int", default=16384,
    doc="frontend arrival hints tokenize at most this much rendered text",
    section=PERF)
K_PREFETCH_PIN_HITS = register(
    "DYN_PREFETCH_PIN_HITS", type="int", default=3,
    doc="restores before a block hash becomes a pin candidate", section=PERF)
K_PREFETCH_PIN_MAX = register(
    "DYN_PREFETCH_PIN_MAX", type="int", default=None,
    doc="max pinned host blocks (default: host blocks / 4)", section=PERF)

# -- disaggregated prefill/decode (docs/performance.md) ----------------------
K_KV_STREAM = register(
    "DYN_KV_STREAM", type="bool", default=True,
    doc="streamed multi-part disagg KV transfer; `0` = single-shot", section=PERF)
K_TRANSFER_HOP = register(
    "DYN_TRANSFER_HOP", type="str", default="",
    doc="explicit override of the worker's *discovered* hop class "
        "(`local`|`ici`|`dcn`) published to the router's transfer-cost "
        "model (unset: the topology plane's classification wins)", section=PERF)
K_DISAGG_PREFILL_TIMEOUT_S = register(
    "DYN_DISAGG_PREFILL_TIMEOUT_S", type="float", default=300.0,
    doc="decode-side wait for the KV stream before falling back to local "
        "prefill", section=PERF)
K_DISAGG_CLOCK_SKEW_S = register(
    "DYN_DISAGG_CLOCK_SKEW_S", type="float", default=30.0,
    doc="tolerated cross-host clock skew when judging queued-prefill "
        "staleness", section=PERF)

# -- fleet topology plane (docs/performance.md) ------------------------------
K_TOPO = register(
    "DYN_TOPO", type="bool", default=True,
    doc="master topology-plane gate: card publication, map watching, and "
        "probing; `0` restores the env-knob-only link model", section=PERF)
K_TOPO_SLICE = register(
    "DYN_TOPO_SLICE", type="str", default="",
    doc="explicit slice label for this worker's TopologyCard (overrides "
        "JAX `slice_index` detection; used to emulate multi-slice fleets)",
    section=PERF)
K_TOPO_PROBE_PERIOD_S = register(
    "DYN_TOPO_PROBE_PERIOD_S", type="float", default=10.0,
    doc="seconds between topology probe ticks (0 disables active probing; "
        "passive KvTransferClient EWMAs still feed the map)", section=PERF)
K_TOPO_PROBE_BYTES = register(
    "DYN_TOPO_PROBE_BYTES", type="int", default=65536,
    doc="payload size of one topology bandwidth probe", section=PERF)
K_TOPO_PROBE_MAX_PER_TICK = register(
    "DYN_TOPO_PROBE_MAX_PER_TICK", type="int", default=4,
    doc="max peers probed per tick (round-robin across the fleet)", section=PERF)

# -- robustness / routing (docs/robustness.md) -------------------------------
K_FAULTS = register(
    "DYN_FAULTS", type="str", default="",
    doc="chaos fault-injection schedule spec (see docs/robustness.md)",
    section=ROBUST)
K_CP_RECONNECT = register(
    "DYN_CP_RECONNECT", type="bool", default=True,
    doc="self-healing control-plane client; `0` restores fail-fast", section=ROBUST)
K_CP_RECONNECT_BACKOFF_S = register(
    "DYN_CP_RECONNECT_BACKOFF_S", type="float", default=0.05,
    doc="initial control-plane reconnect backoff", section=ROBUST)
K_CP_RECONNECT_BACKOFF_MAX_S = register(
    "DYN_CP_RECONNECT_BACKOFF_MAX_S", type="float", default=2.0,
    doc="cap on the control-plane reconnect backoff", section=ROBUST)
K_RETRY_MAX = register(
    "DYN_RETRY_MAX", type="int", default=1,
    doc="pre-first-token re-dispatch attempts for a failed stream", section=ROBUST)
K_CONNECT_TIMEOUT_S = register(
    "DYN_CONNECT_TIMEOUT_S", type="float", default=30.0,
    doc="data-plane rendezvous (connect-back) timeout per attempt", section=ROBUST)
K_DARK_WORKER_TTL_S = register(
    "DYN_DARK_WORKER_TTL_S", type="float", default=30.0,
    doc="quarantine TTL for an instance that failed a rendezvous", section=ROBUST)
K_DARK_PROBE_TIMEOUT_S = register(
    "DYN_DARK_PROBE_TIMEOUT_S", type="float", default=5.0,
    doc="short probe window for quarantined instances (and for waiting out "
        "an empty instance view)", section=ROBUST)
K_RENDEZVOUS_BUDGET_S = register(
    "DYN_RENDEZVOUS_BUDGET_S", type="float", default=0.0,
    doc="hard cap on total rendezvous time across failovers (0 = 3x the "
        "connect timeout)", section=ROBUST)
K_RESUME = register(
    "DYN_RESUME", type="bool", default=True,
    doc="mid-stream resume: re-dispatch a failed stream with a `resume_from` "
        "journal instead of truncating (`0` restores truncation)", section=ROBUST)
K_RESUME_JOURNAL_MAX_ITEMS = register(
    "DYN_RESUME_JOURNAL_MAX_ITEMS", type="int", default=4096,
    doc="max accepted tokens a GenerationJournal retains per request; older "
        "tokens fold into the journal's base prompt so memory stays bounded "
        "on long streams (0 = unbounded)", section=ROBUST)
K_MIGRATE = register(
    "DYN_MIGRATE", type="bool", default=True,
    doc="live session migration: the dispatcher may move an in-flight decode "
        "to another worker (dynctl migrate / drain handoff / planner defrag); "
        "`0` disables the coordinator entirely", section=ROBUST)
K_MIGRATE_FLIP_TIMEOUT_S = register(
    "DYN_MIGRATE_FLIP_TIMEOUT_S", type="float", default=10.0,
    doc="max seconds a migration waits for the consumer loop to commit the "
        "stream flip before aborting back to the source", section=ROBUST)
K_DRAIN_TIMEOUT_S = register(
    "DYN_DRAIN_TIMEOUT_S", type="float", default=30.0,
    doc="graceful drain budget: admissions stop immediately, in-flight work "
        "gets this long to finish or hand off before cancellation", section=ROBUST)
K_KV_DIAL_TIMEOUT_S = register(
    "DYN_KV_DIAL_TIMEOUT_S", type="float", default=5.0,
    doc="KV-transfer pool dial timeout per connection attempt (a black-holed "
        "peer fails the send instead of blocking forever)", section=ROBUST)
K_ADMISSION_MAX_INFLIGHT = register(
    "DYN_ADMISSION_MAX_INFLIGHT", type="int", default=0,
    doc="frontend admission gate: max in-flight requests (0 = off)", section=ROBUST)
K_ADMISSION_QUEUE = register(
    "DYN_ADMISSION_QUEUE", type="int", default=None,
    doc="admission queue depth (default: 2x max in-flight)", section=ROBUST)
K_ADMISSION_QUEUE_TIMEOUT_S = register(
    "DYN_ADMISSION_QUEUE_TIMEOUT_S", type="float", default=2.0,
    doc="max seconds a request may wait in the admission queue", section=ROBUST)
K_ADMISSION_RETRY_AFTER_S = register(
    "DYN_ADMISSION_RETRY_AFTER_S", type="float", default=1.0,
    doc="Retry-After hint attached to shed (429) responses", section=ROBUST)

# -- runtime / deployment plumbing (docs/architecture.md) --------------------
K_CONTROL_PLANE = register(
    "DYN_CONTROL_PLANE", type="str", default="memory",
    doc="control-plane backend (`memory` or `host:port` of a dynctl server)",
    section=ARCH)
K_CACHE_DIR = register(
    "DYN_CACHE_DIR", type="str", default=None,
    doc="artifact/cache directory (default `~/.cache/dynamo_tpu`)", section=ARCH)
K_OFFLINE = register(
    "DYN_OFFLINE", type="bool", default=False,
    doc="never download model artifacts; fail fast on a cache miss", section=ARCH)
K_DISABLE_NATIVE = register(
    "DYN_DISABLE_NATIVE", type="bool", default=False,
    doc="skip the native (C++) data-plane codec and use pure Python", section=ARCH)
K_ALLOW_PRIVATE_IMAGE_URLS = register(
    "DYN_ALLOW_PRIVATE_IMAGE_URLS", type="bool", default=False,
    doc="allow multimodal image fetches from private/internal addresses",
    section=ARCH)
K_TPU_CHIP_COUNT = register(
    "DYN_TPU_CHIP_COUNT", type="int", default=None,
    doc="explicit TPU chip inventory for the SDK allocator (overrides "
        "detection)", section=ARCH)
K_TPU_CHIPS = register(
    "DYN_TPU_CHIPS", type="str", default=None,
    doc="comma-separated chip ids handed to one replica (written by the "
        "allocator into child environments)", section=ARCH)
K_REPLICA_INDEX = register(
    "DYN_REPLICA_INDEX", type="int", default=None,
    doc="replica ordinal the SDK supervisor assigns to each child process",
    section=ARCH)
K_DISABLE_AUTO_TPU_ALLOCATION = register(
    "DYN_DISABLE_AUTO_TPU_ALLOCATION", type="bool", default=False,
    doc="opt a deployment out of automatic per-replica chip partitioning",
    section=ARCH)
K_SERVICE_CONFIG = register(
    "DYN_SERVICE_CONFIG", type="str", default=None,
    doc="path to the service-graph YAML the operator mounts into pods",
    section=ARCH)
K_RUNTIME_CONFIG_PREFIX = register(
    "DYN_RUNTIME", type="str", default=None,
    doc="prefix for layered runtime-config overrides "
        "(`DYN_RUNTIME_<FIELD>`, see utils/config.py)", section=ARCH)
