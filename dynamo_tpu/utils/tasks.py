"""Supervised async task utilities.

Mirrors the reference's ``CriticalTaskExecutionHandle`` (reference:
lib/runtime/src/utils/task.rs): a critical task that fails or panics must take
the whole runtime down rather than leave the process half-alive.
"""

from __future__ import annotations

import asyncio
from collections.abc import Coroutine
from typing import Any, Callable

from dynamo_tpu.utils.logging import get_logger

logger = get_logger("utils.tasks")


def _notify_flight(name: str, exc: BaseException) -> None:
    """Dump the perf flight recorders on a task crash.  Lazy import (tasks
    is near the bottom of the import graph) and best-effort: a crash report
    must never mask the original failure."""
    try:
        from dynamo_tpu.observability import flight

        flight.on_task_crash(name, exc)
    except Exception:  # noqa: BLE001
        logger.debug("flight crash dump failed", exc_info=True)


def _log_if_failed(task: asyncio.Task) -> None:
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        logger.error("background task %s crashed: %r", task.get_name(), exc)
        _notify_flight(task.get_name(), exc)


def spawn_logged(coro: Coroutine, *, name: str | None = None) -> asyncio.Task:
    """``create_task`` with a guaranteed exception surface.

    A raw ``asyncio.ensure_future``/``create_task`` whose handle is only ever
    ``.cancel()``-ed swallows any crash until interpreter shutdown prints
    "Task exception was never retrieved".  This helper attaches a
    done-callback that logs non-cancellation exceptions the moment the task
    dies, so a background loop that crashes is visible in the logs instead of
    silently stopping.  It is the sanctioned spawn path dynlint's
    async-hygiene pass steers fire-and-forget sites toward.
    """
    task = asyncio.ensure_future(coro)
    label = name or getattr(coro, "__qualname__", None)
    if label:
        task.set_name(label)
    task.add_done_callback(_log_if_failed)
    return task


class CriticalTaskGroup:
    """Tracks supervised background tasks.

    - ``spawn(coro)``: plain background task; exceptions are logged.
    - ``spawn_critical(coro)``: if the task raises, ``on_failure`` is invoked
      (typically ``runtime.shutdown``) so the process fails fast.
    - ``cancel_all()``: cancel and await every tracked task.
    """

    def __init__(self, on_failure: Callable[[BaseException], Any] | None = None):
        self._tasks: set[asyncio.Task] = set()
        self._on_failure = on_failure

    def spawn(self, coro: Coroutine, *, name: str | None = None) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._tasks.add(task)
        task.add_done_callback(self._done)
        return task

    def spawn_critical(self, coro: Coroutine, *, name: str | None = None) -> asyncio.Task:
        task = self.spawn(coro, name=name)
        task._dyn_critical = True  # type: ignore[attr-defined]
        return task

    def _done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is None:
            return
        name = task.get_name()
        if getattr(task, "_dyn_critical", False):
            logger.error("critical task %s failed: %r", name, exc)
            _notify_flight(name, exc)
            if self._on_failure is not None:
                self._on_failure(exc)
        else:
            logger.warning("background task %s failed: %r", name, exc)
            _notify_flight(name, exc)

    async def cancel_all(self) -> None:
        tasks = list(self._tasks)
        self._tasks.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    def __len__(self) -> int:
        return len(self._tasks)
