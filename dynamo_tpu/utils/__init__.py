from dynamo_tpu.utils.logging import configure_logging, get_logger
from dynamo_tpu.utils.tasks import CriticalTaskGroup

__all__ = ["configure_logging", "get_logger", "CriticalTaskGroup"]
