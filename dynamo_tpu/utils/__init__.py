from dynamo_tpu.utils.logging import configure_logging, get_logger
from dynamo_tpu.utils.tasks import CriticalTaskGroup, spawn_logged

__all__ = ["configure_logging", "get_logger", "CriticalTaskGroup", "spawn_logged"]
