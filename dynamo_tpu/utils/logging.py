"""Structured logging for dynamo_tpu.

Design mirrors the reference's tracing setup (reference: lib/runtime/src/logging.rs:62,
env filter + optional JSONL output) with Python stdlib logging:

- ``DYN_LOG``          — filter spec, e.g. ``info``, ``debug``,
  ``warn,dynamo_tpu.runtime=debug`` (comma-separated ``target=level`` pairs).
- ``DYN_LOGGING_JSONL``— if set truthy, emit one JSON object per line.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from dynamo_tpu.utils import knobs

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_configured = False

logging.addLevelName(5, "TRACE")


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info and record.exc_info[0] is not None:
            entry["exception"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if extra:
            entry.update(extra)
        return json.dumps(entry, default=str)


class TextFormatter(logging.Formatter):
    default_msec_format = "%s.%03d"

    def __init__(self) -> None:
        super().__init__(
            fmt="%(asctime)s %(levelname)5s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )

    def format(self, record: logging.LogRecord) -> str:
        line = super().format(record)
        fields = getattr(record, "fields", None)
        if fields:
            # same structured fields the JSONL formatter emits, rendered as
            # trailing key=value pairs (request_id correlation in text logs)
            line += " " + " ".join(f"{k}={v}" for k, v in fields.items())
        return line


def _parse_filter(spec: str) -> tuple[int, dict[str, int]]:
    """Parse ``warn,dynamo_tpu.runtime=debug`` into (root_level, {target: level})."""
    root = logging.INFO
    targets: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            target, _, lvl = part.partition("=")
            targets[target.strip()] = _LEVELS.get(lvl.strip().lower(), logging.INFO)
        else:
            root = _LEVELS.get(part.lower(), logging.INFO)
    return root, targets


def configure_logging(level: str | None = None, *, force: bool = False) -> None:
    """Idempotent logging init from DYN_LOG / DYN_LOGGING_JSONL env."""
    global _configured
    if _configured and not force:
        return
    _configured = True

    spec = level or knobs.get("DYN_LOG")
    root_level, targets = _parse_filter(spec)
    jsonl = knobs.get("DYN_LOGGING_JSONL")

    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(JsonlFormatter() if jsonl else TextFormatter())

    root = logging.getLogger("dynamo_tpu")
    root.handlers.clear()
    root.addHandler(handler)
    root.setLevel(root_level)
    root.propagate = False
    for target, lvl in targets.items():
        logging.getLogger(target).setLevel(lvl)


def log_fields(**fields) -> dict:
    """``extra=`` payload attaching structured fields to a log record:
    ``logger.info("done", extra=log_fields(request_id=rid))`` — JSONL output
    merges them into the object, text output appends ``k=v`` pairs."""
    return {"fields": fields}


def get_logger(name: str) -> logging.Logger:
    configure_logging()
    if not name.startswith("dynamo_tpu"):
        name = f"dynamo_tpu.{name}"
    return logging.getLogger(name)
