"""Profiling hooks.

The reference relies on external genai-perf plus ``tracing`` spans
(SURVEY.md §5); on TPU the interesting plane is the device: this wraps
``jax.profiler`` so any engine process can expose traces.

- ``start_server(port)``: serve the profiler so TensorBoard/xprof can attach.
- ``trace(path)``: context manager capturing a trace of the enclosed steps.
- env ``DYN_PROFILER_PORT``: auto-start the profiler server in serving paths.
- env ``DYN_PROFILER_TRACE_DIR``: capture a device trace of the whole engine
  serve window (``maybe_start_trace_from_env`` at engine start,
  ``maybe_stop_trace`` at engine stop) — open the result in TensorBoard /
  xprof, where ``DYN_XPROF_ANNOTATE=1`` span names line up with host spans.
"""

from __future__ import annotations

import contextlib
import os

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils import knobs

logger = get_logger("utils.profiling")

_server_started = False
_trace_dir: str | None = None


def start_server(port: int = 9012) -> None:
    global _server_started
    if _server_started:
        return
    import jax

    jax.profiler.start_server(port)
    _server_started = True
    logger.info("jax profiler server on port %d", port)


def maybe_start_from_env() -> None:
    port = knobs.get("DYN_PROFILER_PORT")
    if port:
        start_server(port)


def maybe_start_trace_from_env() -> str | None:
    """Start a long-running device trace into ``DYN_PROFILER_TRACE_DIR``
    (once per process; the engine serve path calls this at start).  Returns
    the directory when THIS call started the trace, else None — the caller
    that got the directory owns the matching ``maybe_stop_trace``."""
    global _trace_dir
    log_dir = knobs.get("DYN_PROFILER_TRACE_DIR")
    if not log_dir or _trace_dir is not None:
        return None
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception as exc:  # noqa: BLE001 — profiling must never stop serving
        logger.warning("profiler trace start failed: %r", exc)
        return None
    _trace_dir = log_dir
    logger.info("profiler trace capturing to %s", log_dir)
    return log_dir


def maybe_stop_trace() -> None:
    """Stop the env-started trace (no-op when none is active)."""
    global _trace_dir
    if _trace_dir is None:
        return
    import jax

    try:
        jax.profiler.stop_trace()
        logger.info("profiler trace written to %s", _trace_dir)
    except Exception as exc:  # noqa: BLE001
        logger.warning("profiler trace stop failed: %r", exc)
    finally:
        _trace_dir = None


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace: ``with trace('/tmp/tb'): run_steps()``."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str):
    """Named span visible in device traces."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
