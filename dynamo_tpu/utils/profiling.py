"""Profiling hooks.

The reference relies on external genai-perf plus ``tracing`` spans
(SURVEY.md §5); on TPU the interesting plane is the device: this wraps
``jax.profiler`` so any engine process can expose traces.

- ``start_server(port)``: serve the profiler so TensorBoard/xprof can attach.
- ``trace(path)``: context manager capturing a trace of the enclosed steps.
- env ``DYN_PROFILER_PORT``: auto-start in the engine at import.
"""

from __future__ import annotations

import contextlib
import os

from dynamo_tpu.utils.logging import get_logger

logger = get_logger("utils.profiling")

_server_started = False


def start_server(port: int = 9012) -> None:
    global _server_started
    if _server_started:
        return
    import jax

    jax.profiler.start_server(port)
    _server_started = True
    logger.info("jax profiler server on port %d", port)


def maybe_start_from_env() -> None:
    port = os.environ.get("DYN_PROFILER_PORT")
    if port:
        start_server(int(port))


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace: ``with trace('/tmp/tb'): run_steps()``."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str):
    """Named span visible in device traces."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
