"""Layered runtime configuration.

Mirrors the reference's Figment layering (reference: lib/runtime/src/config.rs:80-115):
dataclass defaults < config file (YAML) < environment (``DYN_<PREFIX>_<FIELD>``).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, fields
from pathlib import Path
from typing import Any, Type, TypeVar

import yaml
from dynamo_tpu.utils import knobs

T = TypeVar("T")


def _coerce(value: str, typ: Any) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return value


def load_config(
    cls: Type[T],
    *,
    env_prefix: str,
    config_file: str | Path | None = None,
    overrides: dict[str, Any] | None = None,
) -> T:
    """Build ``cls`` (a dataclass) from defaults, then file, then env, then overrides."""
    values: dict[str, Any] = {}
    if config_file is not None and Path(config_file).exists():
        with open(config_file) as f:
            data = yaml.safe_load(f) or {}
        if not isinstance(data, dict):
            raise ValueError(f"config file {config_file} must contain a mapping")
        values.update(data)

    for field in fields(cls):  # type: ignore[arg-type]
        env_key = f"{env_prefix}_{field.name.upper()}"
        if env_key in os.environ:
            typ = field.type if isinstance(field.type, type) else None
            if typ is None:
                # string annotations: resolve common scalars by default value type
                default = field.default if field.default is not dataclasses.MISSING else None
                typ = type(default) if default is not None else str
            values[field.name] = _coerce(os.environ[env_key], typ)

    if overrides:
        values.update({k: v for k, v in overrides.items() if v is not None})

    known = {f.name for f in fields(cls)}  # type: ignore[arg-type]
    values = {k: v for k, v in values.items() if k in known}
    return cls(**values)


@dataclass
class RuntimeConfig:
    """Top-level runtime knobs (reference: lib/runtime/src/config.rs:31-52)."""

    # Control-plane (discovery + messaging) endpoint, ``host:port`` of a
    # dynctl server, or "memory" for fully in-process static/dev mode.
    control_plane: str = knobs.get("DYN_CONTROL_PLANE")
    # Worker identity
    namespace: str = "dynamo"
    # Graceful shutdown drain window (seconds)
    graceful_shutdown_timeout: float = 30.0
    # TCP data-plane bind host for response streams
    data_host: str = "127.0.0.1"
    data_port: int = 0  # 0 = ephemeral

    @classmethod
    def from_env(cls, **overrides: Any) -> "RuntimeConfig":
        return load_config(cls, env_prefix="DYN_RUNTIME", overrides=overrides)
