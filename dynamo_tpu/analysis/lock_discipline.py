"""lock-discipline pass: threading locks held across ``await``, and asyncio
primitives touched from executor threads.

Two ways this codebase can deadlock or corrupt state that no unit test
reliably reproduces:

- ``with self._lock:`` (a ``threading.Lock``) around an ``await`` parks the
  OS lock while the event loop runs arbitrary other tasks — any of which may
  try to take the same lock from the same thread and deadlock, or from the
  engine thread and stall the device loop;
- a function handed to ``run_in_executor``/``asyncio.to_thread`` runs OFF
  the event-loop thread, where calling asyncio APIs (other than
  ``run_coroutine_threadsafe``/``call_soon_threadsafe``) races loop
  internals.

Detection is token-based: lock identity is the assigned attribute/name of a
``threading.Lock()``-family constructor anywhere in the module.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.core import (
    LOCK_DISCIPLINE,
    Context,
    Finding,
    Module,
    leaf_token,
)

LOCK_CONSTRUCTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}

THREADSAFE_ALLOWED = {
    "asyncio.run_coroutine_threadsafe",
    # reading loop handles / time is fine off-thread
    "asyncio.get_event_loop",
}


def _lock_tokens(mod: Module) -> set[str]:
    tokens: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if mod.dotted(node.value.func) in LOCK_CONSTRUCTORS:
                for target in node.targets:
                    tok = leaf_token(target)
                    if tok:
                        tokens.add(tok)
    return tokens


def _contains_await(body: list[ast.stmt]) -> ast.Await | None:
    """First Await in these statements, not descending into nested defs."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Await):
                return node
    return None


def _check_lock_across_await(mod: Module, findings: list[Finding]) -> None:
    locks = _lock_tokens(mod)
    if not locks:
        return

    class Visitor(ast.NodeVisitor):
        def __init__(self) -> None:
            self.async_stack: list[str] = []

        def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
            self.async_stack.append(node.name)
            self.generic_visit(node)
            self.async_stack.pop()

        def visit_With(self, node: ast.With) -> None:
            if self.async_stack:
                for item in node.items:
                    tok = leaf_token(item.context_expr)
                    if tok in locks:
                        awaited = _contains_await(node.body)
                        if awaited is not None:
                            findings.append(Finding(
                                LOCK_DISCIPLINE, "lock-across-await", mod.rel,
                                awaited.lineno,
                                f"threading lock `{tok}` (taken at line "
                                f"{node.lineno}) is held across an await — "
                                "the event loop runs other tasks while the OS "
                                "lock is parked; use asyncio.Lock or drop the "
                                "lock before awaiting",
                                context=".".join(self.async_stack),
                            ))
            self.generic_visit(node)

    Visitor().visit(mod.tree)


def _executor_targets(mod: Module) -> set[str]:
    """Names of functions handed to run_in_executor / asyncio.to_thread."""
    targets: set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        picked: ast.AST | None = None
        if isinstance(func, ast.Attribute) and func.attr == "run_in_executor":
            if len(node.args) >= 2:
                picked = node.args[1]
        elif mod.dotted(func) == "asyncio.to_thread" and node.args:
            picked = node.args[0]
        if picked is not None:
            tok = leaf_token(picked)
            if tok:
                targets.add(tok)
    return targets


def _check_asyncio_from_thread(mod: Module, findings: list[Finding]) -> None:
    targets = _executor_targets(mod)
    if not targets:
        return
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef) and node.name in targets:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    dotted = mod.dotted(sub.func)
                    if (
                        dotted is not None
                        and dotted.startswith("asyncio.")
                        and dotted not in THREADSAFE_ALLOWED
                    ):
                        findings.append(Finding(
                            LOCK_DISCIPLINE, "asyncio-from-thread", mod.rel,
                            sub.lineno,
                            f"`{dotted}` called inside `{node.name}`, which "
                            "runs on an executor thread — asyncio objects are "
                            "not thread-safe; marshal through "
                            "run_coroutine_threadsafe/call_soon_threadsafe",
                            context=node.name,
                        ))
    return


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for mod in ctx.modules:
        _check_lock_across_await(mod, findings)
        _check_asyncio_from_thread(mod, findings)
    return findings
