"""dynlint — project-native static analysis for dynamo-tpu.

Five AST passes purpose-built for this codebase's failure surfaces (silent
asyncio bugs, JAX hot-path hazards, knob/doc drift, metric-name drift), run
as a tier-1 gate with a baseline ratchet.  See docs/analysis.md for the pass
catalog, suppression syntax, and the ratchet workflow; scripts/dynlint.py is
the CLI.

Stdlib-only on purpose: the gate must run without importing the package
under analysis (no JAX, no prometheus_client).
"""

from __future__ import annotations

from pathlib import Path

from dynamo_tpu.analysis import (
    async_hygiene,
    jit_purity,
    knob_registry,
    lock_discipline,
    metric_names,
)
from dynamo_tpu.analysis.core import (
    ASYNC_HYGIENE,
    BASELINE_NAME,
    JIT_PURITY,
    KNOB_REGISTRY,
    LOCK_DISCIPLINE,
    METRIC_NAMES,
    PASS_IDS,
    SUMMARY_NAME,
    Context,
    Finding,
    apply_pragmas,
    diff_baseline,
    fingerprints,
    load_baseline,
    load_modules,
    write_baseline,
)

PASSES = {
    ASYNC_HYGIENE: async_hygiene.run,
    LOCK_DISCIPLINE: lock_discipline.run,
    JIT_PURITY: jit_purity.run,
    KNOB_REGISTRY: knob_registry.run,
    METRIC_NAMES: metric_names.run,
}

DEFAULT_ROOTS = ("dynamo_tpu", "scripts")


def analyze(
    repo_root: Path, roots: tuple[str, ...] = DEFAULT_ROOTS,
    passes: tuple[str, ...] | None = None,
) -> tuple[list[Finding], dict]:
    """Run the selected passes; -> (pragma-filtered findings, summary dict).

    The summary carries per-pass found/suppressed counts — the artifact CI
    diffs across PRs the way SCENARIO_SOAK.json diffs soak results.
    """
    modules, load_findings = load_modules(repo_root, list(roots))
    ctx = Context(repo_root=Path(repo_root), modules=modules)
    raw: list[Finding] = list(load_findings)
    selected = passes or tuple(PASSES)
    per_pass_found: dict[str, int] = {}
    for pass_id in selected:
        produced = PASSES[pass_id](ctx)
        per_pass_found[pass_id] = len(produced)
        raw.extend(produced)
    findings, suppressed = apply_pragmas(modules, raw)
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id, f.rule))
    summary = {
        "files_scanned": len(modules),
        "findings": len(findings),
        "suppressed": suppressed,
        "per_pass": {
            pass_id: sum(1 for f in findings if f.pass_id == pass_id)
            for pass_id in (*selected, "pragma")
        },
        "per_pass_pre_suppression": per_pass_found,
    }
    return findings, summary


__all__ = [
    "PASSES", "PASS_IDS", "DEFAULT_ROOTS", "BASELINE_NAME", "SUMMARY_NAME",
    "Context", "Finding", "analyze", "apply_pragmas", "diff_baseline",
    "fingerprints", "load_baseline", "load_modules", "write_baseline",
]
