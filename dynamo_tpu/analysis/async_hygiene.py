"""async-hygiene pass: blocking calls in coroutines, unawaited coroutines,
and fire-and-forget task spawns with no exception surface.

Fire-and-forget is the rule that found the real bugs this framework was
built for: a raw ``asyncio.ensure_future``/``create_task`` whose Task handle
is neither consumed by an ``await``/``gather``/``wait`` nor given an
``add_done_callback`` swallows its exception until interpreter GC prints
"Task exception was never retrieved" — long after the background loop died.
The sanctioned spawn path is ``dynamo_tpu/utils/tasks.py`` (``spawn_logged``
/ ``CriticalTaskGroup``), which is the one module this pass exempts.

Heuristics (tuned for this tree; module-wide, not flow-sensitive):

- a spawn whose value is discarded (bare expression statement) is always a
  finding;
- a spawn assigned to a name/attribute (or appended/collected into one) is a
  finding unless that token is *surfaced* somewhere in the module: awaited,
  passed through ``asyncio.gather``/``wait``/``wait_for``/``shield``, or
  given an ``add_done_callback``;
- a spawn consumed directly as an argument (``await gather(spawn(...))``) or
  returned is fine.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.core import (
    ASYNC_HYGIENE,
    Context,
    Finding,
    Module,
    attach_parents,
    leaf_token,
    parent_of,
)

SANCTIONED_MODULES = ("utils/tasks.py",)

BLOCKING_CALLS = {
    "time.sleep": "time.sleep blocks the event loop; use asyncio.sleep",
    "os.system": "os.system blocks the event loop; use asyncio.create_subprocess_shell",
    "socket.create_connection": "sync socket I/O blocks the event loop; use asyncio.open_connection",
    "urllib.request.urlopen": "sync HTTP blocks the event loop; use an async client or to_thread",
}
for _fn in ("run", "call", "check_call", "check_output", "Popen", "getoutput",
            "getstatusoutput"):
    BLOCKING_CALLS[f"subprocess.{_fn}"] = (
        f"subprocess.{_fn} blocks the event loop; use asyncio.create_subprocess_exec"
    )
for _fn in ("get", "post", "put", "patch", "delete", "head", "request"):
    BLOCKING_CALLS[f"requests.{_fn}"] = (
        f"requests.{_fn} blocks the event loop; use an async client or to_thread"
    )

SPAWN_DOTTED = {"asyncio.ensure_future", "asyncio.create_task"}
LOOP_FACTORY_DOTTED = {"asyncio.get_event_loop()", "asyncio.get_running_loop()"}
LOOP_NAME_HINTS = {"loop", "_loop", "event_loop"}
GATHER_DOTTED = {"asyncio.gather", "asyncio.wait", "asyncio.wait_for", "asyncio.shield"}


def _is_spawn(mod: Module, call: ast.Call) -> bool:
    dotted = mod.dotted(call.func)
    if dotted in SPAWN_DOTTED:
        return True
    if isinstance(call.func, ast.Attribute) and call.func.attr == "create_task":
        base = mod.dotted(call.func.value)
        if base in LOOP_FACTORY_DOTTED:
            return True
        base_leaf = leaf_token(call.func.value)
        if base_leaf in LOOP_NAME_HINTS:
            return True
    return False


def _surfaced_tokens(mod: Module) -> set[str]:
    """Module-wide set of handle tokens that have an exception surface."""
    tokens: set[str] = set()

    def collect_names(node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute, ast.Starred)):
                tok = leaf_token(sub)
                if tok:
                    tokens.add(tok)

    awaited_names: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Await):
            inner = node.value
            if isinstance(inner, (ast.Name, ast.Attribute, ast.Subscript)):
                tok = leaf_token(inner)
                if tok:
                    tokens.add(tok)
                    awaited_names.add(tok)
            elif isinstance(inner, ast.Call):
                if mod.dotted(inner.func) in GATHER_DOTTED:
                    for arg in inner.args:
                        collect_names(arg)
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and node.func.attr == "add_done_callback":
                tok = leaf_token(node.func.value)
                if tok:
                    tokens.add(tok)
    # `for t in tasks: await t` surfaces the *collection* token too
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            target_tok = leaf_token(node.target)
            if target_tok and target_tok in awaited_names:
                tok = leaf_token(node.iter)
                if tok:
                    tokens.add(tok)
    return tokens


def _spawn_sink(node: ast.Call) -> tuple[str, str | None]:
    """Classify how a spawn's Task handle is consumed.

    -> ("discarded", None) | ("token", token) | ("consumed", None)
    """
    child: ast.AST = node
    parent = parent_of(node)
    while parent is not None:
        if isinstance(parent, ast.Expr):
            return "discarded", None
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = parent.targets if isinstance(parent, ast.Assign) else [parent.target]
            for target in targets:
                tok = leaf_token(target)
                if tok:
                    return "token", tok
            return "consumed", None  # tuple-unpack etc: assume handled
        if isinstance(parent, ast.Call) and parent is not node:
            if child in parent.args or any(
                child is kw.value for kw in parent.keywords
            ) or any(
                isinstance(a, ast.Starred) and a.value is child for a in parent.args
            ):
                func = parent.func
                if isinstance(func, ast.Attribute) and func.attr in ("append", "add", "insert"):
                    tok = leaf_token(func.value)
                    if tok:
                        return "token", tok
                return "consumed", None
            # we were the .func of a chained call — keep climbing
        if isinstance(parent, (ast.Return, ast.Await, ast.Yield, ast.YieldFrom)):
            return "consumed", None
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module,
                               ast.ClassDef)):
            return "consumed", None
        child, parent = parent, parent_of(parent)
    return "consumed", None


class _FuncStack(ast.NodeVisitor):
    """Walk with an innermost-function-kind stack shared by the sub-rules."""

    def __init__(self, mod: Module, async_defs: set[str], surfaced: set[str],
                 findings: list[Finding]):
        self.mod = mod
        self.async_defs = async_defs
        self.surfaced = surfaced
        self.findings = findings
        self.stack: list[ast.AST] = []  # FunctionDef / AsyncFunctionDef

    # -- scope tracking
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    def _context(self) -> str:
        return ".".join(getattr(f, "name", "?") for f in self.stack)

    def _in_async(self) -> bool:
        return bool(self.stack) and isinstance(self.stack[-1], ast.AsyncFunctionDef)

    # -- rules
    def visit_Call(self, node: ast.Call) -> None:
        mod = self.mod
        dotted = mod.dotted(node.func)
        if self._in_async() and dotted in BLOCKING_CALLS:
            self.findings.append(Finding(
                ASYNC_HYGIENE, "blocking-call", mod.rel, node.lineno,
                BLOCKING_CALLS[dotted], context=self._context(),
            ))
        if _is_spawn(mod, node):
            sink, token = _spawn_sink(node)
            if sink == "discarded" or (sink == "token" and token not in self.surfaced):
                handle = "discarded" if sink == "discarded" else f"`{token}` is never awaited or given a done-callback"
                self.findings.append(Finding(
                    ASYNC_HYGIENE, "fire-and-forget", mod.rel, node.lineno,
                    f"task spawn with no exception surface ({handle}); "
                    "use utils.tasks.spawn_logged / CriticalTaskGroup",
                    context=self._context(),
                ))
        elif isinstance(parent_of(node), ast.Expr) and not node.keywords:
            # Bare statement calling a same-module coroutine function.  Only
            # `f(...)` and `self.f(...)`/`cls.f(...)` receivers: an arbitrary
            # `obj.close()` may be a *different* class's sync method that
            # happens to share a name with an async def here (StreamWriter
            # .close vs our async close), which we cannot resolve.
            name: str | None = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("self", "cls")
            ):
                name = node.func.attr
            if name in self.async_defs:
                self.findings.append(Finding(
                    ASYNC_HYGIENE, "unawaited-coroutine", mod.rel, node.lineno,
                    f"result of coroutine function `{name}` is discarded "
                    "without await — the body never runs",
                    context=self._context(),
                ))
        self.generic_visit(node)


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for mod in ctx.modules:
        if mod.rel.endswith(SANCTIONED_MODULES):
            continue
        attach_parents(mod.tree)
        async_defs = {
            n.name for n in ast.walk(mod.tree) if isinstance(n, ast.AsyncFunctionDef)
        }
        # a same-named sync def anywhere in the module makes the name ambiguous
        sync_defs = {
            n.name for n in ast.walk(mod.tree) if isinstance(n, ast.FunctionDef)
        }
        surfaced = _surfaced_tokens(mod)
        _FuncStack(mod, async_defs - sync_defs, surfaced, findings).visit(mod.tree)
    return findings
