"""metric-names pass: the metrics contract, enforced both statically and at
render time.

PR 5 froze the metric-naming conventions with a rendered-exposition lint
(tests/llm/test_metric_lint.py).  This module is now the single home of
those rules — ``dyn_`` prefix, canonical unit suffixes (``_seconds`` for
time, ``_total`` for counters, ``_perc``/``_ratio`` for fractions; never
``_ms``/``_pct``/``_count``), no duplicate family declarations:

- :func:`lint_family_name` / :func:`lint_exposition` — shared rule
  functions; the old tier-1 test imports these and keeps running against
  the *rendered* registries (requires prometheus_client).
- :func:`run` — the pure-AST dynlint pass: it lints family-name string
  literals at ``Counter(...)``/``Gauge(...)``/``Histogram(...)``
  construction sites (resolving ``f"{PREFIX}_..."`` against module
  constants), so a bad name fails the lint gate even in environments where
  the registry never renders.
"""

from __future__ import annotations

import ast
import re

from dynamo_tpu.analysis.core import Context, Finding, METRIC_NAMES, Module

NAME_RE = re.compile(r"^dyn_[a-z0-9_]+$")

# unit spellings that have a canonical form in this repo
FORBIDDEN_SUFFIXES = (
    "_ms", "_us", "_millis", "_milliseconds", "_microseconds", "_sec",
    "_secs", "_percent", "_pct", "_count", "_num",
)

TIME_TOKENS = ("duration", "latency", "_time_")

_TYPE_RE = re.compile(r"^# TYPE (\S+) (\S+)$", re.MULTILINE)

PROM_CONSTRUCTORS = {"Counter", "Gauge", "Histogram", "Summary", "Info"}


def lint_family_name(name: str, *, metric_type: str | None = None) -> list[str]:
    """Problems with one metric family name (empty list = clean)."""
    problems: list[str] = []
    if not NAME_RE.match(name):
        problems.append(f"{name}: not dyn_-prefixed lower_snake")
    for suffix in FORBIDDEN_SUFFIXES:
        if name.endswith(suffix):
            problems.append(f"{name}: forbidden unit suffix {suffix}")
    if any(tok in name for tok in TIME_TOKENS) and not (
        name.endswith("_seconds") or name.endswith("_seconds_total")
    ):
        problems.append(f"{name}: time-valued family must end in _seconds")
    if metric_type == "counter" and not name.endswith("_total"):
        problems.append(f"{name}: counter families must end in _total")
    return problems


def lint_exposition(text: str, families: set[str]) -> list[str]:
    """Problems across a rendered Prometheus exposition (the render-time
    twin of the AST pass; ``families`` comes from the caller's scrape
    parser so frontend and worker surfaces share one implementation)."""
    problems: list[str] = []
    for name in sorted(families):
        problems.extend(lint_family_name(name))
    for name, mtype in _TYPE_RE.findall(text):
        if mtype == "counter" and not name.endswith("_total"):
            problems.append(f"{name}: counter families must end in _total")
    return problems


def _family_literal(mod: Module, node: ast.AST) -> str | None:
    """Resolve a constructor's name argument: plain literal, module
    constant, or an f-string whose placeholders are module constants."""
    direct = mod.literal_str(node)
    if direct is not None:
        return direct
    if isinstance(node, ast.JoinedStr):
        parts: list[str] = []
        for value in node.values:
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                parts.append(value.value)
            elif isinstance(value, ast.FormattedValue):
                resolved = mod.literal_str(value.value)
                if resolved is None:
                    return None  # dynamic segment: not lintable statically
                parts.append(resolved)
            else:
                return None
        return "".join(parts)
    return None


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for mod in ctx.modules:
        uses_prometheus = any(
            origin.startswith("prometheus_client") for origin in mod.imports.values()
        )
        if not uses_prometheus:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            ctor = (
                func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute) else None
            )
            if ctor not in PROM_CONSTRUCTORS or not node.args:
                continue
            name = _family_literal(mod, node.args[0])
            if name is None:
                continue
            metric_type = "counter" if ctor == "Counter" else None
            for problem in lint_family_name(name, metric_type=metric_type):
                findings.append(Finding(
                    METRIC_NAMES, "bad-family-name", mod.rel, node.lineno,
                    problem, context=name,
                ))
    return findings
