"""dynlint core: module loading, pragmas, findings, and the baseline ratchet.

The analyzer is deliberately pure-AST and stdlib-only — it runs in tier-1
without importing the package under analysis (no JAX, no prometheus_client),
so a broken runtime import can never take the lint gate down with it.

Key pieces:

- :class:`Module` — one parsed source file with its import map, module-level
  string constants, and ``# dynlint: disable=`` pragma table.
- :class:`Finding` — one diagnostic; its :func:`fingerprint` is line-free
  (pass, path, rule, enclosing context + occurrence ordinal) so baselines
  survive unrelated edits to the same file.
- :func:`apply_pragmas` — drops findings suppressed at their line; a
  suppression without a reason is itself a finding (``pragma`` pass).
- :func:`diff_baseline` — the ratchet: NEW findings (not in the recorded
  baseline) fail; findings IN the baseline pass; a baseline entry with no
  surviving finding fails too ("stale"), forcing the baseline to be
  re-recorded (``--write-baseline``) so recorded debt only ever shrinks
  deliberately.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

BASELINE_NAME = "ANALYSIS_BASELINE.json"
SUMMARY_NAME = "ANALYSIS_SUMMARY.json"

# pass ids (the ``pragma`` pseudo-pass carries suppression-syntax findings)
ASYNC_HYGIENE = "async-hygiene"
LOCK_DISCIPLINE = "lock-discipline"
JIT_PURITY = "jit-purity"
KNOB_REGISTRY = "knob-registry"
METRIC_NAMES = "metric-names"
PRAGMA = "pragma"

PASS_IDS = (ASYNC_HYGIENE, LOCK_DISCIPLINE, JIT_PURITY, KNOB_REGISTRY, METRIC_NAMES)

# pass list stops at "--" (the reason separator) — pass names themselves may
# contain single hyphens, so the list group is non-greedy with an anchored tail
_PRAGMA_RE = re.compile(
    r"#\s*dynlint:\s*disable=([a-zA-Z0-9_,\- ]+?)(?:\s*--\s*(.*))?$"
)


@dataclass(frozen=True)
class Finding:
    pass_id: str
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    context: str = ""  # enclosing function/class qualname (fingerprint key)

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        ctx = f" [{self.context}]" if self.context else ""
        return f"{where}: {self.pass_id}/{self.rule}: {self.message}{ctx}"


def fingerprints(findings: list[Finding]) -> dict[str, int]:
    """Line-free fingerprint -> count (counts make repeats in one context
    ratchet-able without encoding line numbers)."""
    counts: dict[str, int] = {}
    for f in findings:
        key = f"{f.pass_id}|{f.path}|{f.rule}|{f.context}"
        counts[key] = counts.get(key, 0) + 1
    return counts


class Module:
    """One parsed source file plus the lookup tables every pass wants."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source)
        self.lines = source.splitlines()
        # line -> set of disabled pass ids; line -> reason text
        self.pragma_lines: dict[int, set[str]] = {}
        self.pragma_reasons: dict[int, str] = {}
        self.pragma_findings: list[Finding] = []
        self._scan_pragmas()
        # local name -> dotted origin ("np" -> "numpy", "sleep" -> "time.sleep")
        self.imports: dict[str, str] = {}
        # module-level UPPER_CASE string constants (resolves env-name aliases)
        self.constants: dict[str, str] = {}
        self._scan_top_level()

    # -- pragmas -----------------------------------------------------------
    def _scan_pragmas(self) -> None:
        for idx, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if m is None:
                continue
            passes = {p.strip() for p in m.group(1).split(",") if p.strip()}
            reason = (m.group(2) or "").strip()
            # a pragma on a comment-only line suppresses the NEXT line
            target = idx + 1 if text.strip().startswith("#") else idx
            self.pragma_lines.setdefault(target, set()).update(passes)
            self.pragma_reasons[target] = reason
            unknown = passes - set(PASS_IDS)
            if unknown:
                self.pragma_findings.append(Finding(
                    PRAGMA, "unknown-pass", self.rel, idx,
                    f"pragma disables unknown pass(es): {', '.join(sorted(unknown))}",
                ))
            if len(reason) < 3:
                self.pragma_findings.append(Finding(
                    PRAGMA, "missing-reason", self.rel, idx,
                    "suppression must carry a reason: "
                    "`# dynlint: disable=<pass> -- <why this is safe>`",
                ))

    def suppressed(self, pass_id: str, line: int) -> bool:
        return pass_id in self.pragma_lines.get(line, set())

    # -- imports / constants ----------------------------------------------
    def _scan_top_level(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                prefix = node.module or ""
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        f"{prefix}.{alias.name}" if prefix else alias.name
                    )
        for node in self.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                self.constants[node.targets[0].id] = node.value.value

    def dotted(self, node: ast.AST) -> str | None:
        """Dotted origin of an expression: ``np.asarray`` -> ``numpy.asarray``,
        ``asyncio.get_running_loop().create_task`` ->
        ``asyncio.get_running_loop().create_task``.  None when unresolvable."""
        if isinstance(node, ast.Name):
            return self.imports.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.dotted(node.value)
            return None if base is None else f"{base}.{node.attr}"
        if isinstance(node, ast.Call):
            base = self.dotted(node.func)
            return None if base is None else f"{base}()"
        return None

    def literal_str(self, node: ast.AST) -> str | None:
        """A string literal, or a Name resolving to a module-level string
        constant (``os.environ.get(ALLOW_PRIVATE_ENV)``)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self.constants.get(node.id)
        return None


def leaf_token(node: ast.AST) -> str | None:
    """The rightmost identifier of a Name/Attribute/Subscript chain — the
    token two sites share when they talk about the same handle
    (``self._read_task`` -> ``_read_task``; ``tasks[k]`` -> ``tasks``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return leaf_token(node.value)
    if isinstance(node, ast.Starred):
        return leaf_token(node.value)
    return None


def attach_parents(tree: ast.Module) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._dynlint_parent = node  # type: ignore[attr-defined]


def parent_of(node: ast.AST) -> ast.AST | None:
    return getattr(node, "_dynlint_parent", None)


@dataclass
class Context:
    """What passes get besides the module list."""

    repo_root: Path
    modules: list[Module] = field(default_factory=list)

    def module(self, rel_suffix: str) -> Module | None:
        for m in self.modules:
            if m.rel.endswith(rel_suffix):
                return m
        return None

    def docs_text(self) -> str:
        """Concatenated docs corpus the knob pass checks names against."""
        chunks = []
        docs = self.repo_root / "docs"
        if docs.is_dir():
            for page in sorted(docs.glob("*.md")):
                chunks.append(page.read_text())
        readme = self.repo_root / "README.md"
        if readme.exists():
            chunks.append(readme.read_text())
        return "\n".join(chunks)


def load_modules(repo_root: Path, roots: list[str]) -> tuple[list[Module], list[Finding]]:
    modules: list[Module] = []
    findings: list[Finding] = []
    seen: set[Path] = set()
    for root in roots:
        base = (repo_root / root).resolve()
        paths = [base] if base.is_file() else sorted(base.rglob("*.py"))
        for path in paths:
            if path in seen or "__pycache__" in path.parts:
                continue
            seen.add(path)
            rel = path.relative_to(repo_root).as_posix()
            try:
                modules.append(Module(path, rel, path.read_text()))
            except SyntaxError as exc:
                findings.append(Finding(
                    PRAGMA, "parse-error", rel, exc.lineno or 0,
                    f"file does not parse: {exc.msg}",
                ))
    return modules, findings


def apply_pragmas(modules: list[Module], findings: list[Finding]) -> tuple[list[Finding], int]:
    """Drop suppressed findings; append pragma-syntax findings."""
    by_rel = {m.rel: m for m in modules}
    kept: list[Finding] = []
    suppressed = 0
    for f in findings:
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressed(f.pass_id, f.line):
            suppressed += 1
            continue
        kept.append(f)
    for mod in modules:
        kept.extend(mod.pragma_findings)
    return kept, suppressed


# -- baseline ratchet -------------------------------------------------------

def load_baseline(path: Path) -> dict[str, int]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return dict(data.get("counts", {}))


def write_baseline(path: Path, findings: list[Finding]) -> None:
    payload = {
        "version": 1,
        "note": "dynlint debt baseline — regenerate with scripts/dynlint.py "
                "--write-baseline after deliberately paying down or accepting "
                "debt; CI fails on new findings AND on stale entries here.",
        "counts": dict(sorted(fingerprints(findings).items())),
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")


def diff_baseline(
    findings: list[Finding], baseline: dict[str, int]
) -> tuple[list[Finding], list[str]]:
    """-> (new findings beyond the baseline, stale baseline fingerprints)."""
    current = fingerprints(findings)
    new: list[Finding] = []
    budget = dict(baseline)
    # deterministic order so "which occurrence is new" is stable
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        key = f"{f.pass_id}|{f.path}|{f.rule}|{f.context}"
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            new.append(f)
    stale = sorted(
        key for key, count in baseline.items() if current.get(key, 0) < count
    )
    return new, stale
