"""jit-purity pass: host syncs and impure Python inside jit-reachable code.

Builds, per module, the set of *jit roots* — functions decorated with
``@jax.jit`` / ``@partial(jax.jit, ...)`` or wrapped via
``name = jax.jit(fn)`` — then walks the intra-module call graph reachable
from them (plain ``f(...)`` and ``self.f(...)`` edges) and flags operations
that force a device→host sync or break tracing purity:

- ``.item()`` / ``.tolist()`` — forces a blocking device readback; inside a
  jitted trace it is an escape hatch that either fails or silently falls
  back to eager;
- ``jax.device_get`` / ``.block_until_ready()`` — explicit host syncs;
- ``np.asarray`` / ``np.array`` / ``np.frombuffer`` on a tracer — silently
  materializes on host and constant-folds into the compiled graph;
- ``print`` and ``time.time``-family calls — trace-time side effects that
  fire once per *compile*, not per step, which is never what the author
  meant in a step function.

The decode retire/readback seams in ``engine/engine.py`` legitimately sync —
they are host-side; suppress with an inline pragma (disable=jit-purity plus
a reason) where the call graph cannot see the jit boundary.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.core import Context, Finding, JIT_PURITY, Module

JIT_WRAPPERS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
PARTIAL_NAMES = {"functools.partial", "partial"}

HOST_SYNC_METHODS = {
    "item": "`.item()` forces a blocking device->host readback",
    "tolist": "`.tolist()` forces a blocking device->host readback",
    "block_until_ready": "`.block_until_ready()` is an explicit host sync",
}
HOST_SYNC_DOTTED = {
    "jax.device_get": "`jax.device_get` is an explicit host sync",
    "numpy.asarray": "`np.asarray` on a tracer materializes it on host",
    "numpy.array": "`np.array` on a tracer materializes it on host",
    "numpy.frombuffer": "`np.frombuffer` inside jitted code is host-only",
}
TRACE_TIME_EFFECTS = {
    "print": "`print` inside jitted code fires at trace time, once per compile",
    "time.time": "`time.time` inside jitted code is evaluated at trace time",
    "time.perf_counter": "`time.perf_counter` inside jitted code is evaluated at trace time",
    "time.monotonic": "`time.monotonic` inside jitted code is evaluated at trace time",
}


def _is_jit_expr(mod: Module, node: ast.AST) -> bool:
    """True for ``jax.jit``, ``partial(jax.jit, ...)`` and ``jax.jit(...)``
    used as a decorator expression."""
    if mod.dotted(node) in JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        dotted = mod.dotted(node.func)
        if dotted in JIT_WRAPPERS:
            return True
        if dotted in PARTIAL_NAMES and node.args and _is_jit_expr(mod, node.args[0]):
            return True
    return False


def _collect(mod: Module) -> tuple[dict[str, ast.AST], set[str]]:
    """-> (function name -> def node, jit root names)."""
    functions: dict[str, ast.AST] = {}
    roots: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            functions.setdefault(node.name, node)
            if any(_is_jit_expr(mod, d) for d in node.decorator_list):
                roots.add(node.name)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if _is_jit_expr(mod, call.func) or (
                mod.dotted(call.func) in JIT_WRAPPERS
            ):
                # name = jax.jit(fn) / self._step_fn = jax.jit(self._step)
                for arg in call.args[:1]:
                    if isinstance(arg, ast.Name):
                        roots.add(arg.id)
                    elif isinstance(arg, ast.Attribute):
                        roots.add(arg.attr)
    return functions, roots


def _reachable(functions: dict[str, ast.AST], roots: set[str]) -> set[str]:
    seen: set[str] = set()
    frontier = [r for r in roots if r in functions]
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        node = functions[name]
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                callee: str | None = None
                if isinstance(sub.func, ast.Name):
                    callee = sub.func.id
                elif isinstance(sub.func, ast.Attribute) and isinstance(
                    sub.func.value, ast.Name
                ) and sub.func.value.id in ("self", "cls"):
                    callee = sub.func.attr
                if callee and callee in functions and callee not in seen:
                    frontier.append(callee)
    return seen


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for mod in ctx.modules:
        functions, roots = _collect(mod)
        if not roots:
            continue
        for name in sorted(_reachable(functions, roots)):
            node = functions[name]
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) and sub is not node:
                    continue  # nested defs get their own entry if reachable
                if not isinstance(sub, ast.Call):
                    continue
                message: str | None = None
                if isinstance(sub.func, ast.Attribute) and sub.func.attr in HOST_SYNC_METHODS:
                    message = HOST_SYNC_METHODS[sub.func.attr]
                else:
                    dotted = mod.dotted(sub.func)
                    if dotted in HOST_SYNC_DOTTED:
                        message = HOST_SYNC_DOTTED[dotted]
                    elif dotted in TRACE_TIME_EFFECTS:
                        message = TRACE_TIME_EFFECTS[dotted]
                if message is not None:
                    findings.append(Finding(
                        JIT_PURITY, "host-sync", mod.rel, sub.lineno,
                        f"{message} (reachable from a @jax.jit root)",
                        context=name,
                    ))
    return findings
