"""knob-registry pass: every ``DYN_*`` environment read goes through the
typed registry in ``dynamo_tpu/utils/knobs.py`` and every registered knob is
documented.

The registry itself is read *statically*: ``register("DYN_X", ...)`` calls
are literal by design, so this pass — like the rest of dynlint — never
imports the package under analysis.

Rules:

- ``raw-env-read``: ``os.environ.get/[]``, ``os.getenv``, or any
  ``<mapping>.get("DYN_...")`` outside knobs.py.  Reads through mapping
  parameters count too (they read a process environment by convention —
  ``knobs.get(name, env=...)`` covers that case).  Env *writes*
  (``os.environ["DYN_X"] = ...``) are allowed: that is how supervisors
  configure children.
- ``unregistered-knob``: a ``knobs.get``/``get_raw`` call naming a knob the
  registry does not declare (would raise KeyError at runtime; caught here).
- ``undocumented-knob``: a registered knob whose literal name appears
  nowhere under ``docs/`` or in README.md.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.core import Context, Finding, KNOB_REGISTRY, Module

KNOBS_MODULE_SUFFIX = "utils/knobs.py"
KNOB_PREFIX = "DYN_"
ENV_READERS = {"os.environ.get", "os.getenv", "environ.get"}
KNOB_READERS = {"get", "get_raw", "is_set"}


def registered_knobs(mod: Module) -> dict[str, int]:
    """Knob name -> registration line, parsed from knobs.py's AST."""
    names: dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "register"
            and node.args
        ):
            name = mod.literal_str(node.args[0])
            if name:
                names[name] = node.lineno
    return names


def _knob_read_name(mod: Module, call: ast.Call) -> str | None:
    """The DYN_* literal a ``knobs.get(...)``-style call names, if any."""
    func = call.func
    if not (isinstance(func, ast.Attribute) and func.attr in KNOB_READERS):
        return None
    base = mod.dotted(func.value)
    if base is None or not base.endswith("knobs"):
        return None
    if not call.args:
        return None
    name = mod.literal_str(call.args[0])
    if name and name.startswith(KNOB_PREFIX):
        return name
    return None


def _raw_env_read(mod: Module, node: ast.AST) -> tuple[str, int] | None:
    """-> (knob name, line) for a raw environment read of a DYN_* name."""
    if isinstance(node, ast.Call):
        func = node.func
        if not isinstance(func, ast.Attribute):
            # os.getenv imported bare as getenv
            if mod.dotted(func) == "os.getenv" and node.args:
                name = mod.literal_str(node.args[0])
                if name and name.startswith(KNOB_PREFIX):
                    return name, node.lineno
            return None
        dotted = mod.dotted(func)
        if dotted in ENV_READERS or (func.attr == "get" and node.args):
            if dotted is not None and dotted.endswith("knobs.get"):
                return None
            if node.args:
                name = mod.literal_str(node.args[0])
                if name and name.startswith(KNOB_PREFIX):
                    return name, node.lineno
        return None
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        base = mod.dotted(node.value)
        if base in ("os.environ", "environ"):
            name = mod.literal_str(node.slice)
            if name and name.startswith(KNOB_PREFIX):
                return name, node.lineno
    return None


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    knobs_mod = ctx.module(KNOBS_MODULE_SUFFIX)
    registry: dict[str, int] = {}
    if knobs_mod is None:
        findings.append(Finding(
            KNOB_REGISTRY, "no-registry", "dynamo_tpu/utils/knobs.py", 0,
            "knob registry module not found under the scanned roots",
        ))
    else:
        registry = registered_knobs(knobs_mod)

    for mod in ctx.modules:
        if mod.rel.endswith(KNOBS_MODULE_SUFFIX):
            continue
        for node in ast.walk(mod.tree):
            raw = _raw_env_read(mod, node)
            if raw is not None:
                name, line = raw
                extra = "" if name in registry else " (and it is not registered)"
                findings.append(Finding(
                    KNOB_REGISTRY, "raw-env-read", mod.rel, line,
                    f"raw environment read of `{name}`{extra}; route through "
                    "utils/knobs.py (`knobs.get`)",
                    context=name,
                ))
            elif isinstance(node, ast.Call):
                name = _knob_read_name(mod, node)
                if name is not None and name not in registry:
                    findings.append(Finding(
                        KNOB_REGISTRY, "unregistered-knob", mod.rel, node.lineno,
                        f"`{name}` read through knobs.get but never "
                        "registered — this raises KeyError at runtime",
                        context=name,
                    ))

    if knobs_mod is not None and registry:
        docs = ctx.docs_text()
        for name, line in sorted(registry.items()):
            if name not in docs:
                findings.append(Finding(
                    KNOB_REGISTRY, "undocumented-knob", knobs_mod.rel, line,
                    f"registered knob `{name}` appears nowhere under docs/ "
                    "or README.md — add its table row "
                    "(scripts/dynlint.py --knob-table prints one)",
                    context=name,
                ))
    return findings
