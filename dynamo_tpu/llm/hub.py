"""Model resolution: local path | cache | HuggingFace Hub download.

The reference resolves model names through the HF hub with a local content
cache (lib/llm/src/hub.rs:32 ``from_hf`` — volume-mounted cache keyed by
repo, skip-if-present download of config/tokenizer/weights).  Same contract
here:

- an existing local directory (or GGUF file) is used as-is;
- otherwise ``{cache}/hub/{org}--{repo}`` is checked;
- otherwise the repo is downloaded into the cache via ``huggingface_hub``
  (offline/air-gapped environments get a clear error instead of a hang —
  pass ``allow_download=False`` or set ``DYN_OFFLINE=1`` to skip the
  network entirely).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Callable

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils import knobs

logger = get_logger("llm.hub")

# files a serving worker needs: model card + tokenizer + weights
DOWNLOAD_PATTERNS = [
    "config.json",
    "generation_config.json",
    "tokenizer.json",
    "tokenizer.model",  # SPM-only repos ship this instead of tokenizer.json
    "tokenizer_config.json",
    "special_tokens_map.json",
    "*.safetensors",
    "*.safetensors.index.json",
]


def cache_base(cache_dir: str | Path | None = None) -> Path:
    """Shared on-disk cache root (hub snapshots, MDC artifacts)."""
    return Path(
        cache_dir
        or knobs.get("DYN_CACHE_DIR")
        or Path.home() / ".cache" / "dynamo_tpu"
    )


def _hf_download(repo_id: str, dest: Path) -> None:
    """Default downloader: huggingface_hub snapshot into ``dest``."""
    from huggingface_hub import snapshot_download

    snapshot_download(
        repo_id=repo_id,
        local_dir=str(dest),
        allow_patterns=DOWNLOAD_PATTERNS,
    )


def classify_model_dir(path: Path) -> str:
    """One classification for resolution decisions:
    - "complete": config + a loadable tokenizer;
    - "unloadable_spm": only an SPM tokenizer.model and the conversion
      deps (sentencepiece/transformers) are missing — actionable error;
    - "incomplete": anything else (download / keep looking)."""
    from dynamo_tpu.llm.tokenizer import spm_conversion_available

    if not (path / "config.json").exists():
        return "incomplete"
    if (path / "tokenizer.json").exists():
        return "complete"
    if (path / "tokenizer.model").exists():
        return "complete" if spm_conversion_available() else "unloadable_spm"
    return "incomplete"


def is_complete(path: Path) -> bool:
    return classify_model_dir(path) == "complete"


def resolve_model(
    name_or_path: str | Path,
    *,
    cache_dir: str | Path | None = None,
    downloader: Callable[[str, Path], None] | None = None,
    allow_download: bool = True,
) -> Path:
    """Resolve a model reference to a local directory (or GGUF file).

    ``downloader(repo_id, dest)`` is injectable for tests and air-gapped
    mirrors; the default uses ``huggingface_hub``.
    """
    p = Path(name_or_path)
    if p.exists():
        return p

    name = str(name_or_path)
    if name.startswith((".", "/")) or "/" not in name:
        raise FileNotFoundError(f"model path {name!r} does not exist")

    dest = cache_base(cache_dir) / "hub" / name.replace("/", "--")
    if is_complete(dest):
        logger.info("model %s served from cache %s", name, dest)
        return dest
    _reject_unloadable_spm(name, dest)

    if not allow_download or knobs.get("DYN_OFFLINE"):
        raise FileNotFoundError(
            f"model {name!r} is not cached at {dest} and downloads are "
            "disabled (DYN_OFFLINE=1 / allow_download=False)"
        )

    dest.mkdir(parents=True, exist_ok=True)
    fetch = downloader or _hf_download
    try:
        logger.info("downloading %s into %s", name, dest)
        fetch(name, dest)
    except Exception as exc:  # noqa: BLE001 — surface a usable error
        raise FileNotFoundError(
            f"model {name!r}: hub download failed ({exc}); provide a local "
            "path, pre-populate the cache, or fix network access"
        ) from exc
    _reject_unloadable_spm(name, dest)
    if not is_complete(dest):
        raise FileNotFoundError(
            f"model {name!r}: download completed but {dest} lacks "
            "config.json or a tokenizer (tokenizer.json/tokenizer.model)"
        )
    return dest


def _reject_unloadable_spm(name: str, dest: Path) -> None:
    """A cached dir whose only tokenizer is an SPM tokenizer.model in an
    environment without the conversion deps must fail with the actionable
    cause — not re-download on every resolve, not claim the tokenizer is
    missing."""
    if classify_model_dir(dest) == "unloadable_spm":
        raise FileNotFoundError(
            f"model {name!r} at {dest} ships only a SentencePiece "
            "tokenizer.model and the 'sentencepiece'/'transformers' packages "
            "needed to convert it are not installed; install them or provide "
            "a tokenizer.json"
        )
