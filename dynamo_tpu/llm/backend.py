"""Backend operator: detokenization + stop-condition enforcement.

Sits between the preprocessor and the engine (reference: lib/llm/src/backend.rs:63-80):
forward passes the PreprocessedRequest through; backward incrementally
detokenizes engine token deltas and runs the hidden stop-sequence "jail" —
text that might be the prefix of a stop sequence is held back until it either
completes (finish, truncate) or diverges (release).
"""

from __future__ import annotations

from typing import AsyncIterator

from dynamo_tpu.llm.protocols.common import (
    Annotated,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.llm.tokenizer import HfTokenizer
from dynamo_tpu.runtime.engine import Context, Operator, ResponseStream


class StopSequenceJail:
    """Holds back text that could become a stop sequence.

    ``push(delta) -> (released_text, matched)``: released text safe to emit;
    ``matched`` True when a stop sequence completed (released text excludes it).
    """

    def __init__(self, stop_sequences: list[str]):
        self.stops = [s for s in stop_sequences if s]
        self._held = ""

    def push(self, delta: str) -> tuple[str, bool]:
        if not self.stops:
            return delta, False
        text = self._held + delta
        # full match anywhere in the accumulated window?
        for stop in self.stops:
            idx = text.find(stop)
            if idx != -1:
                self._held = ""
                return text[:idx], True
        # hold the longest suffix that is a proper prefix of any stop
        max_hold = 0
        for stop in self.stops:
            for k in range(min(len(stop) - 1, len(text)), 0, -1):
                if text.endswith(stop[:k]):
                    max_hold = max(max_hold, k)
                    break
        if max_hold:
            self._held = text[-max_hold:]
            return text[:-max_hold], False
        self._held = ""
        return text, False

    def flush(self) -> str:
        held, self._held = self._held, ""
        return held


class Backend(Operator):
    """Wire-dict operator: PreprocessedRequest dicts in, Annotated
    LLMEngineOutput dicts out (with ``text`` filled in)."""

    def __init__(self, tokenizer: HfTokenizer):
        self.tokenizer = tokenizer

    async def preprocess(self, request: Context[dict]) -> Context[dict]:
        return request

    async def postprocess(
        self, stream: ResponseStream[dict], request: Context[dict]
    ) -> ResponseStream[dict]:
        pre = PreprocessedRequest.from_wire(request.data)
        decode = self.tokenizer.decode_stream()
        jail = StopSequenceJail(pre.stop.stop)
        ctx = request.ctx

        async def gen() -> AsyncIterator[dict]:
            finished = False
            async for item in stream:
                if finished:
                    break
                ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
                if ann.is_annotation() or ann.data is None:
                    yield item
                    continue
                out: LLMEngineOutput = ann.data
                if out.finish_reason is FinishReason.ERROR:
                    # an engine-side failure must not masquerade as a clean
                    # stop: raise so unary handlers return 500 and SSE
                    # streams emit an error event (the diagnostic would
                    # otherwise be dropped entirely)
                    raise RuntimeError(out.error or "engine error")
                text_parts: list[str] = []
                finish = out.finish_reason
                consumed = 0
                for token_id in out.token_ids:
                    if _is_stop_token(token_id, pre):
                        if finish is None:
                            finish = FinishReason.STOP
                        finished = True
                        break
                    consumed += 1
                    piece = decode.step(token_id)
                    if piece is None:
                        continue
                    released, matched = jail.push(piece)
                    if released:
                        text_parts.append(released)
                    if matched:
                        finish = FinishReason.STOP
                        finished = True
                        break
                if finish is not None and not finished:
                    finished = True
                if consumed < len(out.token_ids):
                    # a stop cut the burst short: keep tokens/logprobs in sync
                    out.token_ids = out.token_ids[:consumed]
                    if out.logprobs is not None:
                        out.logprobs = out.logprobs[:consumed]
                    if out.top_logprobs is not None:
                        out.top_logprobs = out.top_logprobs[:consumed]
                out.text = "".join(text_parts)
                out.finish_reason = finish
                yield Annotated.from_data(out).to_wire(LLMEngineOutput.to_wire)
                if finished:
                    # tell the engine to stop producing (graceful upstream stop)
                    ctx.stop_generating()
                    break

        return ResponseStream(gen(), ctx)


def _is_stop_token(token_id: int, pre: PreprocessedRequest) -> bool:
    if pre.stop.ignore_eos:
        return token_id in pre.stop.stop_token_ids
    return token_id in pre.eos_token_ids or token_id in pre.stop.stop_token_ids
