"""Preprocessor: OpenAI request → PreprocessedRequest, and engine deltas →
OpenAI stream chunks.

The bidirectional frontend operator (reference: lib/llm/src/preprocessor.rs:98):
forward renders the chat template (jinja2 sandbox, as minijinja serves the
reference) and tokenizes; backward turns ``Annotated[LLMEngineOutput]`` wire
items into OpenAI SSE chunk objects.  Supported annotations (requested via
``ext.annotations``): ``formatted_prompt``, ``token_ids`` (reference:
preprocessor.rs:61-63).
"""

from __future__ import annotations

from typing import AsyncIterator

from jinja2.sandbox import ImmutableSandboxedEnvironment

from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.common import (
    Annotated,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.llm.protocols.openai import (
    ChatChunkChoice,
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatDelta,
    CompletionChoice,
    CompletionRequest,
    CompletionResponse,
    Usage,
    finish_reason_to_openai,
    new_request_id,
)
from dynamo_tpu.llm.tokenizer import HfTokenizer
from dynamo_tpu.runtime.engine import Context, Operator, ResponseStream

ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"
ANNOTATION_LLM_METRICS = "llm_metrics"

_DEFAULT_TEMPLATE = (
    "{% for message in messages %}{{ message.role }}: {{ message.content }}\n"
    "{% endfor %}assistant:"
)


class PromptFormatter:
    """Jinja chat-template renderer (reference:
    lib/llm/src/preprocessor/prompt/template/)."""

    def __init__(self, template: str | None):
        env = ImmutableSandboxedEnvironment(trim_blocks=True, lstrip_blocks=True)
        env.globals["raise_exception"] = _raise_exception
        self._template = env.from_string(template or _DEFAULT_TEMPLATE)

    def render(self, request: ChatCompletionRequest) -> str:
        messages = [
            {"role": m.role, "content": m.text(), "name": m.name} for m in request.messages
        ]
        return self._template.render(
            messages=messages,
            add_generation_prompt=True,
            # HF chat templates index tools as dicts ({{ tool['function'] }});
            # the typed ToolDef models dump back to the wire shape
            tools=(
                [t.model_dump(exclude_none=True) for t in request.tools]
                if request.tools else None
            ),
        )


def render_logprob_entries(
    tokenizer: HfTokenizer,
    token_ids: list[int],
    logprobs: list[float],
    top_logprobs: list[list[list]] | None = None,
) -> list[dict]:
    """OpenAI chat ``logprobs.content`` entries for one emitted burst.
    ``top_logprobs`` rows are [[token_id, logprob], ...] alternatives when
    the engine supplied them.  Callers must skip rendering when the engine
    supplied no logprobs — fabricating values would report false
    certainty."""
    entries = []
    for pos, (tid, lp) in enumerate(zip(token_ids, logprobs)):
        text = tokenizer.decode([tid], skip_special_tokens=False)
        alts = []
        if top_logprobs is not None and pos < len(top_logprobs):
            for alt_id, alt_lp in top_logprobs[pos]:
                alt_text = tokenizer.decode([int(alt_id)], skip_special_tokens=False)
                alts.append(
                    {
                        "token": alt_text,
                        "logprob": float(alt_lp),
                        "bytes": list(alt_text.encode("utf-8")),
                    }
                )
        entries.append(
            {
                "token": text,
                "logprob": lp,
                "bytes": list(text.encode("utf-8")),
                "top_logprobs": alts,
            }
        )
    return entries


def _raise_exception(message: str):
    raise ValueError(message)


class _PreprocessorCore:
    def __init__(self, mdc: ModelDeploymentCard, tokenizer: HfTokenizer):
        self.mdc = mdc
        self.tokenizer = tokenizer
        self.formatter = PromptFormatter(mdc.chat_template)

    def eos_ids(self) -> list[int]:
        return self.mdc.eos_token_ids or self.tokenizer.eos_token_ids

    def build_preprocessed(
        self, token_ids: list[int], request, annotations: list[str]
    ) -> PreprocessedRequest:
        stop = request.stop_conditions()
        if stop.max_tokens is None:
            stop.max_tokens = max(self.mdc.context_length - len(token_ids), 1)
        if len(token_ids) >= self.mdc.context_length:
            raise ValueError(
                f"prompt length {len(token_ids)} exceeds context length "
                f"{self.mdc.context_length}"
            )
        return PreprocessedRequest(
            token_ids=token_ids,
            sampling=request.sampling_options(),
            stop=stop,
            eos_token_ids=self.eos_ids(),
            model=request.model,
            annotations=annotations,
            mdc_sum=self.mdc.checksum,
        )


class ChatPreprocessor(Operator):
    """ChatCompletionRequest ⇄ PreprocessedRequest/ChatCompletionChunk."""

    def __init__(self, mdc: ModelDeploymentCard, tokenizer: HfTokenizer):
        self.core = _PreprocessorCore(mdc, tokenizer)

    async def preprocess(self, request: Context[ChatCompletionRequest]) -> Context[dict]:
        from dynamo_tpu.llm.multimodal import (
            encode_image_wire,
            extract_image_url,
            resolve_image,
        )

        req = request.data
        prompt = self.core.formatter.render(req)
        token_ids = self.core.tokenizer.encode(prompt)
        annotations = list(req.ext.annotations) if req.ext else []
        pre = self.core.build_preprocessed(token_ids, req, annotations)
        ctx_data = pre.to_wire()
        # image_url content parts: fetch/decode here (host I/O belongs at
        # the frontend), ship the normalized array to the engine, which
        # encodes + splices patch embeddings (examples/multimodal/
        # pipeline.py MultimodalEngine; reference processor.py:107-217)
        image_url = extract_image_url(req)
        if image_url is not None:
            image = await resolve_image(image_url)
            ctx_data["image"] = encode_image_wire(image)
        # guided decoding: json_object constrains sampling to valid-JSON
        # prefixes; the engine rejects when its mask table is not enabled
        # (llm/guided.py; engine/engine.py enable_guided_json)
        if (req.response_format or {}).get("type") == "json_object":
            ctx_data["output_format"] = "json"
        # stash state for postprocess on the context object
        request.ctx._pre_state = {  # type: ignore[attr-defined]
            "prompt": prompt,
            "prompt_tokens": len(token_ids),
            "annotations": annotations,
            "model": req.model,
            "response_id": new_request_id("chatcmpl"),
        }
        return request.transfer(ctx_data)

    async def postprocess(
        self, stream: ResponseStream[dict], request: Context[ChatCompletionRequest]
    ) -> ResponseStream[Annotated[ChatCompletionChunk]]:
        state = request.ctx._pre_state  # type: ignore[attr-defined]
        include_usage = bool(
            request.data.stream_options and request.data.stream_options.get("include_usage")
        )

        want_logprobs = bool(request.data.logprobs)
        tokenizer = self.core.tokenizer

        async def gen() -> AsyncIterator[Annotated[ChatCompletionChunk]]:
            first = True
            completion_tokens = 0
            for name in state["annotations"]:
                if name == ANNOTATION_FORMATTED_PROMPT:
                    yield Annotated.from_annotation(ANNOTATION_FORMATTED_PROMPT, state["prompt"])
                if name == ANNOTATION_TOKEN_IDS:
                    yield Annotated.from_annotation(ANNOTATION_TOKEN_IDS, state["prompt_tokens"])
            async for item in stream:
                ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
                if ann.is_annotation() or ann.data is None:
                    continue
                out: LLMEngineOutput = ann.data
                completion_tokens += len(out.token_ids)
                delta = ChatDelta(
                    role="assistant" if first else None,
                    content=out.text if out.text else ("" if first else None),
                )
                first = False
                lp_content = None
                if want_logprobs and out.token_ids and out.logprobs is not None:
                    lp_content = {
                        "content": render_logprob_entries(
                            tokenizer, out.token_ids, out.logprobs,
                            out.top_logprobs,
                        )
                    }
                yield Annotated.from_data(
                    ChatCompletionChunk(
                        id=state["response_id"],
                        model=state["model"],
                        choices=[
                            ChatChunkChoice(
                                index=0,
                                delta=delta,
                                finish_reason=finish_reason_to_openai(out.finish_reason),
                                logprobs=lp_content,
                            )
                        ],
                    )
                )
            if include_usage:
                yield Annotated.from_data(
                    ChatCompletionChunk(
                        id=state["response_id"],
                        model=state["model"],
                        choices=[],
                        usage=Usage(
                            prompt_tokens=state["prompt_tokens"],
                            completion_tokens=completion_tokens,
                            total_tokens=state["prompt_tokens"] + completion_tokens,
                        ),
                    )
                )

        return ResponseStream(gen(), request.ctx)


class CompletionPreprocessor(Operator):
    """CompletionRequest ⇄ PreprocessedRequest/CompletionResponse chunks."""

    def __init__(self, mdc: ModelDeploymentCard, tokenizer: HfTokenizer):
        self.core = _PreprocessorCore(mdc, tokenizer)

    async def preprocess(self, request: Context[CompletionRequest]) -> Context[dict]:
        req = request.data
        if isinstance(req.prompt, str):
            token_ids = self.core.tokenizer.encode(req.prompt)
        elif req.prompt and isinstance(req.prompt[0], int):
            token_ids = list(req.prompt)  # pre-tokenized
        else:
            raise ValueError("batch prompts must be dispatched one per request")
        annotations = list(req.ext.annotations) if req.ext else []
        pre = self.core.build_preprocessed(token_ids, req, annotations)
        request.ctx._pre_state = {  # type: ignore[attr-defined]
            "prompt_tokens": len(token_ids),
            "model": req.model,
            "response_id": new_request_id("cmpl"),
        }
        return request.transfer(pre.to_wire())

    async def postprocess(
        self, stream: ResponseStream[dict], request: Context[CompletionRequest]
    ) -> ResponseStream[Annotated[CompletionResponse]]:
        state = request.ctx._pre_state  # type: ignore[attr-defined]
        include_usage = bool(
            request.data.stream_options and request.data.stream_options.get("include_usage")
        )

        want_logprobs = request.data.logprobs is not None and request.data.logprobs > 0
        tokenizer = self.core.tokenizer

        async def gen() -> AsyncIterator[Annotated[CompletionResponse]]:
            completion_tokens = 0
            char_offset = 0  # running offset within the generated text
            async for item in stream:
                ann = Annotated.from_wire(item, LLMEngineOutput.from_wire)
                if ann.is_annotation() or ann.data is None:
                    continue
                out = ann.data
                completion_tokens += len(out.token_ids)
                lp_block = None
                if want_logprobs and out.token_ids and out.logprobs is not None:
                    token_texts = [
                        tokenizer.decode([t], skip_special_tokens=False)
                        for t in out.token_ids
                    ]
                    offsets = []
                    for text in token_texts:
                        offsets.append(char_offset)
                        char_offset += len(text)
                    top = None
                    if out.top_logprobs is not None:
                        top = [
                            {
                                tokenizer.decode([int(aid)], skip_special_tokens=False):
                                float(alp)
                                for aid, alp in row
                            }
                            for row in out.top_logprobs
                        ]
                    lp_block = {
                        "tokens": token_texts,
                        "token_logprobs": out.logprobs,
                        "top_logprobs": top,
                        "text_offset": offsets,
                    }
                yield Annotated.from_data(
                    CompletionResponse(
                        id=state["response_id"],
                        model=state["model"],
                        choices=[
                            CompletionChoice(
                                index=0,
                                text=out.text or "",
                                finish_reason=finish_reason_to_openai(out.finish_reason),
                                logprobs=lp_block,
                            )
                        ],
                    )
                )
            if include_usage:
                yield Annotated.from_data(
                    CompletionResponse(
                        id=state["response_id"],
                        model=state["model"],
                        choices=[],
                        usage=Usage(
                            prompt_tokens=state["prompt_tokens"],
                            completion_tokens=completion_tokens,
                            total_tokens=state["prompt_tokens"] + completion_tokens,
                        ),
                    )
                )

        return ResponseStream(gen(), request.ctx)
