"""Tokenizer wrapper + incremental detokenization.

Wraps HF ``tokenizers`` (reference: lib/llm/src/tokenizers.rs) and provides a
``DecodeStream`` for per-token incremental detokenization that is correct for
multi-byte/multi-token unicode: text is only released once the decoder
produces output that no longer ends in a replacement character, using the
prefix-window re-decode technique.
"""

from __future__ import annotations

import json
from pathlib import Path

from tokenizers import Tokenizer

REPLACEMENT_CHAR = "�"


def spm_conversion_available() -> bool:
    """Whether a SentencePiece tokenizer.model can be converted to a fast
    tokenizer (the conversion runs through transformers' converter, which
    needs the sentencepiece package)."""
    import importlib.util

    return (
        importlib.util.find_spec("sentencepiece") is not None
        and importlib.util.find_spec("transformers") is not None
    )


class HfTokenizer:
    def __init__(self, tokenizer: Tokenizer, *, eos_token_ids: list[int] | None = None):
        self._tk = tokenizer
        self.eos_token_ids = eos_token_ids or []

    @classmethod
    def from_file(cls, path: str | Path) -> "HfTokenizer":
        path = Path(path)
        tk = Tokenizer.from_file(str(path))
        eos_ids: list[int] = []
        config_path = path.parent / "tokenizer_config.json"
        if config_path.exists():
            config = json.loads(config_path.read_text())
            eos_token = config.get("eos_token")
            if isinstance(eos_token, dict):
                eos_token = eos_token.get("content")
            if eos_token is not None:
                eos_id = tk.token_to_id(eos_token)
                if eos_id is not None:
                    eos_ids.append(eos_id)
        return cls(tk, eos_token_ids=eos_ids)

    @classmethod
    def from_model_dir(cls, model_dir: str | Path) -> "HfTokenizer":
        """Load from a model directory: the fast ``tokenizer.json`` when
        present, else convert a SentencePiece ``tokenizer.model`` through
        transformers (needs the ``sentencepiece`` package)."""
        model_dir = Path(model_dir)
        if (model_dir / "tokenizer.json").exists():
            return cls.from_file(model_dir / "tokenizer.json")
        if (model_dir / "tokenizer.model").exists():
            if not spm_conversion_available():
                raise FileNotFoundError(
                    f"{model_dir} ships only a SentencePiece tokenizer.model "
                    "and the 'sentencepiece' package is not installed; "
                    "provide tokenizer.json or install sentencepiece"
                )
            from transformers import AutoTokenizer

            fast = AutoTokenizer.from_pretrained(str(model_dir), use_fast=True)
            eos_ids = [fast.eos_token_id] if fast.eos_token_id is not None else []
            return cls(fast.backend_tokenizer, eos_token_ids=eos_ids)
        raise FileNotFoundError(f"no tokenizer.json/tokenizer.model in {model_dir}")

    def encode(self, text: str, *, add_special_tokens: bool = False) -> list[int]:
        return self._tk.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: list[int], *, skip_special_tokens: bool = True) -> str:
        return self._tk.decode(ids, skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> int | None:
        return self._tk.token_to_id(token)

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def decode_stream(self, *, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens=skip_special_tokens)


class DecodeStream:
    """Incremental detokenizer (reference: tokenizers DecodeStream used in
    lib/llm/src/backend.rs:70-76).

    ``step(token_id) -> str | None``: the new text produced by this token, or
    None if it is held (incomplete unicode sequence / special token).
    """

    def __init__(self, tokenizer: HfTokenizer, *, skip_special_tokens: bool = True):
        self._tk = tokenizer
        self._skip_special = skip_special_tokens
        self._ids: list[int] = []
        self._prefix_offset = 0  # window start for context-sensitive decoding
        self._read_offset = 0    # everything before this is already emitted

    def step(self, token_id: int) -> str | None:
        self._ids.append(token_id)
        prefix_text = self._tk.decode(
            self._ids[self._prefix_offset : self._read_offset],
            skip_special_tokens=self._skip_special,
        )
        new_text = self._tk.decode(
            self._ids[self._prefix_offset :], skip_special_tokens=self._skip_special
        )
        if new_text.endswith(REPLACEMENT_CHAR):
            # mid-codepoint: hold until the sequence completes
            return None
        delta = new_text[len(prefix_text):]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return delta if delta else None
