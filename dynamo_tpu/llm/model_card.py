"""Model Deployment Card (MDC).

Identity + artifacts of a served model (reference:
lib/llm/src/model_card/model.rs:86): where the tokenizer/config/weights live,
context length, KV block size, eos ids, and the chat template.  Published to
the control-plane KV store (with TTL refresh via the serving instance's
lease) and large artifacts via the object store.
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class ModelDeploymentCard:
    name: str
    path: str | None = None                  # local dir with tokenizer/config
    context_length: int = 4096
    kv_block_size: int = 16
    eos_token_ids: list[int] = field(default_factory=list)
    chat_template: str | None = None
    model_type: str = "llama"
    checksum: str = ""

    def finalize(self) -> "ModelDeploymentCard":
        if not self.checksum:
            payload = json.dumps(
                [self.name, self.path, self.context_length, self.kv_block_size],
                sort_keys=True,
            ).encode()
            self.checksum = hashlib.sha256(payload).hexdigest()[:16]
        return self

    @classmethod
    def from_local_path(cls, path: str | Path, name: str | None = None) -> "ModelDeploymentCard":
        """Build an MDC from a local model directory (tokenizer.json +
        tokenizer_config.json + config.json)."""
        path = Path(path)
        name = name or path.name
        context_length = 4096
        chat_template = None
        eos_ids: list[int] = []
        model_type = "llama"

        config_path = path / "tokenizer_config.json"
        if config_path.exists():
            config = json.loads(config_path.read_text())
            chat_template = config.get("chat_template")
            context_length = config.get("model_max_length") or context_length

        model_config_path = path / "config.json"
        if model_config_path.exists():
            config = json.loads(model_config_path.read_text())
            model_type = config.get("model_type", model_type)
            context_length = min(
                context_length, config.get("max_position_embeddings", context_length)
            )
            eos = config.get("eos_token_id")
            if isinstance(eos, int):
                eos_ids.append(eos)
            elif isinstance(eos, list):
                eos_ids.extend(eos)

        return cls(
            name=name,
            path=str(path),
            context_length=context_length,
            eos_token_ids=eos_ids,
            chat_template=chat_template,
            model_type=model_type,
        ).finalize()

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "name": self.name,
                "path": self.path,
                "context_length": self.context_length,
                "kv_block_size": self.kv_block_size,
                "eos_token_ids": self.eos_token_ids,
                "chat_template": self.chat_template,
                "model_type": self.model_type,
                "checksum": self.checksum,
            }
        ).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "ModelDeploymentCard":
        d = json.loads(data)
        return cls(**d)

    # -- artifact distribution (reference: lib/runtime/src/transports/nats.rs:
    # 123-211 — NATS object store carries MDC artifacts so frontends on other
    # machines can build tokenizer pipelines without a shared filesystem) ----

    async def publish_artifacts(self, store) -> int:
        """Upload this model's small artifacts (tokenizer/config/template
        files — never weights) to the object store under this card's
        checksum.  Returns the number of files uploaded."""
        if not self.path:
            return 0
        src = Path(self.path)
        uploaded = 0
        for fname in ARTIFACT_FILES:
            f = src / fname
            if f.exists():
                await store.object_put(ARTIFACT_BUCKET, f"{self.checksum}/{fname}", f.read_bytes())
                uploaded += 1
        return uploaded

    async def fetch_artifacts(self, store, cache_dir: str | Path | None = None) -> Path | None:
        """Download this card's artifacts into a local cache dir and point
        ``self.path`` at it.  Returns the dir, or None if the store holds
        nothing for this checksum (e.g. a worker that never published)."""
        from dynamo_tpu.llm.hub import cache_base

        dest = cache_base(cache_dir) / "mdc" / self.checksum
        fetched = 0
        for fname in ARTIFACT_FILES:
            if (dest / fname).exists():
                fetched += 1
                continue
            data = await store.object_get(ARTIFACT_BUCKET, f"{self.checksum}/{fname}")
            if data is None:
                continue
            dest.mkdir(parents=True, exist_ok=True)
            # per-process-unique temp name: concurrent fetchers sharing a
            # cache dir must never truncate each other's in-flight write
            tmp = dest / f".{fname}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
            tmp.write_bytes(data)
            tmp.rename(dest / fname)  # atomic publish
            fetched += 1
        if fetched == 0:
            return None
        self.path = str(dest)
        return dest


ARTIFACT_BUCKET = "mdc-artifacts"
ARTIFACT_FILES = (
    "tokenizer.json",
    "tokenizer.model",
    "tokenizer_config.json",
    "config.json",
    "special_tokens_map.json",
    "generation_config.json",
)
