"""Model Deployment Card (MDC).

Identity + artifacts of a served model (reference:
lib/llm/src/model_card/model.rs:86): where the tokenizer/config/weights live,
context length, KV block size, eos ids, and the chat template.  Published to
the control-plane KV store (with TTL refresh via the serving instance's
lease) and large artifacts via the object store.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class ModelDeploymentCard:
    name: str
    path: str | None = None                  # local dir with tokenizer/config
    context_length: int = 4096
    kv_block_size: int = 16
    eos_token_ids: list[int] = field(default_factory=list)
    chat_template: str | None = None
    model_type: str = "llama"
    checksum: str = ""

    def finalize(self) -> "ModelDeploymentCard":
        if not self.checksum:
            payload = json.dumps(
                [self.name, self.path, self.context_length, self.kv_block_size],
                sort_keys=True,
            ).encode()
            self.checksum = hashlib.sha256(payload).hexdigest()[:16]
        return self

    @classmethod
    def from_local_path(cls, path: str | Path, name: str | None = None) -> "ModelDeploymentCard":
        """Build an MDC from a local model directory (tokenizer.json +
        tokenizer_config.json + config.json)."""
        path = Path(path)
        name = name or path.name
        context_length = 4096
        chat_template = None
        eos_ids: list[int] = []
        model_type = "llama"

        config_path = path / "tokenizer_config.json"
        if config_path.exists():
            config = json.loads(config_path.read_text())
            chat_template = config.get("chat_template")
            context_length = config.get("model_max_length") or context_length

        model_config_path = path / "config.json"
        if model_config_path.exists():
            config = json.loads(model_config_path.read_text())
            model_type = config.get("model_type", model_type)
            context_length = min(
                context_length, config.get("max_position_embeddings", context_length)
            )
            eos = config.get("eos_token_id")
            if isinstance(eos, int):
                eos_ids.append(eos)
            elif isinstance(eos, list):
                eos_ids.extend(eos)

        return cls(
            name=name,
            path=str(path),
            context_length=context_length,
            eos_token_ids=eos_ids,
            chat_template=chat_template,
            model_type=model_type,
        ).finalize()

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "name": self.name,
                "path": self.path,
                "context_length": self.context_length,
                "kv_block_size": self.kv_block_size,
                "eos_token_ids": self.eos_token_ids,
                "chat_template": self.chat_template,
                "model_type": self.model_type,
                "checksum": self.checksum,
            }
        ).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "ModelDeploymentCard":
        d = json.loads(data)
        return cls(**d)
