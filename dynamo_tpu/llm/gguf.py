"""GGUF model-file support (reference: lib/llm/src/gguf/* — metadata/config
parsing `ContentConfig`/`ModelConfigLike` and vocab extraction
lib/llm/src/gguf/gguf_tokenizer.rs:587).

Pure-python binary parser for GGUF v2/v3 plus:
- :func:`config_from_gguf` — llama.* metadata → :class:`LlamaConfig`;
- :func:`tokenizer_from_gguf` — ``tokenizer.ggml.*`` vocab/merges → a HF
  ``tokenizers`` BPE tokenizer (gpt2-style byte-level);
- :func:`load_gguf_weights` — F32/F16 tensors → the layer-stacked llama
  param pytree (quantized GGML types are recognized but not dequantized);
- :func:`write_gguf` — writer used by tests and for exporting small models.

GGML stores dims fastest-varying-first; numpy shapes here are the reverse.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from pathlib import Path
from typing import Any, BinaryIO

import numpy as np

GGUF_MAGIC = b"GGUF"
DEFAULT_ALIGNMENT = 32

# metadata value types
T_UINT8, T_INT8, T_UINT16, T_INT16, T_UINT32, T_INT32 = range(6)
T_FLOAT32, T_BOOL, T_STRING, T_ARRAY, T_UINT64, T_INT64, T_FLOAT64 = range(6, 13)

_SCALAR_FMT = {
    T_UINT8: "<B", T_INT8: "<b", T_UINT16: "<H", T_INT16: "<h",
    T_UINT32: "<I", T_INT32: "<i", T_FLOAT32: "<f",
    T_UINT64: "<Q", T_INT64: "<q", T_FLOAT64: "<d",
}

# GGML tensor dtypes (subset; quantized types listed for recognition only)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1, GGML_Q8_0 = 2, 3, 8
GGML_BF16 = 30
_GGML_NUMPY = {GGML_F32: np.float32, GGML_F16: np.float16}
GGML_TYPE_NAMES = {
    GGML_F32: "F32", GGML_F16: "F16", GGML_Q4_0: "Q4_0", GGML_Q4_1: "Q4_1",
    GGML_Q8_0: "Q8_0", GGML_BF16: "BF16",
}


@dataclass
class GGUFTensorInfo:
    name: str
    shape: tuple[int, ...]       # numpy order (reversed from on-disk ggml dims)
    ggml_type: int
    offset: int                  # relative to data section start

    @property
    def type_name(self) -> str:
        return GGML_TYPE_NAMES.get(self.ggml_type, f"ggml#{self.ggml_type}")


def _read_str(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype in _SCALAR_FMT:
        fmt = _SCALAR_FMT[vtype]
        return struct.unpack(fmt, f.read(struct.calcsize(fmt)))[0]
    if vtype == T_BOOL:
        return f.read(1) != b"\x00"
    if vtype == T_STRING:
        return _read_str(f)
    if vtype == T_ARRAY:
        (item_type,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, item_type) for _ in range(count)]
    raise ValueError(f"unknown GGUF metadata value type {vtype}")


class GGUFFile:
    """Parsed GGUF container: ``metadata`` dict + tensor directory with lazy
    data access (memmap)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, GGUFTensorInfo] = {}
        with open(self.path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            (self.version,) = struct.unpack("<I", f.read(4))
            if self.version not in (2, 3):
                raise ValueError(f"{path}: unsupported GGUF version {self.version}")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = _read_str(f)
                (vtype,) = struct.unpack("<I", f.read(4))
                self.metadata[key] = _read_value(f, vtype)
            for _ in range(n_tensors):
                name = _read_str(f)
                (n_dims,) = struct.unpack("<I", f.read(4))
                dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
                ggml_type, offset = struct.unpack("<IQ", f.read(12))
                self.tensors[name] = GGUFTensorInfo(
                    name=name, shape=tuple(reversed(dims)), ggml_type=ggml_type,
                    offset=offset,
                )
            alignment = int(self.metadata.get("general.alignment", DEFAULT_ALIGNMENT))
            pos = f.tell()
            self.data_start = (pos + alignment - 1) // alignment * alignment

    def tensor_data(self, name: str) -> np.ndarray:
        """Load one tensor (F32/F16/BF16 only)."""
        info = self.tensors[name]
        if info.ggml_type == GGML_BF16:
            raw = np.memmap(self.path, np.uint16, "r", self.data_start + info.offset,
                            int(np.prod(info.shape)))
            return (raw.astype(np.uint32) << 16).view(np.float32).reshape(info.shape)
        dtype = _GGML_NUMPY.get(info.ggml_type)
        if dtype is None:
            raise NotImplementedError(
                f"tensor {name!r} has quantized type {info.type_name}; "
                "dequantization is not supported — export F16/F32"
            )
        return np.array(
            np.memmap(self.path, dtype, "r", self.data_start + info.offset,
                      int(np.prod(info.shape))).reshape(info.shape)
        )


# ------------------------------------------------------------------ writer


def _write_str(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)))
    f.write(b)


def _value_type(v: Any) -> int:
    if isinstance(v, bool):
        return T_BOOL
    if isinstance(v, int):
        return T_UINT32 if 0 <= v < 2**32 else T_INT64
    if isinstance(v, float):
        return T_FLOAT32
    if isinstance(v, str):
        return T_STRING
    if isinstance(v, (list, tuple)):
        return T_ARRAY
    raise TypeError(f"cannot encode {type(v)} in GGUF metadata")


def _write_value(f: BinaryIO, v: Any, vtype: int | None = None) -> None:
    vtype = _value_type(v) if vtype is None else vtype
    if vtype in _SCALAR_FMT:
        f.write(struct.pack(_SCALAR_FMT[vtype], v))
    elif vtype == T_BOOL:
        f.write(b"\x01" if v else b"\x00")
    elif vtype == T_STRING:
        _write_str(f, v)
    elif vtype == T_ARRAY:
        item_type = _value_type(v[0]) if v else T_UINT32
        f.write(struct.pack("<I", item_type))
        f.write(struct.pack("<Q", len(v)))
        for item in v:
            _write_value(f, item, item_type)


def write_gguf(
    path: str | Path, metadata: dict[str, Any], tensors: dict[str, np.ndarray]
) -> None:
    """Write a GGUF v3 file with F32/F16 tensors (numpy-order shapes)."""
    with open(path, "wb") as f:
        f.write(GGUF_MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<QQ", len(tensors), len(metadata)))
        for key, value in metadata.items():
            _write_str(f, key)
            vtype = _value_type(value)
            f.write(struct.pack("<I", vtype))
            _write_value(f, value, vtype)

        offset = 0
        arrays: list[np.ndarray] = []
        for name, arr in tensors.items():
            arr = np.ascontiguousarray(arr)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ggml_type = {np.dtype(np.float32): GGML_F32, np.dtype(np.float16): GGML_F16}[arr.dtype]
            _write_str(f, name)
            f.write(struct.pack("<I", arr.ndim))
            f.write(struct.pack(f"<{arr.ndim}Q", *reversed(arr.shape)))
            f.write(struct.pack("<IQ", ggml_type, offset))
            arrays.append(arr)
            size = arr.nbytes
            offset += (size + DEFAULT_ALIGNMENT - 1) // DEFAULT_ALIGNMENT * DEFAULT_ALIGNMENT

        pos = f.tell()
        f.write(b"\x00" * ((pos + DEFAULT_ALIGNMENT - 1) // DEFAULT_ALIGNMENT * DEFAULT_ALIGNMENT - pos))
        for arr in arrays:
            data = arr.tobytes()
            f.write(data)
            pad = (len(data) + DEFAULT_ALIGNMENT - 1) // DEFAULT_ALIGNMENT * DEFAULT_ALIGNMENT - len(data)
            f.write(b"\x00" * pad)


# ---------------------------------------------------------- config/tokenizer


def config_from_gguf(gguf: "GGUFFile"):
    """``llama.*`` metadata → LlamaConfig (reference: ContentConfig /
    ModelConfigLike extraction)."""
    from dynamo_tpu.models.llama import LlamaConfig

    meta = gguf.metadata
    arch = meta.get("general.architecture", "llama")
    if arch not in ("llama", "qwen2"):
        raise ValueError(f"unsupported GGUF architecture {arch!r}")

    def key(suffix: str, default=None):
        return meta.get(f"{arch}.{suffix}", default)

    hidden = int(key("embedding_length"))
    heads = int(key("attention.head_count"))
    vocab = int(key("vocab_size", 0)) or len(meta.get("tokenizer.ggml.tokens", []))
    has_lm_head = "output.weight" in gguf.tensors
    return LlamaConfig(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=int(key("feed_forward_length")),
        num_layers=int(key("block_count")),
        num_heads=heads,
        num_kv_heads=int(key("attention.head_count_kv", heads)),
        head_dim=int(key("attention.key_length", hidden // heads)),
        max_position_embeddings=int(key("context_length", 4096)),
        rope_theta=float(key("rope.freq_base", 10000.0)),
        rms_norm_eps=float(key("attention.layer_norm_rms_epsilon", 1e-5)),
        tie_word_embeddings=not has_lm_head,
        attention_bias=f"blk.0.attn_q.bias" in gguf.tensors,
    )


def tokenizer_from_gguf(gguf: "GGUFFile"):
    """Build a HF ``tokenizers`` tokenizer from ``tokenizer.ggml.*`` vocab
    (gpt2-style byte-level BPE; the common GGUF export format)."""
    from tokenizers import Tokenizer, decoders, pre_tokenizers
    from tokenizers.models import BPE

    meta = gguf.metadata
    model_kind = meta.get("tokenizer.ggml.model", "gpt2")
    if model_kind != "gpt2":
        raise NotImplementedError(
            f"GGUF tokenizer model {model_kind!r} not supported (gpt2 BPE only)"
        )
    tokens: list[str] = meta["tokenizer.ggml.tokens"]
    merges_raw: list[str] = meta.get("tokenizer.ggml.merges", [])
    vocab = {tok: i for i, tok in enumerate(tokens)}
    merges = [tuple(m.split(" ", 1)) for m in merges_raw]
    tok = Tokenizer(BPE(vocab, merges, fuse_unk=False))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    return tok


def mdc_from_gguf(path: str | Path, name: str | None = None):
    """GGUF file → ModelDeploymentCard (context length, eos, chat template)."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    gguf = GGUFFile(path)
    meta = gguf.metadata
    arch = meta.get("general.architecture", "llama")
    eos = meta.get("tokenizer.ggml.eos_token_id")
    return ModelDeploymentCard(
        name=name or meta.get("general.name", Path(path).stem),
        path=str(path),
        context_length=int(meta.get(f"{arch}.context_length", 4096)),
        eos_token_ids=[int(eos)] if eos is not None else [],
        chat_template=meta.get("tokenizer.chat_template"),
        model_type=arch,
    ).finalize()


# ------------------------------------------------------------------ weights

# llama.cpp tensor names → our layer-stacked pytree.  GGML stores
# projections as numpy [out, in] after dim reversal → transpose like HF.
_GGUF_LAYER_MAP = {
    "attn_norm": "blk.{i}.attn_norm.weight",
    "wq": "blk.{i}.attn_q.weight",
    "wk": "blk.{i}.attn_k.weight",
    "wv": "blk.{i}.attn_v.weight",
    "wo": "blk.{i}.attn_output.weight",
    "mlp_norm": "blk.{i}.ffn_norm.weight",
    "w_gate": "blk.{i}.ffn_gate.weight",
    "w_up": "blk.{i}.ffn_up.weight",
    "w_down": "blk.{i}.ffn_down.weight",
}


def load_gguf_weights(cfg, gguf: "GGUFFile") -> dict:
    """F32/F16 GGUF tensors → llama param pytree (same layout as
    models.llama.load_hf_weights)."""
    import jax.numpy as jnp

    def get(name: str, transpose: bool = False):
        t = gguf.tensor_data(name)
        if transpose:
            t = t.T
        return jnp.asarray(t, cfg.dtype)

    layer_map = dict(_GGUF_LAYER_MAP)
    if cfg.attention_bias:
        layer_map.update(
            bq="blk.{i}.attn_q.bias", bk="blk.{i}.attn_k.bias", bv="blk.{i}.attn_v.bias"
        )
    layers: dict[str, list] = {k: [] for k in layer_map}
    for i in range(cfg.num_layers):
        for ours, theirs in layer_map.items():
            layers[ours].append(get(theirs.format(i=i), transpose=ours.startswith("w")))
    params = {
        "embed": get("token_embd.weight"),
        "final_norm": get("output_norm.weight"),
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
    }
    if not cfg.tie_word_embeddings and "output.weight" in gguf.tensors:
        params["lm_head"] = get("output.weight", transpose=True)
    return params
