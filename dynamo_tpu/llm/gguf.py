"""GGUF model-file support (reference: lib/llm/src/gguf/* — metadata/config
parsing `ContentConfig`/`ModelConfigLike` and vocab extraction
lib/llm/src/gguf/gguf_tokenizer.rs:587).

Pure-python binary parser for GGUF v2/v3 plus:
- :func:`config_from_gguf` — llama.* metadata → :class:`LlamaConfig`;
- :func:`tokenizer_from_gguf` — ``tokenizer.ggml.*`` vocab/merges → a HF
  ``tokenizers`` BPE tokenizer (gpt2-style byte-level);
- :func:`load_gguf_weights` — F32/F16 tensors → the layer-stacked llama
  param pytree; quantized GGML types (Q4_0/Q4_1/Q5_0/Q5_1/Q8_0 and
  Q4_K/Q5_K/Q6_K) are dequantized to float on load;
- :func:`write_gguf` — writer used by tests and for exporting small models.

GGML stores dims fastest-varying-first; numpy shapes here are the reverse.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from pathlib import Path
from typing import Any, BinaryIO

import numpy as np

GGUF_MAGIC = b"GGUF"
DEFAULT_ALIGNMENT = 32

# metadata value types
T_UINT8, T_INT8, T_UINT16, T_INT16, T_UINT32, T_INT32 = range(6)
T_FLOAT32, T_BOOL, T_STRING, T_ARRAY, T_UINT64, T_INT64, T_FLOAT64 = range(6, 13)

_SCALAR_FMT = {
    T_UINT8: "<B", T_INT8: "<b", T_UINT16: "<H", T_INT16: "<h",
    T_UINT32: "<I", T_INT32: "<i", T_FLOAT32: "<f",
    T_UINT64: "<Q", T_INT64: "<q", T_FLOAT64: "<d",
}

# GGML tensor dtypes
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q5_0, GGML_Q5_1, GGML_Q8_0 = 6, 7, 8
GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 10, 11, 12, 13, 14
GGML_BF16 = 30
_GGML_NUMPY = {GGML_F32: np.float32, GGML_F16: np.float16}
GGML_TYPE_NAMES = {
    GGML_F32: "F32", GGML_F16: "F16", GGML_Q4_0: "Q4_0", GGML_Q4_1: "Q4_1",
    GGML_Q5_0: "Q5_0", GGML_Q5_1: "Q5_1", GGML_Q8_0: "Q8_0",
    GGML_Q2_K: "Q2_K", GGML_Q3_K: "Q3_K", GGML_Q4_K: "Q4_K",
    GGML_Q5_K: "Q5_K", GGML_Q6_K: "Q6_K", GGML_BF16: "BF16",
}

# bytes per block, weights per block (llama.cpp ggml-common.h block layouts)
GGML_BLOCK_SIZES = {
    GGML_Q4_0: (18, 32), GGML_Q4_1: (20, 32),
    GGML_Q5_0: (22, 32), GGML_Q5_1: (24, 32), GGML_Q8_0: (34, 32),
    GGML_Q4_K: (144, 256), GGML_Q5_K: (176, 256), GGML_Q6_K: (210, 256),
}


# ---------------------------------------------------------------- dequant
# Vectorized numpy dequantization of the dominant GGML quantized formats
# (reference parses the full quant range, lib/llm/src/gguf/*; llama.cpp
# dequantize_row_* are the layout ground truth).  All return float32.

def _f16(b: np.ndarray) -> np.ndarray:
    """[nb, 2] uint8 → [nb, 1] float32 (little-endian fp16 scales)."""
    return np.ascontiguousarray(b).view(np.float16).astype(np.float32)


def _dequant_q4_0(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks[:, 0:2])
    qs = blocks[:, 2:18]
    q = np.concatenate([qs & 0xF, qs >> 4], axis=1).astype(np.float32) - 8.0
    return d * q


def _dequant_q4_1(blocks: np.ndarray) -> np.ndarray:
    d, m = _f16(blocks[:, 0:2]), _f16(blocks[:, 2:4])
    qs = blocks[:, 4:20]
    q = np.concatenate([qs & 0xF, qs >> 4], axis=1).astype(np.float32)
    return d * q + m


def _unpack_qh(qh_bytes: np.ndarray) -> np.ndarray:
    """[nb, 4] uint8 → [nb, 32] the per-weight 5th bit (0/1)."""
    qh = np.ascontiguousarray(qh_bytes).view(np.uint32)  # [nb, 1]
    shifts = np.arange(32, dtype=np.uint32)
    return ((qh >> shifts) & 1).astype(np.uint8)


def _dequant_q5_0(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks[:, 0:2])
    hi = _unpack_qh(blocks[:, 2:6])
    qs = blocks[:, 6:22]
    lo = np.concatenate([qs & 0xF, qs >> 4], axis=1)
    q = (lo | (hi << 4)).astype(np.float32) - 16.0
    return d * q


def _dequant_q5_1(blocks: np.ndarray) -> np.ndarray:
    d, m = _f16(blocks[:, 0:2]), _f16(blocks[:, 2:4])
    hi = _unpack_qh(blocks[:, 4:8])
    qs = blocks[:, 8:24]
    lo = np.concatenate([qs & 0xF, qs >> 4], axis=1)
    q = (lo | (hi << 4)).astype(np.float32)
    return d * q + m


def _dequant_q8_0(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks[:, 0:2])
    q = np.ascontiguousarray(blocks[:, 2:34]).view(np.int8).astype(np.float32)
    return d * q


def _k_scale_min(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Q4_K/Q5_K 6-bit packed sub-block scales/mins: [nb, 12] uint8 →
    ([nb, 8], [nb, 8]) (llama.cpp get_scale_min_k4)."""
    sc = np.empty(scales.shape[:1] + (8,), np.uint8)
    mn = np.empty_like(sc)
    for j in range(4):
        sc[:, j] = scales[:, j] & 63
        mn[:, j] = scales[:, j + 4] & 63
    for j in range(4, 8):
        sc[:, j] = (scales[:, j + 4] & 0xF) | ((scales[:, j - 4] >> 6) << 4)
        mn[:, j] = (scales[:, j + 4] >> 4) | ((scales[:, j] >> 6) << 4)
    return sc, mn


def _dequant_q4_k(blocks: np.ndarray) -> np.ndarray:
    nb = blocks.shape[0]
    d, dmin = _f16(blocks[:, 0:2]), _f16(blocks[:, 2:4])
    sc, mn = _k_scale_min(blocks[:, 4:16])
    qs = blocks[:, 16:144].reshape(nb, 4, 32)  # 4 chunks of 64 weights
    # chunk i: low nibbles → sub-block 2i, high nibbles → sub-block 2i+1
    q = np.stack([qs & 0xF, qs >> 4], axis=2).reshape(nb, 8, 32).astype(np.float32)
    w = d[:, None] * sc.astype(np.float32)[..., None] * q \
        - dmin[:, None] * mn.astype(np.float32)[..., None]
    return w.reshape(nb, 256)


def _dequant_q5_k(blocks: np.ndarray) -> np.ndarray:
    nb = blocks.shape[0]
    d, dmin = _f16(blocks[:, 0:2]), _f16(blocks[:, 2:4])
    sc, mn = _k_scale_min(blocks[:, 4:16])
    qh = blocks[:, 16:48]                      # [nb, 32]
    qs = blocks[:, 48:176].reshape(nb, 4, 32)  # 4 chunks of 64 weights
    lo = np.stack([qs & 0xF, qs >> 4], axis=2)            # [nb, 4, 2, 32]
    shifts = (2 * np.arange(4, dtype=np.uint8))[None, :, None, None] \
        + np.arange(2, dtype=np.uint8)[None, None, :, None]
    hi = (qh[:, None, None, :] >> shifts) & 1
    q = (lo + (hi << 4)).reshape(nb, 8, 32).astype(np.float32)
    w = d[:, None] * sc.astype(np.float32)[..., None] * q \
        - dmin[:, None] * mn.astype(np.float32)[..., None]
    return w.reshape(nb, 256)


def _dequant_q6_k(blocks: np.ndarray) -> np.ndarray:
    nb = blocks.shape[0]
    ql = blocks[:, 0:128].reshape(nb, 2, 64)     # two 128-weight halves
    qh = blocks[:, 128:192].reshape(nb, 2, 32)
    scales = np.ascontiguousarray(blocks[:, 192:208]).view(np.int8)  # [nb, 16]
    d = _f16(blocks[:, 208:210])
    l_lo, l_hi = ql[:, :, :32], ql[:, :, 32:]
    h = qh  # [nb, 2, 32]
    q1 = (l_lo & 0xF) | (((h >> 0) & 3) << 4)    # weights   0..31 of half
    q2 = (l_hi & 0xF) | (((h >> 2) & 3) << 4)    # weights  32..63
    q3 = (l_lo >> 4) | (((h >> 4) & 3) << 4)     # weights  64..95
    q4 = (l_hi >> 4) | (((h >> 6) & 3) << 4)     # weights  96..127
    q = np.concatenate([q1, q2, q3, q4], axis=2).astype(np.float32) - 32.0
    # scale index: within half n, weight j uses scales[8n + j//16]
    sc = scales.reshape(nb, 2, 8).astype(np.float32)
    w = d[:, None] * np.repeat(sc, 16, axis=2) * q
    return w.reshape(nb, 256)


_DEQUANT = {
    GGML_Q4_0: _dequant_q4_0, GGML_Q4_1: _dequant_q4_1,
    GGML_Q5_0: _dequant_q5_0, GGML_Q5_1: _dequant_q5_1,
    GGML_Q8_0: _dequant_q8_0,
    GGML_Q4_K: _dequant_q4_k, GGML_Q5_K: _dequant_q5_k,
    GGML_Q6_K: _dequant_q6_k,
}


def quantize_q8_0(w: np.ndarray) -> np.ndarray:
    """float weights → Q8_0 block bytes (for the writer/tests).  Rows of 32."""
    flat = np.asarray(w, np.float32).reshape(-1, 32)
    amax = np.abs(flat).max(axis=1, keepdims=True)
    d = (amax / 127.0).astype(np.float16)
    scale = np.where(d == 0, 1.0, d.astype(np.float32))
    q = np.round(flat / scale).clip(-127, 127).astype(np.int8)
    return np.concatenate([d.view(np.uint8), q.view(np.uint8)], axis=1)


def quantize_q4_0(w: np.ndarray) -> np.ndarray:
    """float weights → Q4_0 block bytes.  Rows of 32."""
    flat = np.asarray(w, np.float32).reshape(-1, 32)
    idx = np.abs(flat).argmax(axis=1)
    maxv = flat[np.arange(flat.shape[0]), idx]
    d = (maxv / -8.0).astype(np.float16)
    scale = np.where(d == 0, 1.0, d.astype(np.float32))[:, None]
    q = (np.round(flat / scale) + 8).clip(0, 15).astype(np.uint8)
    packed = q[:, :16] | (q[:, 16:] << 4)
    return np.concatenate([d[:, None].view(np.uint8), packed], axis=1)


@dataclass
class GGUFTensorInfo:
    name: str
    shape: tuple[int, ...]       # numpy order (reversed from on-disk ggml dims)
    ggml_type: int
    offset: int                  # relative to data section start

    @property
    def type_name(self) -> str:
        return GGML_TYPE_NAMES.get(self.ggml_type, f"ggml#{self.ggml_type}")


def _read_str(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype in _SCALAR_FMT:
        fmt = _SCALAR_FMT[vtype]
        return struct.unpack(fmt, f.read(struct.calcsize(fmt)))[0]
    if vtype == T_BOOL:
        return f.read(1) != b"\x00"
    if vtype == T_STRING:
        return _read_str(f)
    if vtype == T_ARRAY:
        (item_type,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, item_type) for _ in range(count)]
    raise ValueError(f"unknown GGUF metadata value type {vtype}")


class GGUFFile:
    """Parsed GGUF container: ``metadata`` dict + tensor directory with lazy
    data access (memmap)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, GGUFTensorInfo] = {}
        with open(self.path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            (self.version,) = struct.unpack("<I", f.read(4))
            if self.version not in (2, 3):
                raise ValueError(f"{path}: unsupported GGUF version {self.version}")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = _read_str(f)
                (vtype,) = struct.unpack("<I", f.read(4))
                self.metadata[key] = _read_value(f, vtype)
            for _ in range(n_tensors):
                name = _read_str(f)
                (n_dims,) = struct.unpack("<I", f.read(4))
                dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
                ggml_type, offset = struct.unpack("<IQ", f.read(12))
                self.tensors[name] = GGUFTensorInfo(
                    name=name, shape=tuple(reversed(dims)), ggml_type=ggml_type,
                    offset=offset,
                )
            alignment = int(self.metadata.get("general.alignment", DEFAULT_ALIGNMENT))
            pos = f.tell()
            self.data_start = (pos + alignment - 1) // alignment * alignment

    def tensor_data(self, name: str) -> np.ndarray:
        """Load one tensor: F32/F16/BF16 directly; quantized GGML formats
        (Q4_0/Q4_1/Q5_0/Q5_1/Q8_0 and the Q4_K/Q5_K/Q6_K k-quants behind
        the common Q4_K_M/Q5_K_M/Q8_0 exports) dequantize to float32."""
        info = self.tensors[name]
        n = int(np.prod(info.shape))
        if info.ggml_type == GGML_BF16:
            raw = np.memmap(self.path, np.uint16, "r", self.data_start + info.offset, n)
            return (raw.astype(np.uint32) << 16).view(np.float32).reshape(info.shape)
        dtype = _GGML_NUMPY.get(info.ggml_type)
        if dtype is not None:
            return np.array(
                np.memmap(self.path, dtype, "r", self.data_start + info.offset,
                          n).reshape(info.shape)
            )
        dequant = _DEQUANT.get(info.ggml_type)
        if dequant is None:
            raise NotImplementedError(
                f"tensor {name!r} has unsupported quantized type {info.type_name}"
            )
        block_bytes, block_weights = GGML_BLOCK_SIZES[info.ggml_type]
        if n % block_weights:
            raise ValueError(
                f"tensor {name!r}: {n} weights not a multiple of the "
                f"{info.type_name} block size {block_weights}"
            )
        nbytes = n // block_weights * block_bytes
        raw = np.array(
            np.memmap(self.path, np.uint8, "r", self.data_start + info.offset, nbytes)
        ).reshape(-1, block_bytes)
        return dequant(raw).reshape(info.shape)


# ------------------------------------------------------------------ writer


def _write_str(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)))
    f.write(b)


def _value_type(v: Any) -> int:
    if isinstance(v, bool):
        return T_BOOL
    if isinstance(v, int):
        return T_UINT32 if 0 <= v < 2**32 else T_INT64
    if isinstance(v, float):
        return T_FLOAT32
    if isinstance(v, str):
        return T_STRING
    if isinstance(v, (list, tuple)):
        return T_ARRAY
    raise TypeError(f"cannot encode {type(v)} in GGUF metadata")


def _write_value(f: BinaryIO, v: Any, vtype: int | None = None) -> None:
    vtype = _value_type(v) if vtype is None else vtype
    if vtype in _SCALAR_FMT:
        f.write(struct.pack(_SCALAR_FMT[vtype], v))
    elif vtype == T_BOOL:
        f.write(b"\x01" if v else b"\x00")
    elif vtype == T_STRING:
        _write_str(f, v)
    elif vtype == T_ARRAY:
        item_type = _value_type(v[0]) if v else T_UINT32
        f.write(struct.pack("<I", item_type))
        f.write(struct.pack("<Q", len(v)))
        for item in v:
            _write_value(f, item, item_type)


def write_gguf(
    path: str | Path, metadata: dict[str, Any], tensors: dict[str, Any]
) -> None:
    """Write a GGUF v3 file (numpy-order shapes).  Tensor values are float
    arrays (stored F32/F16) or ``(ggml_type, shape, block_bytes)`` tuples
    for pre-quantized data (e.g. from :func:`quantize_q8_0`)."""
    with open(path, "wb") as f:
        f.write(GGUF_MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<QQ", len(tensors), len(metadata)))
        for key, value in metadata.items():
            _write_str(f, key)
            vtype = _value_type(value)
            f.write(struct.pack("<I", vtype))
            _write_value(f, value, vtype)

        offset = 0
        arrays: list[np.ndarray] = []
        for name, arr in tensors.items():
            if isinstance(arr, tuple):
                ggml_type, shape, raw = arr
                arr = np.ascontiguousarray(raw).view(np.uint8).ravel()
                block_bytes, block_weights = GGML_BLOCK_SIZES[ggml_type]
                n = int(np.prod(shape))
                if n % block_weights or arr.nbytes != n // block_weights * block_bytes:
                    raise ValueError(
                        f"tensor {name!r}: {arr.nbytes} quantized bytes do not "
                        f"match shape {tuple(shape)} for {GGML_TYPE_NAMES[ggml_type]}"
                    )
            else:
                arr = np.ascontiguousarray(arr)
                if arr.dtype == np.float64:
                    arr = arr.astype(np.float32)
                ggml_type = {np.dtype(np.float32): GGML_F32, np.dtype(np.float16): GGML_F16}[arr.dtype]
                shape = arr.shape
            _write_str(f, name)
            f.write(struct.pack("<I", len(shape)))
            f.write(struct.pack(f"<{len(shape)}Q", *reversed(shape)))
            f.write(struct.pack("<IQ", ggml_type, offset))
            arrays.append(arr)
            size = arr.nbytes
            offset += (size + DEFAULT_ALIGNMENT - 1) // DEFAULT_ALIGNMENT * DEFAULT_ALIGNMENT

        pos = f.tell()
        f.write(b"\x00" * ((pos + DEFAULT_ALIGNMENT - 1) // DEFAULT_ALIGNMENT * DEFAULT_ALIGNMENT - pos))
        for arr in arrays:
            data = arr.tobytes()
            f.write(data)
            pad = (len(data) + DEFAULT_ALIGNMENT - 1) // DEFAULT_ALIGNMENT * DEFAULT_ALIGNMENT - len(data)
            f.write(b"\x00" * pad)


# ---------------------------------------------------------- config/tokenizer


def config_from_gguf(gguf: "GGUFFile"):
    """``llama.*`` metadata → LlamaConfig (reference: ContentConfig /
    ModelConfigLike extraction)."""
    from dynamo_tpu.models.llama import LlamaConfig

    meta = gguf.metadata
    arch = meta.get("general.architecture", "llama")
    if arch not in ("llama", "qwen2"):
        raise ValueError(f"unsupported GGUF architecture {arch!r}")

    def key(suffix: str, default=None):
        return meta.get(f"{arch}.{suffix}", default)

    hidden = int(key("embedding_length"))
    heads = int(key("attention.head_count"))
    vocab = int(key("vocab_size", 0)) or len(meta.get("tokenizer.ggml.tokens", []))
    has_lm_head = "output.weight" in gguf.tensors
    return LlamaConfig(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=int(key("feed_forward_length")),
        num_layers=int(key("block_count")),
        num_heads=heads,
        num_kv_heads=int(key("attention.head_count_kv", heads)),
        head_dim=int(key("attention.key_length", hidden // heads)),
        max_position_embeddings=int(key("context_length", 4096)),
        rope_theta=float(key("rope.freq_base", 10000.0)),
        rms_norm_eps=float(key("attention.layer_norm_rms_epsilon", 1e-5)),
        tie_word_embeddings=not has_lm_head,
        attention_bias=f"blk.0.attn_q.bias" in gguf.tensors,
    )


def tokenizer_from_gguf(gguf: "GGUFFile"):
    """Build a HF ``tokenizers`` tokenizer from ``tokenizer.ggml.*`` vocab.

    Supports the two GGUF tokenizer families (reference parses both,
    lib/llm/src/gguf/gguf_tokenizer.rs:587):
    - ``gpt2``: byte-level BPE from tokens + merges;
    - ``llama``: SentencePiece-style Unigram from tokens + scores, with
      Metaspace pre-tokenization and byte-fallback tokens.
    """
    from tokenizers import Tokenizer, decoders, pre_tokenizers
    from tokenizers.models import BPE, Unigram

    meta = gguf.metadata
    model_kind = meta.get("tokenizer.ggml.model", "gpt2")
    tokens: list[str] = meta["tokenizer.ggml.tokens"]
    if model_kind == "gpt2":
        merges_raw: list[str] = meta.get("tokenizer.ggml.merges", [])
        vocab = {tok: i for i, tok in enumerate(tokens)}
        merges = [tuple(m.split(" ", 1)) for m in merges_raw]
        tok = Tokenizer(BPE(vocab, merges, fuse_unk=False))
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
        return tok
    if model_kind == "llama":
        scores: list[float] = meta.get("tokenizer.ggml.scores") or [0.0] * len(tokens)
        unk_id = int(meta.get("tokenizer.ggml.unknown_token_id", 0))
        # llama-family vocabs carry <0x00>..<0xFF> byte tokens: characters
        # absent from the vocab encode through them (byte_fallback), and
        # generated byte tokens must decode as UTF-8 bytes, not literals
        tok = Tokenizer(
            Unigram(
                [(t, float(s)) for t, s in zip(tokens, scores)],
                unk_id=unk_id,
                byte_fallback=True,
            )
        )
        tok.pre_tokenizer = pre_tokenizers.Metaspace(
            replacement="▁", prepend_scheme="first"
        )
        tok.decoder = decoders.Sequence(
            [
                decoders.Replace("▁", " "),
                decoders.ByteFallback(),
                decoders.Fuse(),
                decoders.Strip(" ", 1, 0),
            ]
        )
        return tok
    raise NotImplementedError(
        f"GGUF tokenizer model {model_kind!r} not supported (gpt2 BPE / llama SPM)"
    )


def mdc_from_gguf(path: str | Path, name: str | None = None):
    """GGUF file → ModelDeploymentCard (context length, eos, chat template)."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    gguf = GGUFFile(path)
    meta = gguf.metadata
    arch = meta.get("general.architecture", "llama")
    eos = meta.get("tokenizer.ggml.eos_token_id")
    return ModelDeploymentCard(
        name=name or meta.get("general.name", Path(path).stem),
        path=str(path),
        context_length=int(meta.get(f"{arch}.context_length", 4096)),
        eos_token_ids=[int(eos)] if eos is not None else [],
        chat_template=meta.get("tokenizer.chat_template"),
        model_type=arch,
    ).finalize()


# ------------------------------------------------------------------ weights

# llama.cpp tensor names → our layer-stacked pytree.  GGML stores
# projections as numpy [out, in] after dim reversal → transpose like HF.
_GGUF_LAYER_MAP = {
    "attn_norm": "blk.{i}.attn_norm.weight",
    "wq": "blk.{i}.attn_q.weight",
    "wk": "blk.{i}.attn_k.weight",
    "wv": "blk.{i}.attn_v.weight",
    "wo": "blk.{i}.attn_output.weight",
    "mlp_norm": "blk.{i}.ffn_norm.weight",
    "w_gate": "blk.{i}.ffn_gate.weight",
    "w_up": "blk.{i}.ffn_up.weight",
    "w_down": "blk.{i}.ffn_down.weight",
}


def load_gguf_weights(cfg, gguf: "GGUFFile") -> dict:
    """F32/F16 GGUF tensors → llama param pytree (same layout as
    models.llama.load_hf_weights)."""
    import jax.numpy as jnp

    def get(name: str, transpose: bool = False):
        t = gguf.tensor_data(name)
        if transpose:
            t = t.T
        return jnp.asarray(t, cfg.dtype)

    layer_map = dict(_GGUF_LAYER_MAP)
    if cfg.attention_bias:
        layer_map.update(
            bq="blk.{i}.attn_q.bias", bk="blk.{i}.attn_k.bias", bv="blk.{i}.attn_v.bias"
        )
    layers: dict[str, list] = {k: [] for k in layer_map}
    for i in range(cfg.num_layers):
        for ours, theirs in layer_map.items():
            layers[ours].append(get(theirs.format(i=i), transpose=ours.startswith("w")))
    params = {
        "embed": get("token_embd.weight"),
        "final_norm": get("output_norm.weight"),
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
    }
    if not cfg.tie_word_embeddings and "output.weight" in gguf.tensors:
        params["lm_head"] = get("output.weight", transpose=True)
    return params
