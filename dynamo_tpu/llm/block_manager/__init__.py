"""Multi-tier KV block manager (KVBM).

TPU-native re-design of the reference's block manager (reference:
lib/llm/src/block_manager.rs:68-118 and block_manager/): a hierarchy of
fixed-size KV block pools

    G1 device HBM  →  G2 host DRAM  →  G3 local disk (→ G4 remote)

with block lifecycle Reset → Partial → Complete → Registered, content-hash
registry for dedupe/reuse, LRU eviction of registered blocks, and an offload
manager that moves cold blocks down-tier and onboards prefix hits back up.

Data movement is XLA-native: device↔host via ``jax.device_put``/
``device_get`` (replaces cudaMemcpyAsync), host↔disk via memory-mapped
files (replaces GDS), remote via the DCN transfer client (replaces NIXL
RDMA).  The Null storage backend provides metadata-only pools for
infrastructure tests, mirroring the reference's Null allocators
(block_manager/storage.rs:446-519).
"""

from dynamo_tpu.llm.block_manager.storage import (
    DeviceStorage,
    DiskStorage,
    HostStorage,
    NullStorage,
    block_nbytes,
)
from dynamo_tpu.llm.block_manager.pool import BlockPool, BlockState
from dynamo_tpu.llm.block_manager.manager import KvBlockManager, KvbmConfig, Tier
from dynamo_tpu.llm.block_manager.offload import OffloadManager

__all__ = [
    "BlockPool",
    "BlockState",
    "DeviceStorage",
    "DiskStorage",
    "HostStorage",
    "KvBlockManager",
    "KvbmConfig",
    "NullStorage",
    "OffloadManager",
    "Tier",
    "block_nbytes",
]
