"""G4 remote KV block tier: a block store served over DCN (TCP).

The reference's fourth tier is remote memory reached via NIXL RDMA
descriptors (lib/llm/src/block_manager.rs:68-81 G4, storage/nixl.rs:98-231
remote descriptors).  TPUs have no host-initiated RDMA plane, so the
TPU-native shape is host-staged DCN: a ``BlockStoreServer`` process owns a
big block pool (host DRAM or SSD) and serves batched read/write by block id
over TCP with the two-part codec; decode/prefill hosts mount it as a
``RemoteStorage`` backend — the same uniform ``Storage`` interface every
other tier uses, so pools/offload/onboard logic is tier-agnostic.

Wire protocol (one two-part frame per request/response):
    → {op: "write", ids: [...], dtype, shape}  payload = raw block bytes
    ← {ok: true}
    → {op: "read", ids: [...]}
    ← {ok: true, dtype, shape}                 payload = raw block bytes
    → {op: "info"}
    ← {ok: true, num_blocks, dtype, shape}

Run standalone:  python -m dynamo_tpu.llm.block_manager.remote --port 7051 \
    --num-blocks 4096 --shape 2,2,16,2,16 --dtype float32
"""

from __future__ import annotations

import argparse
import asyncio
import queue
import socket
import threading

import numpy as np

from dynamo_tpu.llm.block_manager.storage import Storage
from dynamo_tpu.runtime.codec import (
    TwoPartMessage,
    encode_frame,
    read_two_part,
    read_two_part_sync,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("llm.block_manager.remote")


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class BlockStoreServer:
    """Owns a local Storage backend and serves it to remote mounters."""

    def __init__(self, backing: Storage, *, host: str = "127.0.0.1", port: int = 0):
        self.backing = backing
        self.host = host
        self.port = port
        self._server: asyncio.Server | None = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("block store serving %d blocks on %s", self.backing.num_blocks, self.address)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self.backing.close()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                msg = await read_two_part(reader)
                if msg is None:
                    return
                try:
                    reply = await self._dispatch(msg)
                except Exception as exc:  # noqa: BLE001
                    logger.exception("block store request failed")
                    reply = TwoPartMessage({"ok": False, "error": str(exc)})
                writer.write(encode_frame(reply))
                await writer.drain()
        finally:
            writer.close()

    async def _dispatch(self, msg: TwoPartMessage) -> TwoPartMessage:
        op = msg.header.get("op")
        if op == "info":
            probe = self.backing.read_batch([0])
            return TwoPartMessage(
                {
                    "ok": True,
                    "num_blocks": self.backing.num_blocks,
                    "dtype": probe.dtype.name,
                    "shape": list(probe.shape[1:]),
                }
            )
        ids = list(msg.header.get("ids", []))
        if op == "read":
            data = await asyncio.to_thread(self.backing.read_batch, ids)
            return TwoPartMessage(
                {"ok": True, "dtype": data.dtype.name, "shape": list(data.shape)},
                np.ascontiguousarray(data).tobytes(),
            )
        if op == "write":
            dtype = _resolve_dtype(msg.header["dtype"])
            data = np.frombuffer(msg.payload, dtype=dtype).reshape(msg.header["shape"])
            await asyncio.to_thread(self.backing.write_batch, ids, data)
            return TwoPartMessage({"ok": True})
        return TwoPartMessage({"ok": False, "error": f"unknown op {op!r}"})


class RemoteStorage(Storage):
    """Client-side Storage backend mounted on a BlockStoreServer.

    Synchronous (the offload manager drives Storage through
    ``asyncio.to_thread``); a small blocking-socket pool makes concurrent
    batch transfers from multiple offload workers safe.
    """

    def __init__(self, address: str, *, pool_size: int = 4, timeout: float = 30.0):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._pool: queue.Queue[socket.socket] = queue.Queue()
        self._pool_size = pool_size
        self._created = 0
        self._lock = threading.Lock()
        info = self._request({"op": "info"})
        self.num_blocks = info.header["num_blocks"]
        self.shape = tuple(info.header["shape"])
        self.dtype = _resolve_dtype(info.header["dtype"])

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _acquire(self) -> socket.socket:
        try:
            return self._pool.get_nowait()
        except queue.Empty:
            pass
        with self._lock:
            may_create = self._created < self._pool_size
            if may_create:
                self._created += 1
        if may_create:
            try:
                return self._connect()
            except Exception:
                with self._lock:
                    self._created -= 1  # failed connect must not leak the slot
                raise
        try:
            return self._pool.get(timeout=self._timeout)
        except queue.Empty:
            raise TimeoutError(
                f"no free connection to block store {self._addr} after {self._timeout}s"
            ) from None

    def _request(self, header: dict, payload: bytes = b"") -> TwoPartMessage:
        sock = self._acquire()
        try:
            sock.sendall(encode_frame(TwoPartMessage(header, payload)))
            reply = read_two_part_sync(sock)
        except Exception:
            with self._lock:
                self._created -= 1
            sock.close()
            raise
        if reply is None:
            with self._lock:
                self._created -= 1
            sock.close()
            raise ConnectionError(f"block store {self._addr} closed the connection")
        self._pool.put(sock)
        if not reply.header.get("ok"):
            raise RuntimeError(f"block store error: {reply.header.get('error')}")
        return reply

    def read_batch(self, block_ids: list[int]) -> np.ndarray:
        reply = self._request({"op": "read", "ids": [int(b) for b in block_ids]})
        dtype = _resolve_dtype(reply.header["dtype"])
        return np.frombuffer(reply.payload, dtype=dtype).reshape(reply.header["shape"]).copy()

    def write_batch(self, block_ids: list[int], data: np.ndarray) -> None:
        data = np.ascontiguousarray(data)
        self._request(
            {
                "op": "write",
                "ids": [int(b) for b in block_ids],
                "dtype": data.dtype.name,
                "shape": list(data.shape),
            },
            data.tobytes(),
        )

    def close(self) -> None:
        while True:
            try:
                self._pool.get_nowait().close()
            except queue.Empty:
                return


def main() -> int:
    from dynamo_tpu.llm.block_manager.storage import DiskStorage, HostStorage
    from dynamo_tpu.utils.logging import configure_logging

    parser = argparse.ArgumentParser(description="standalone G4 block store server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=7051)
    parser.add_argument("--num-blocks", type=int, default=4096)
    parser.add_argument("--shape", default="2,2,16,2,16",
                        help="block shape layers,kv,block_size,kv_heads,head_dim")
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--nbytes", type=int, default=None,
                        help="serve RAW uint8 payload blocks of this size "
                             "instead of structured --shape/--dtype blocks "
                             "(what a serving engine's G4 tier mounts; the "
                             "engine logs its block_nbytes at startup and "
                             "errors with both sizes on mismatch)")
    parser.add_argument("--disk-path", default=None,
                        help="back the store with an SSD memmap instead of DRAM")
    args = parser.parse_args()

    configure_logging()
    if args.nbytes:
        shape, dtype = (args.nbytes,), np.dtype(np.uint8)
    else:
        shape = tuple(int(x) for x in args.shape.split(","))
        dtype = _resolve_dtype(args.dtype)
    if args.disk_path:
        backing: Storage = DiskStorage(args.num_blocks, shape, dtype, path=args.disk_path)
    else:
        backing = HostStorage(args.num_blocks, shape, dtype)

    async def run() -> None:
        server = BlockStoreServer(backing, host=args.host, port=args.port)
        await server.start()
        try:
            await asyncio.Event().wait()
        finally:
            await server.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
