"""KvBlockManager facade: the tiered cache as one object.

(Reference: lib/llm/src/block_manager.rs:90-118 KvBlockManager over
KvBlockManagerState.)  Wires pools G1 (device HBM) / G2 (host) / G3 (disk)
with the offload manager, and exposes the sequence-level operations the
engine uses:

- ``store_sequence(hashes, data)``     — register freshly-computed blocks
- ``match_prefix(hashes)``             — longest cached prefix across tiers,
  onboarding lower-tier hits into the target tier
- ``release_sequence`` / eviction via pool LRU + background offload
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

from dynamo_tpu.llm.block_manager.offload import OffloadManager
from dynamo_tpu.llm.block_manager.pool import BlockPool
from dynamo_tpu.llm.block_manager.storage import (
    DeviceStorage,
    DiskStorage,
    HostStorage,
    NullStorage,
    block_shape,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("llm.block_manager")


class Tier(str, enum.Enum):
    G1_DEVICE = "g1"
    G2_HOST = "g2"
    G3_DISK = "g3"
    G4_REMOTE = "g4"


@dataclass
class KvbmConfig:
    num_layers: int = 2
    block_size: int = 16
    kv_heads: int = 2
    head_dim: int = 16
    dtype: object = np.float32
    device_blocks: int = 0          # 0 = no device tier (host-only tests)
    host_blocks: int = 128
    disk_blocks: int = 0            # 0 = no disk tier
    disk_path: str | None = None
    remote_address: str | None = None  # "host:port" of a BlockStoreServer (G4)
    null_storage: bool = False      # metadata-only pools (fast logic tests)
    # raw-payload mode: tiers hold pre-serialized blocks of this exact shape
    # (the serving engine's offload tier serializes each cache-pytree slice
    # to one uint8 vector), bypassing the structured layers/heads layout
    payload_shape: tuple | None = None


class KvBlockManager:
    def __init__(self, config: KvbmConfig):
        self.config = config
        shape = tuple(config.payload_shape) if config.payload_shape else block_shape(
            config.num_layers, config.block_size, config.kv_heads, config.head_dim
        )
        self.pools: dict[str, BlockPool] = {}

        def make_storage(n: int, kind: str):
            if config.null_storage:
                return NullStorage(n, shape, config.dtype)
            if kind == "device":
                return DeviceStorage(n, shape, config.dtype)
            if kind == "disk":
                return DiskStorage(n, shape, config.dtype, path=config.disk_path)
            return HostStorage(n, shape, config.dtype)

        if config.device_blocks:
            self.pools[Tier.G1_DEVICE] = BlockPool(
                make_storage(config.device_blocks, "device"), tier_name="g1"
            )
        if config.host_blocks:
            self.pools[Tier.G2_HOST] = BlockPool(
                make_storage(config.host_blocks, "host"), tier_name="g2"
            )
        if config.disk_blocks:
            if not config.disk_path and not config.null_storage:
                raise ValueError("disk tier needs disk_path")
            self.pools[Tier.G3_DISK] = BlockPool(
                make_storage(config.disk_blocks, "disk"), tier_name="g3"
            )
        if config.remote_address:
            # G4: a BlockStoreServer mounted over DCN. The mounter owns the
            # server's block-id space (one logical owner per store; shared
            # read-only mounts would need a coordination layer on top).
            # NOTE: mounting does blocking network IO — construct the manager
            # off the event loop (see ``create_async``).
            from dynamo_tpu.llm.block_manager.remote import RemoteStorage

            remote = RemoteStorage(config.remote_address)
            if remote.shape != shape:
                raise ValueError(
                    f"block store {config.remote_address} serves blocks of shape "
                    f"{remote.shape}, but this manager is configured for {shape}"
                )
            if np.dtype(remote.dtype) != np.dtype(config.dtype):
                raise ValueError(
                    f"block store {config.remote_address} serves dtype "
                    f"{remote.dtype}, but this manager is configured for "
                    f"{np.dtype(config.dtype)}"
                )
            self.pools[Tier.G4_REMOTE] = BlockPool(remote, tier_name="g4")
        if not self.pools:
            raise ValueError("at least one tier required")
        self.tier_order = [
            t
            for t in (Tier.G1_DEVICE, Tier.G2_HOST, Tier.G3_DISK, Tier.G4_REMOTE)
            if t in self.pools
        ]
        self.offload = OffloadManager(
            {t: p for t, p in self.pools.items()}, tier_order=list(self.tier_order)
        )

    @classmethod
    async def create_async(cls, config: KvbmConfig) -> "KvBlockManager":
        """Construct off the event loop: mounting a G4 store does blocking
        TCP connect + info RPC in the constructor."""
        import asyncio

        return await asyncio.to_thread(cls, config)

    def start(self) -> None:
        self.offload.start()

    async def stop(self) -> None:
        await self.offload.stop()
        for pool in self.pools.values():
            pool.storage.close()

    # -- sequence ops --------------------------------------------------------
    @property
    def primary(self) -> BlockPool:
        return self.pools[self.tier_order[0]]

    def store_sequence(
        self, seq_hashes: list[int], data: np.ndarray | None = None, *, offload: bool = True
    ) -> list[int] | None:
        """Register computed blocks in the primary tier (data: [n, *block]),
        queueing background offload one tier down."""
        pool = self.primary
        ids = []
        for i, h in enumerate(seq_hashes):
            existing = pool.match_hash(h)
            if existing is not None:
                ids.append(existing)
                continue
            bid = pool.allocate()
            if bid is None:
                for b in ids:
                    pool.release(b)
                return None
            if data is not None:
                pool.write([bid], data[i : i + 1])
            pool.complete(bid, self.config.block_size)
            pool.register(bid, h)
            ids.append(bid)
            if offload and len(self.tier_order) > 1:
                self.offload.request_offload(
                    self.tier_order[0], self.tier_order[1], bid, h
                )
        return ids

    def match_prefix_tier(self, seq_hashes: list[int], tier: Tier) -> int:
        """How many prefix blocks a tier holds (no side effects)."""
        pool = self.pools[tier]
        n = 0
        for h in seq_hashes:
            if not pool.has_hash(h):
                break
            n += 1
        return n

    async def match_and_onboard(self, seq_hashes: list[int]) -> tuple[list[int], Tier | None]:
        """Longest cached prefix: try primary tier first, then onboard from
        lower tiers.  Returns (primary-tier block ids with bumped refs, tier
        the data came from)."""
        primary = self.primary
        hit_ids: list[int] = []
        matched_from: Tier | None = None
        n_primary = 0
        for h in seq_hashes:
            bid = primary.match_hash(h)
            if bid is None:
                break
            hit_ids.append(bid)
            n_primary += 1
        if n_primary:
            matched_from = self.tier_order[0]
        # extend from lower tiers
        remaining = seq_hashes[n_primary:]
        for tier in self.tier_order[1:]:
            if not remaining:
                break
            n = self.match_prefix_tier(remaining, tier)
            if n == 0:
                continue
            onboarded = await self.offload.onboard(remaining[:n], self.tier_order[0], tier)
            if onboarded is None:
                break
            # bump refs for the caller (onboard registered + released them)
            for h in remaining[:n]:
                bid = primary.match_hash(h)
                if bid is not None:
                    hit_ids.append(bid)
            matched_from = tier
            remaining = remaining[n:]
        return hit_ids, matched_from

    def release_sequence(self, block_ids: list[int]) -> None:
        pool = self.primary
        for bid in block_ids:
            pool.release(bid)

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        out = {}
        for tier, pool in self.pools.items():
            out[tier.value] = {
                "total": pool.num_blocks,
                "free": pool.free_count,
                "inactive": pool.inactive_count,
                "evictions": pool.evictions,
                "reuse_hits": pool.reuse_hits,
            }
        out["offload"] = {
            "completed": self.offload.completed,
            "failed": self.offload.failed,
            "skipped": self.offload.skipped,
        }
        return out
