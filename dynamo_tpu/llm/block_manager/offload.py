"""Offload manager: moves KV blocks between tiers.

(Reference: lib/llm/src/block_manager/offload.rs — priority queue, bounded
concurrency MAX_CONCURRENT_TRANSFERS=4, batching BATCH=16, per-pair transfer
strategies.)  Here the strategies are XLA/OS-native:

    G1→G2  jax.device_get (device→host DMA)
    G2→G1  jax.device_put (host→device DMA)
    G2↔G3  memmap IO
    G1→G3  staged through G2

Transfers are batched and run on a bounded set of worker tasks; completion
registers the block's hash in the destination pool.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
from dataclasses import dataclass, field

from dynamo_tpu.llm.block_manager.pool import BlockPool
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("llm.block_manager.offload")

MAX_CONCURRENT_TRANSFERS = 4
TRANSFER_BATCH = 16


@dataclass(order=True)
class _Job:
    priority: int
    seq: int
    src_tier: str = field(compare=False)
    dst_tier: str = field(compare=False)
    block_id: int = field(compare=False)
    seq_hash: int = field(compare=False)


class OffloadManager:
    def __init__(self, pools: dict[str, BlockPool], tier_order: list | None = None):
        self.pools = pools
        # when tier order is known, completed offloads cascade one tier
        # further down (G1→G2→G3→G4 population, reference offload.rs)
        self.tier_order = tier_order or []
        self._queue: list[_Job] = []
        self._seq = itertools.count()
        self._wake = asyncio.Event()
        self._stopping = False
        self._workers: list[asyncio.Task] = []
        self._inflight = 0
        # hashes an onboard() is currently copying up-tier: a concurrent
        # onboard for the same hash (demand restore racing a prefetch hint)
        # awaits the first copy instead of double-allocating (event per
        # batch; single-event-loop use by construction)
        self._onboard_inflight: dict[int, asyncio.Event] = {}
        self.completed = 0
        self.failed = 0
        self.skipped = 0
        self.tier_inserts: dict[str, int] = {}  # per-tier insert_sync counts

    def start(self, workers: int = MAX_CONCURRENT_TRANSFERS) -> None:
        if not self._workers:
            self._workers = [
                spawn_logged(self._worker()) for _ in range(workers)
            ]

    async def stop(self, drain_timeout: float = 5.0) -> None:
        """Drain in-flight transfers, then stop workers.

        Cancelling a task blocked in ``to_thread`` abandons a still-running
        OS thread that would race the storage close that follows — so ask
        workers to exit between batches and only cancel stragglers after
        the drain timeout."""
        self._stopping = True
        self._wake.set()
        workers, self._workers = self._workers, []
        if not workers:
            return
        done, pending = await asyncio.wait(workers, timeout=drain_timeout)
        for w in pending:
            w.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)

    # -- API -----------------------------------------------------------------
    def request_offload(
        self, src_tier: str, dst_tier: str, block_id: int, seq_hash: int, *, priority: int = 10
    ) -> None:
        """Queue a copy of a registered block down-tier (lower priority value
        = sooner)."""
        heapq.heappush(
            self._queue,
            _Job(priority, next(self._seq), src_tier, dst_tier, block_id, seq_hash),
        )
        self._wake.set()

    async def onboard(
        self,
        seq_hashes: list[int],
        dst_tier: str,
        src_tier: str,
        *,
        on_fully_evicted=None,
    ) -> list[int] | None:
        """Bring blocks up-tier (prefix hit on a lower tier, or a prefetch
        hint promoting disk/remote content toward the device).  Returns the
        destination block ids of the hashes THIS call copied (may be empty
        when every hash was already up-tier), or None if the source lost a
        hash or the destination could not allocate — nothing is claimed on
        failure.

        Safe under concurrent demand + prefetch requests for the same
        hashes: hashes already registered in ``dst_tier`` are skipped
        (dedupe — callers re-match by hash afterwards), and hashes another
        onboard is mid-copy are awaited rather than double-allocated, so
        the same content can never occupy two destination blocks and no
        allocation leaks.  Destination-LRU evictions the allocation causes
        cascade one tier further down read-before-overwrite (same contract
        as ``insert_sync``); ``on_fully_evicted`` fires for hashes the
        cascade pushed out of the bottom tier."""
        src = self.pools[src_tier]
        dst = self.pools[dst_tier]
        # wait out copies another onboard already has in flight for these
        # hashes (re-check after each wait: the set mutates while we sleep)
        while True:
            waiting = [
                ev for h in seq_hashes
                if (ev := self._onboard_inflight.get(h)) is not None
            ]
            if not waiting:
                break
            for ev in waiting:
                await ev.wait()
        todo = [h for h in seq_hashes if not dst.has_hash(h)]
        self.skipped += len(seq_hashes) - len(todo)
        if not todo:
            return []
        done_ev = asyncio.Event()
        for h in todo:
            self._onboard_inflight[h] = done_ev
        try:
            src_ids = []
            for h in todo:
                bid = src.match_hash(h)
                if bid is None:
                    for b in src_ids:
                        src.release(b)
                    return None
                src_ids.append(bid)
            # next tier down receives anything the dst allocation evicts
            nxt = None
            if dst_tier in self.tier_order:
                idx = self.tier_order.index(dst_tier)
                if idx + 1 < len(self.tier_order):
                    nxt = self.tier_order[idx + 1]
            dst_ids = []
            for h in todo:
                captured: list[int] = []
                prev_sink = dst.evict_sink
                dst.evict_sink = captured.append
                try:
                    bid = dst.allocate()
                finally:
                    dst.evict_sink = prev_sink
                if bid is None:
                    for b in dst_ids:
                        dst.release(b)
                    for b in src_ids:
                        src.release(b)
                    return None
                for ev in captured:
                    # the evicted block's bytes still live at ``bid`` until
                    # the write below lands — cascade them down-tier now
                    placed = nxt is not None and self.insert_sync(
                        nxt, dst.read([bid]), ev, on_fully_evicted=on_fully_evicted
                    )
                    if not placed and on_fully_evicted is not None:
                        on_fully_evicted(ev)
                dst_ids.append(bid)
            # batched copy through host
            for start in range(0, len(src_ids), TRANSFER_BATCH):
                chunk_src = src_ids[start : start + TRANSFER_BATCH]
                chunk_dst = dst_ids[start : start + TRANSFER_BATCH]
                data = await asyncio.to_thread(src.read, chunk_src)
                await asyncio.to_thread(dst.write, chunk_dst, data)
            for h, src_bid, dst_bid in zip(todo, src_ids, dst_ids):
                dst.complete(dst_bid, src.blocks[src_bid].token_count)
                dst.register(dst_bid, h)
                # park inactive (discoverable + evictable): callers revive by
                # hash — the old code left the ref, leaking the block as
                # active forever once its caller released only one ref
                dst.release(dst_bid)
            for bid in src_ids:
                src.release(bid)
            self.completed += len(todo)
            return dst_ids
        finally:
            for h in todo:
                if self._onboard_inflight.get(h) is done_ev:
                    del self._onboard_inflight[h]
            done_ev.set()

    def insert_sync(
        self,
        tier,
        data,
        seq_hash: int,
        token_count: int = 0,
        *,
        on_fully_evicted=None,
    ) -> bool:
        """Synchronously insert one serialized block into ``tier``, cascading
        any LRU eviction the insertion causes one tier further down
        (read-before-overwrite: the evicted block's bytes survive in storage
        until the new write lands, so they are copied down FIRST).

        This is the serving engine's path — it runs on the device thread,
        where the async worker machinery above can't be awaited.  Returns
        False when the tier (and thus the chain) cannot take the block;
        ``on_fully_evicted`` fires for any hash the cascade pushed out of
        the bottom tier (it no longer exists anywhere).
        """
        pool = self.pools[tier]
        if pool.has_hash(seq_hash):
            return True
        captured: list[int] = []
        prev_sink = pool.evict_sink
        pool.evict_sink = captured.append
        try:
            bid = pool.allocate()
        finally:
            pool.evict_sink = prev_sink
        if bid is None:
            return False
        nxt = None
        if tier in self.tier_order:
            idx = self.tier_order.index(tier)
            if idx + 1 < len(self.tier_order):
                nxt = self.tier_order[idx + 1]
        for ev in captured:
            # the evicted block's bytes still live at ``bid`` until the
            # write below — copy them down-tier now or lose them
            placed = nxt is not None and self.insert_sync(
                nxt, pool.read([bid]), ev, on_fully_evicted=on_fully_evicted
            )
            if not placed and on_fully_evicted is not None:
                on_fully_evicted(ev)
        pool.write([bid], data)
        pool.complete(bid, token_count)
        pool.register(bid, seq_hash)
        pool.release(bid)  # park in the inactive LRU, discoverable + evictable
        self.completed += 1
        key = tier.value if hasattr(tier, "value") else str(tier)
        self.tier_inserts[key] = self.tier_inserts.get(key, 0) + 1
        return True

    # -- workers ---------------------------------------------------------------
    async def _worker(self) -> None:
        while True:
            while not self._queue:
                if self._stopping:
                    return
                self._wake.clear()
                if self._stopping:  # re-check: stop() may have set the (now
                    return          # cleared) wake event in between
                await self._wake.wait()
            # batch same src→dst pairs
            job = heapq.heappop(self._queue)
            batch = [job]
            rest: list[_Job] = []
            while self._queue and len(batch) < TRANSFER_BATCH:
                nxt = heapq.heappop(self._queue)
                if nxt.src_tier == job.src_tier and nxt.dst_tier == job.dst_tier:
                    batch.append(nxt)
                else:
                    rest.append(nxt)
            for r in rest:
                heapq.heappush(self._queue, r)
            try:
                await self._transfer(batch)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                self.failed += len(batch)
                logger.exception("offload batch failed")

    async def _transfer(self, batch: list[_Job]) -> None:
        src = self.pools[batch[0].src_tier]
        dst = self.pools[batch[0].dst_tier]
        jobs = []
        for job in batch:
            if dst.has_hash(job.seq_hash):
                self.skipped += 1  # already down-tier (dedupe)
                continue
            if src.blocks[job.block_id].seq_hash != job.seq_hash:
                self.skipped += 1  # stale: source block evicted/reused since queued
                continue
            jobs.append(job)
        if not jobs:
            return
        dst_ids = []
        kept: list[_Job] = []
        for job in jobs:
            bid = dst.allocate()
            if bid is None:
                self.failed += 1
                continue
            dst_ids.append(bid)
            kept.append(job)
        if not kept:
            return
        data = await asyncio.to_thread(src.read, [j.block_id for j in kept])
        await asyncio.to_thread(dst.write, dst_ids, data)
        next_tier = None
        if batch[0].dst_tier in self.tier_order:
            idx = self.tier_order.index(batch[0].dst_tier)
            if idx + 1 < len(self.tier_order):
                next_tier = self.tier_order[idx + 1]
        for job, bid in zip(kept, dst_ids):
            dst.complete(bid, src.blocks[job.block_id].token_count)
            dst.register(bid, job.seq_hash)
            dst.release(bid)  # parks in inactive LRU, discoverable
            self.completed += 1
            if next_tier is not None:
                self.request_offload(
                    batch[0].dst_tier, next_tier, bid, job.seq_hash,
                    priority=job.priority + 1,
                )
