"""Storage backends for KV block pools.

A block's payload is one ndarray ``[layers, 2(kv), block_size, kv_heads,
head_dim]``.  Backends expose uniform read/write by block id; batched
variants amortize dispatch (the transfer engine always moves batches).

(Reference: lib/llm/src/block_manager/storage.rs — System/Pinned/Device/
Disk/Null backends; here Device is a jax array in HBM, Host is numpy in
DRAM — effectively pinned for TPU DMA purposes — Disk is a memmap.)
"""

from __future__ import annotations

from pathlib import Path

import numpy as np


def block_shape(num_layers: int, block_size: int, kv_heads: int, head_dim: int) -> tuple:
    return (num_layers, 2, block_size, kv_heads, head_dim)


def block_nbytes(num_layers, block_size, kv_heads, head_dim, dtype) -> int:
    return int(np.prod(block_shape(num_layers, block_size, kv_heads, head_dim))) * np.dtype(dtype).itemsize


class Storage:
    """Uniform block storage interface."""

    num_blocks: int

    def read(self, block_id: int) -> np.ndarray:
        return self.read_batch([block_id])[0]

    def write(self, block_id: int, data: np.ndarray) -> None:
        self.write_batch([block_id], data[None])

    def read_batch(self, block_ids: list[int]) -> np.ndarray:
        raise NotImplementedError

    def write_batch(self, block_ids: list[int], data: np.ndarray) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullStorage(Storage):
    """Metadata-only: accepts writes, reads zeros.  For pool/offload logic
    tests with no memory cost."""

    def __init__(self, num_blocks: int, shape: tuple, dtype=np.float32):
        self.num_blocks = num_blocks
        self.shape = shape
        self.dtype = np.dtype(dtype)

    def read_batch(self, block_ids: list[int]) -> np.ndarray:
        return np.zeros((len(block_ids), *self.shape), self.dtype)

    def write_batch(self, block_ids: list[int], data: np.ndarray) -> None:
        pass


class HostStorage(Storage):
    """Host DRAM pool (G2)."""

    def __init__(self, num_blocks: int, shape: tuple, dtype=np.float32):
        self.num_blocks = num_blocks
        self.shape = shape
        self._data = np.zeros((num_blocks, *shape), dtype)

    def read_batch(self, block_ids: list[int]) -> np.ndarray:
        return self._data[np.asarray(block_ids, np.int64)].copy()

    def write_batch(self, block_ids: list[int], data: np.ndarray) -> None:
        self._data[np.asarray(block_ids, np.int64)] = data


class DiskStorage(Storage):
    """Local SSD pool (G3) via np.memmap (host-mediated; the TPU analog of
    the reference's GDS-backed disk tier)."""

    def __init__(self, num_blocks: int, shape: tuple, dtype=np.float32, *, path: str | Path):
        self.num_blocks = num_blocks
        self.shape = shape
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._data = np.memmap(
            self.path, dtype=dtype, mode="w+", shape=(num_blocks, *shape)
        )

    def read_batch(self, block_ids: list[int]) -> np.ndarray:
        return np.asarray(self._data[np.asarray(block_ids, np.int64)])

    def write_batch(self, block_ids: list[int], data: np.ndarray) -> None:
        self._data[np.asarray(block_ids, np.int64)] = data

    def flush(self) -> None:
        self._data.flush()

    def close(self) -> None:
        self.flush()
        del self._data


class DeviceStorage(Storage):
    """Device HBM pool (G1): one jax array, batched gather/scatter transfers
    (jax.device_put/get replace cudaMemcpy; on TPU these ride the host DMA
    path, and same-mesh moves stay on ICI)."""

    def __init__(self, num_blocks: int, shape: tuple, dtype=None, *, device=None, sharding=None):
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self.num_blocks = num_blocks
        self.shape = shape
        dtype = dtype or jnp.float32
        self._data = jnp.zeros((num_blocks, *shape), dtype)
        if sharding is not None:
            self._data = jax.device_put(self._data, sharding)
        elif device is not None:
            self._data = jax.device_put(self._data, device)
        # on TPU the Pallas block-copy kernels move blocks with pipelined
        # HBM↔VMEM DMAs (the block_copy.cu replacement, SURVEY.md §2.2);
        # XLA gather/scatter is the portable fallback
        use_pallas = False
        if sharding is None:
            try:
                use_pallas = jax.default_backend() == "tpu"
            except Exception:  # wedged plugin: portable path
                use_pallas = False
        if use_pallas:
            from dynamo_tpu.ops.pallas.block_copy import gather_blocks, scatter_blocks

            self._write = lambda pool, ids, blocks: scatter_blocks(
                pool, blocks.astype(pool.dtype), ids
            )
            self._read = gather_blocks
        else:
            self._write = jax.jit(
                lambda pool, ids, blocks: pool.at[ids].set(blocks.astype(pool.dtype)),
                donate_argnums=(0,),
            )
            self._read = jax.jit(lambda pool, ids: pool[ids])

    @property
    def array(self):
        return self._data

    def read_batch(self, block_ids: list[int]) -> np.ndarray:
        ids = self._jnp.asarray(np.asarray(block_ids, np.int32))
        return np.asarray(self._read(self._data, ids))

    def write_batch(self, block_ids: list[int], data: np.ndarray) -> None:
        ids = self._jnp.asarray(np.asarray(block_ids, np.int32))
        self._data = self._write(self._data, ids, self._jnp.asarray(data))
