"""Block pool: lifecycle, registry, reuse, eviction.

Lifecycle (reference: lib/llm/src/block_manager/block.rs):
    RESET → PARTIAL (tokens being appended) → COMPLETE (full) →
    REGISTERED (content-hashed, discoverable for reuse)

A pool keeps an *active* set (held by sequences) and an *inactive* set of
registered blocks in LRU order (reference: block_manager/pool.rs,
pool/inactive.rs).  Allocation prefers the free list, then evicts the
least-recently-used inactive registered block.  ``match_hash`` revives an
inactive registered block (prefix cache hit) instead of recomputing it.
"""

from __future__ import annotations

import enum
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from dynamo_tpu.llm.block_manager.storage import Storage
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("llm.block_manager.pool")


class BlockState(enum.Enum):
    RESET = "reset"
    PARTIAL = "partial"
    COMPLETE = "complete"
    REGISTERED = "registered"


@dataclass
class BlockMeta:
    block_id: int
    state: BlockState = BlockState.RESET
    seq_hash: int | None = None
    token_count: int = 0
    ref_count: int = 0
    registered_at: float = 0.0


class BlockPool:
    def __init__(self, storage: Storage, *, tier_name: str = "pool"):
        self.storage = storage
        self.tier_name = tier_name
        self.blocks = [BlockMeta(block_id=i) for i in range(storage.num_blocks)]
        self._free: deque[int] = deque(range(storage.num_blocks))
        # inactive registered blocks: seq_hash -> block_id in LRU order
        self._inactive: OrderedDict[int, int] = OrderedDict()
        self._by_hash: dict[int, int] = {}
        # optional observer: called with the seq_hash of each block evicted
        # by allocate() (tier owners propagate removed events from it)
        self.evict_sink = None
        # stats
        self.evictions = 0
        self.reuse_hits = 0

    # -- capacity ------------------------------------------------------------
    @property
    def num_blocks(self) -> int:
        return self.storage.num_blocks

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def inactive_count(self) -> int:
        return len(self._inactive)

    @property
    def available(self) -> int:
        return self.free_count + self.inactive_count

    # -- allocation ------------------------------------------------------------
    def allocate(self) -> int | None:
        """A RESET block for writing; evicts LRU inactive if free list empty."""
        if self._free:
            bid = self._free.popleft()
        elif self._inactive:
            _, bid = self._inactive.popitem(last=False)  # LRU
            meta = self.blocks[bid]
            if meta.seq_hash is not None:
                self._by_hash.pop(meta.seq_hash, None)
                if self.evict_sink is not None:
                    self.evict_sink(meta.seq_hash)
            self.evictions += 1
        else:
            return None
        meta = self.blocks[bid]
        meta.state = BlockState.PARTIAL
        meta.seq_hash = None
        meta.token_count = 0
        meta.ref_count = 1
        return bid

    def complete(self, block_id: int, token_count: int) -> None:
        meta = self.blocks[block_id]
        meta.state = BlockState.COMPLETE
        meta.token_count = token_count

    def register(self, block_id: int, seq_hash: int) -> None:
        """Make a complete block discoverable by content hash.  If the hash
        is already registered, this block stays unregistered (dedupe —
        reference: block/registry.rs)."""
        meta = self.blocks[block_id]
        if seq_hash in self._by_hash and self._by_hash[seq_hash] != block_id:
            meta.state = BlockState.COMPLETE
            return
        meta.state = BlockState.REGISTERED
        meta.seq_hash = seq_hash
        meta.registered_at = time.monotonic()
        self._by_hash[seq_hash] = block_id

    def match_hash(self, seq_hash: int) -> int | None:
        """Prefix-cache lookup: revive an inactive registered block (bumps
        ref) or return an active one."""
        bid = self._by_hash.get(seq_hash)
        if bid is None:
            return None
        if seq_hash in self._inactive:
            self._inactive.pop(seq_hash)
        self.blocks[bid].ref_count += 1
        self.reuse_hits += 1
        return bid

    def has_hash(self, seq_hash: int) -> bool:
        return seq_hash in self._by_hash

    def peek_hash(self, seq_hash: int) -> int | None:
        """Non-reviving lookup: the block id registered under this hash
        without touching refcounts or the inactive LRU (for callers that
        already hold a pin from ``match_hash``)."""
        return self._by_hash.get(seq_hash)

    def registered_hashes(self) -> list[int]:
        return list(self._by_hash)

    def ref_count(self, seq_hash: int) -> int:
        bid = self._by_hash.get(seq_hash)
        return 0 if bid is None else self.blocks[bid].ref_count

    def release(self, block_id: int) -> None:
        """Sequence done with the block: registered blocks park in the
        inactive LRU (still reusable); others return to the free list."""
        meta = self.blocks[block_id]
        meta.ref_count = max(0, meta.ref_count - 1)
        if meta.ref_count > 0:
            return
        if meta.state == BlockState.REGISTERED and meta.seq_hash is not None:
            self._inactive[meta.seq_hash] = block_id
            self._inactive.move_to_end(meta.seq_hash)
        else:
            self._reset(block_id)

    def _reset(self, block_id: int) -> None:
        meta = self.blocks[block_id]
        if meta.seq_hash is not None:
            self._by_hash.pop(meta.seq_hash, None)
            self._inactive.pop(meta.seq_hash, None)
        meta.state = BlockState.RESET
        meta.seq_hash = None
        meta.token_count = 0
        meta.ref_count = 0
        self._free.append(block_id)

    def drop_hash(self, seq_hash: int) -> None:
        """Forcibly forget a registered hash (used when a tier invalidates)."""
        bid = self._by_hash.get(seq_hash)
        if bid is not None:
            self._reset(bid)

    # -- data ------------------------------------------------------------------
    def read(self, block_ids: list[int]):
        return self.storage.read_batch(block_ids)

    def write(self, block_ids: list[int], data) -> None:
        self.storage.write_batch(block_ids, data)
