"""Disaggregated prefill/decode.

Decision + orchestration (reference: SURVEY.md §3.4; decision thresholds
lib/llm/src/disagg_router.rs:25-34 with etcd hot-reload :38-90; queue
examples/llm/utils/prefill_queue.py + NatsQueue):

- ``DisaggRouter``    — prefill locally vs remotely: remote iff prompt length
  exceeds ``max_local_prefill_length`` AND the prefill queue is not backed
  up; config hot-reloads from a control-plane KV key watch.
- ``PrefillQueue``    — durable work queue on the control-plane bus.
- ``DisaggDecodeEngine`` — decode-worker engine wrapper: on remote decision,
  reserves landing blocks, enqueues a RemotePrefillRequest, waits for the KV
  transfer, then decodes.  Local decision falls through to the inner engine.
- ``PrefillWorker``   — dequeues, prefills on its own engine/mesh, ships KV
  blocks to the decode worker's transfer server.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid
from dataclasses import dataclass

from dynamo_tpu.engine.engine import JaxLlmEngine
from dynamo_tpu.llm.protocols.common import PreprocessedRequest
from dynamo_tpu.observability import get_recorder
from dynamo_tpu.observability.trace import read_trace, stamp_trace
from dynamo_tpu.parallel.kv_transfer import (
    KvTransferClient,
    KvTransferPayload,
    KvTransferServer,
)
from dynamo_tpu.robustness.faults import FAULTS, PREFILL_DEQUEUE
from dynamo_tpu.robustness.retry import Backoff
from dynamo_tpu.runtime.component import ROOT_PATH
from dynamo_tpu.runtime.controlplane.interface import WatchEventType
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("llm.disagg")


def disagg_config_key(model: str) -> str:
    return f"{ROOT_PATH}public/components/disagg_router/models/chat/{model}"


def _payload_bytes(blocks) -> int:
    """Total bytes of a KV transfer payload's cache pytree (host or device
    arrays both expose nbytes)."""
    import jax

    return int(sum(getattr(leaf, "nbytes", 0) for leaf in jax.tree.leaves(blocks)))


@dataclass
class DisaggConfig:
    max_local_prefill_length: int = 512
    max_prefill_queue_size: int = 16


class DisaggRouter:
    """Local-vs-remote prefill decision with KV-watched hot reload."""

    def __init__(self, runtime: DistributedRuntime, model: str, config: DisaggConfig | None = None):
        self.runtime = runtime
        self.model = model
        self.config = config or DisaggConfig()
        self._watch = None
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        self._watch = self.runtime.plane.kv.watch_prefix(disagg_config_key(self.model))
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        if self._watch is not None:
            self._watch.cancel()
        if self._task is not None:
            self._task.cancel()

    async def _loop(self) -> None:
        """Run the config watch; on connection loss, resubscribe with
        backoff instead of exiting permanently (pre-fix, one dropped
        control-plane connection froze the disagg thresholds forever —
        the router kept serving on the last config, but could never see
        another hot-reload)."""
        backoff = Backoff(initial=0.1, max_delay=5.0)
        while True:
            started = asyncio.get_running_loop().time()
            try:
                await self._config_loop()
                return  # watch cancelled / closed cleanly (stop())
            except ConnectionError as exc:
                # a watch that survived a while before dying is a fresh,
                # independent outage — don't let attempts accumulate over a
                # long process lifetime until every blip pays the max delay
                if asyncio.get_running_loop().time() - started > 5.0:
                    backoff.reset()
                delay = backoff.next()
                logger.warning(
                    "disagg config watch lost (keeping last config; "
                    "resubscribing in %.1fs): %s", delay, exc,
                )
                await asyncio.sleep(delay)  # stop() cancels us here
                self._watch = self.runtime.plane.kv.watch_prefix(
                    disagg_config_key(self.model)
                )

    async def _config_loop(self) -> None:
        async for event in self._watch:
            if event.type != WatchEventType.PUT:
                continue
            try:
                d = json.loads(event.entry.value)
                self.config = DisaggConfig(
                    max_local_prefill_length=d.get(
                        "max_local_prefill_length", self.config.max_local_prefill_length
                    ),
                    max_prefill_queue_size=d.get(
                        "max_prefill_queue_size", self.config.max_prefill_queue_size
                    ),
                )
                logger.info("disagg config reloaded: %s", self.config)
            except Exception:  # noqa: BLE001
                logger.exception("bad disagg config update")
                # a poison value that keeps getting re-emitted (e.g. a
                # config controller fighting the watch) must not spin this
                # loop hot
                await asyncio.sleep(0.1)

    def prefill_remote(self, prefill_length: int, queue_size: int) -> bool:
        return (
            prefill_length > self.config.max_local_prefill_length
            and queue_size < self.config.max_prefill_queue_size
        )


class PrefillQueue:
    """Durable prefill work queue (JetStream-analog on the control-plane bus)."""

    def __init__(self, runtime: DistributedRuntime, namespace: str, component: str):
        self.runtime = runtime
        self.queue_name = f"{namespace}.{component}.prefill"

    async def enqueue(self, request: dict) -> None:
        await self.runtime.plane.bus.queue_publish(
            self.queue_name, json.dumps(request).encode()
        )

    async def dequeue(self, timeout: float | None = None) -> dict | None:
        raw = await self.runtime.plane.bus.queue_pop(self.queue_name, timeout)
        return json.loads(raw) if raw is not None else None

    async def dequeue_with_age(
        self, timeout: float | None = None
    ) -> tuple[dict, float | None] | None:
        """Dequeue plus the broker-measured queue age (None when the bus
        doesn't track enqueue times)."""
        item = await self.runtime.plane.bus.queue_pop_meta(self.queue_name, timeout)
        if item is None:
            return None
        raw, age = item
        return json.loads(raw), age

    async def size(self) -> int:
        return await self.runtime.plane.bus.queue_len(self.queue_name)


class DisaggDecodeEngine:
    """Engine wrapper on the decode worker implementing the remote-prefill
    flow; wire-compatible AsyncEngine."""

    def __init__(
        self,
        runtime: DistributedRuntime,
        engine: JaxLlmEngine,
        router: DisaggRouter,
        queue: PrefillQueue,
        *,
        transfer_host: str = "127.0.0.1",
    ):
        self.runtime = runtime
        self.engine = engine
        self.router = router
        self.queue = queue
        # seq_id -> (future, reserved landing blocks, trace).  Ownership protocol
        # (all transitions are atomic dict pops on the one event loop):
        # whoever pops the entry owns the blocks' fate — the requester
        # releases on timeout, the transfer path injects and then releases
        # iff the requester's wait was already cancelled.  This is what
        # keeps a LATE transfer from scattering stale KV into blocks that
        # were released and re-allocated to a live sequence.
        self._pending: dict[str, tuple[asyncio.Future, list[int], object]] = {}
        self.prefill_timeout_s = float(
            os.environ.get("DYN_DISAGG_PREFILL_TIMEOUT_S", "300")
        )
        self.transfer_server = KvTransferServer(self._on_transfer, host=transfer_host)
        # observability
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_prefill_timeouts = 0
        # KV-transfer observability (cumulative; per-request latency/bytes
        # also land on each trace's kv.transfer span)
        self.kv_transfer_bytes_total = 0
        self.kv_transfer_seconds_total = 0.0

    async def start(self) -> None:
        await self.transfer_server.start()

    async def stop(self) -> None:
        await self.transfer_server.stop()

    async def _on_transfer(self, payload: KvTransferPayload) -> None:
        entry = self._pending.pop(payload.seq_id, None)
        if entry is None:
            # the requester already gave up AND released the landing blocks
            # (they may belong to another sequence by now) — never inject
            logger.warning(
                "dropping late KV transfer for %s (request abandoned)",
                payload.seq_id,
            )
            return
        fut, block_ids, trace = entry
        nbytes = _payload_bytes(payload.blocks)
        span = get_recorder().start(
            "kv.transfer", trace, component="decode_worker",
            attrs={"bytes": nbytes, "blocks": len(payload.block_ids)},
        )
        t0 = time.monotonic()
        try:
            await self.engine.inject_blocks(payload.block_ids, payload.blocks)
        except Exception as exc:  # noqa: BLE001
            if span is not None:
                span.end(status="error", error=repr(exc))
            if fut.cancelled():
                self.engine.release_blocks(block_ids)
            elif not fut.done():
                fut.set_exception(exc)  # requester releases (generate())
            return
        self.kv_transfer_bytes_total += nbytes
        self.kv_transfer_seconds_total += time.monotonic() - t0
        if span is not None:
            span.end()
        if fut.cancelled():
            # requester's wait timed out between our pop and the inject
            # finishing; the blocks were still reserved (we owned them), so
            # the inject was harmless — free them now
            self.engine.release_blocks(block_ids)
        elif not fut.done():
            fut.set_result(
                (
                    payload.first_token,
                    payload.first_token_logprob,
                    payload.first_token_top_logprobs,
                )
            )

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        pre = PreprocessedRequest.from_wire(request.data)
        queue_size = await self.queue.size()
        if not self.router.prefill_remote(len(pre.token_ids), queue_size):
            self.local_prefills += 1
            return await self.engine.generate(request)

        # remote prefill: reserve the KV landing zone first
        block_ids = self.engine.reserve_blocks(len(pre.token_ids) + 1)
        if block_ids is None:
            logger.warning("no blocks free for remote prefill; falling back local")
            self.local_prefills += 1
            return await self.engine.generate(request)

        self.remote_prefills += 1
        seq_id = request.ctx.id or uuid.uuid4().hex
        trace = getattr(request.ctx, "trace", None)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[seq_id] = (fut, block_ids, trace)
        n_kv_blocks = self.engine.allocator.blocks_needed(len(pre.token_ids))
        # trace context rides the queue item (stamp_trace below) so the
        # prefill worker's span joins the same request tree
        await self.queue.enqueue(
            stamp_trace({
                "seq_id": seq_id,
                "request": request.data,
                "dst_block_ids": block_ids[:n_kv_blocks],
                "transfer_address": self.transfer_server.address,
                # staleness contract: a worker dequeuing after the requester
                # has timed out (and prefilled locally) must drop the item
                # rather than burn a prefill whose transfer would be
                # discarded.  ``ttl_s`` is a duration (skew-free); the
                # worker compares it against the queue broker's own
                # enqueue→pop age measurement.  ``deadline_ts`` is the
                # wall-clock fallback for buses without age metadata,
                # applied with a skew margin.
                "ttl_s": self.prefill_timeout_s,
                "deadline_ts": time.time() + self.prefill_timeout_s,
            }, trace)
        )
        try:
            first_token, first_lp, first_top = await asyncio.wait_for(
                fut, timeout=self.prefill_timeout_s
            )
        except (asyncio.TimeoutError, asyncio.CancelledError) as err:
            if self._pending.pop(seq_id, None) is not None:
                # we still own the landing blocks — a transfer that arrives
                # from here on finds no pending entry and is dropped
                self.engine.release_blocks(block_ids)
            # else: _on_transfer claimed the entry; it observes the
            # cancelled future and releases the blocks itself
            if isinstance(err, asyncio.CancelledError):
                raise  # caller went away; nothing to serve
            # the prefill fleet is slow/dead, but this worker still owns
            # the request and a whole engine: serve it locally (slower
            # TTFT beats a failed request — the reference's disagg also
            # degrades to aggregated serving when remote prefill is
            # unavailable)
            self.remote_prefill_timeouts += 1
            self.local_prefills += 1  # counted like the no-blocks fallback
            logger.warning(
                "remote prefill for %s timed out after %.1fs; prefilling locally",
                seq_id, self.prefill_timeout_s,
            )
            return await self.engine.generate(request)
        except Exception:
            # inject failed after the transfer claimed the entry; blocks
            # were never handed to a sequence — release here
            self._pending.pop(seq_id, None)
            self.engine.release_blocks(block_ids)
            raise
        return await self.engine.generate_prefilled(
            request, block_ids, first_token, first_token_logprob=first_lp,
            first_token_top_logprobs=first_top,
        )

    def stats(self) -> dict:
        stats = self.engine.stats()
        stats["remote_prefills"] = self.remote_prefills
        stats["local_prefills"] = self.local_prefills
        stats["remote_prefill_timeouts"] = self.remote_prefill_timeouts
        stats["kv_transfer_bytes_total"] = self.kv_transfer_bytes_total
        stats["kv_transfer_seconds_total"] = self.kv_transfer_seconds_total
        return stats


class PrefillWorker:
    """Prefill-side pump: dequeue → prefill → ship KV → (decode worker
    continues).  One pump per prefill engine instance."""

    def __init__(self, runtime: DistributedRuntime, engine: JaxLlmEngine, queue: PrefillQueue):
        self.runtime = runtime
        self.engine = engine
        self.queue = queue
        self.client = KvTransferClient()
        self._task: asyncio.Task | None = None
        self.prefills_done = 0
        self.stale_dropped = 0
        # tolerated cross-host clock disagreement: a dequeued item is only
        # dropped as stale once it is past its TTL by MORE than this margin,
        # so a skewed requester clock degrades to the occasional wasted
        # prefill instead of silently dropping all disagg traffic
        self.clock_skew_margin_s = float(
            os.environ.get("DYN_DISAGG_CLOCK_SKEW_S", "30")
        )

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        await self.client.close()

    async def _loop(self) -> None:
        while True:
            try:
                # chaos seam: a failed dequeue exercises the sleep-and-retry
                # path below (the pump must survive broker churn)
                FAULTS.check(PREFILL_DEQUEUE)
                popped = await self.queue.dequeue_with_age(timeout=1.0)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("prefill queue pop failed")
                await asyncio.sleep(0.5)
                continue
            if popped is None:
                continue
            item, age = popped
            try:
                await self._handle(item, age)
            except Exception:  # noqa: BLE001
                logger.exception("remote prefill failed for %s", item.get("seq_id"))

    def _is_stale(self, item: dict, queue_age_s: float | None) -> bool:
        """True iff the requester has certainly timed out already.

        Preferred signal: the broker-measured queue age (enqueue→pop on the
        broker's own clock) against the item's relative TTL — two durations,
        no cross-host wall-clock comparison anywhere.  Buses without age
        metadata fall back to the absolute ``deadline_ts`` with a skew
        margin, which errs toward the wasted prefill (whose transfer the
        decode side drops harmlessly) rather than toward dropping live
        traffic when clocks disagree.
        """
        ttl = item.get("ttl_s")
        if queue_age_s is not None and ttl is not None:
            return queue_age_s > ttl
        deadline = item.get("deadline_ts")
        return deadline is not None and time.time() > deadline + self.clock_skew_margin_s

    def stats(self) -> dict:
        return {
            "prefills_done": self.prefills_done,
            "stale_dropped": self.stale_dropped,
        }

    async def _handle(self, item: dict, queue_age_s: float | None = None) -> None:
        from dynamo_tpu.parallel.kv_transfer import LOCAL_SERVERS

        if self._is_stale(item, queue_age_s):
            # the requester already timed out and served itself locally; a
            # prefill now would be pure waste amplifying the overload that
            # caused the timeout (its transfer would be dropped anyway)
            self.stale_dropped += 1
            logger.warning(
                "dropping stale prefill request %s (stale_dropped=%d)",
                item.get("seq_id"), self.stale_dropped,
            )
            return
        pre = PreprocessedRequest.from_wire(item["request"])
        trace = read_trace(item)
        span = get_recorder().start(
            "prefill_worker.handle", trace, component="prefill_worker",
            attrs={"prompt_tokens": len(pre.token_ids)},
        )
        # strategy selection by destination locality (reference:
        # block/transfer/strategy.rs:345): same-process destinations keep
        # blocks on device (ICI-class copy), remote ones stage to host
        local = item["transfer_address"] in LOCAL_SERVERS
        try:
            first_token, first_lp, first_top, blocks, n = await self.engine.prefill_extract(
                pre, device=local
            )
            await self.client.send(
                item["transfer_address"],
                KvTransferPayload(
                    seq_id=item["seq_id"],
                    first_token=first_token,
                    first_token_logprob=first_lp,
                    first_token_top_logprobs=first_top,
                    block_ids=item["dst_block_ids"][:n],
                    blocks=blocks,
                ),
            )
        except BaseException as exc:
            if span is not None:
                span.end(status="error", error=repr(exc))
            raise
        if span is not None:
            span.end(bytes=_payload_bytes(blocks), blocks=n)
        self.prefills_done += 1  # actual prefills only, not dropped items
