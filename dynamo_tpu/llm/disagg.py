"""Disaggregated prefill/decode.

Decision + orchestration (reference: SURVEY.md §3.4; decision thresholds
lib/llm/src/disagg_router.rs:25-34 with etcd hot-reload :38-90; queue
examples/llm/utils/prefill_queue.py + NatsQueue):

- ``DisaggRouter``    — prefill locally vs remotely: remote iff prompt length
  exceeds ``max_local_prefill_length`` AND the prefill queue is not backed
  up; config hot-reloads from a control-plane KV key watch.
- ``PrefillQueue``    — durable work queue on the control-plane bus.
- ``DisaggDecodeEngine`` — decode-worker engine wrapper: on remote decision,
  reserves landing blocks, enqueues a RemotePrefillRequest, waits for the KV
  transfer, then decodes.  Local decision falls through to the inner engine.
- ``PrefillWorker``   — dequeues, prefills on its own engine/mesh, ships KV
  blocks to the decode worker's transfer server.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid
from dataclasses import dataclass, field

from dynamo_tpu.engine.engine import JaxLlmEngine
from dynamo_tpu.llm.protocols.common import PreprocessedRequest
from dynamo_tpu.observability import get_recorder
from dynamo_tpu.observability.trace import read_trace, stamp_trace
from dynamo_tpu.parallel.kv_transfer import (
    KvTransferClient,
    KvTransferPayload,
    KvTransferServer,
)
from dynamo_tpu.robustness import counters
from dynamo_tpu.robustness.faults import FAULTS, PREFILL_DEQUEUE
from dynamo_tpu.robustness.retry import Backoff
from dynamo_tpu.runtime.component import ROOT_PATH
from dynamo_tpu.runtime.controlplane.interface import WatchEventType
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged
from dynamo_tpu.utils import knobs

logger = get_logger("llm.disagg")


def disagg_config_key(model: str) -> str:
    return f"{ROOT_PATH}public/components/disagg_router/models/chat/{model}"


def _payload_bytes(blocks) -> int:
    """Total bytes of a KV transfer payload's cache pytree (host or device
    arrays both expose nbytes)."""
    import jax

    return int(sum(getattr(leaf, "nbytes", 0) for leaf in jax.tree.leaves(blocks)))


def kv_stream_enabled() -> bool:
    """Streamed (multi-part, overlapped-with-prefill) KV transfer knob.
    Default ON; ``DYN_KV_STREAM=0`` falls back to the single-shot
    post-prefill transfer."""
    return knobs.get("DYN_KV_STREAM")


@dataclass
class DisaggConfig:
    max_local_prefill_length: int = 512
    max_prefill_queue_size: int = 16
    # link-cost guard: skip remote prefill when the estimated KV transfer
    # (prompt blocks / measured inbound bandwidth) would take longer than
    # this — behind a slow DCN hop, local prefill beats shipping the cache.
    # 0 = guard off; unmeasured links are never gated.
    max_transfer_seconds: float = 0.0


class DisaggRouter:
    """Local-vs-remote prefill decision with KV-watched hot reload."""

    def __init__(self, runtime: DistributedRuntime, model: str, config: DisaggConfig | None = None):
        self.runtime = runtime
        self.model = model
        self.config = config or DisaggConfig()
        self._watch = None
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        self._watch = self.runtime.plane.kv.watch_prefix(disagg_config_key(self.model))
        self._task = spawn_logged(self._loop())

    async def stop(self) -> None:
        if self._watch is not None:
            self._watch.cancel()
        if self._task is not None:
            self._task.cancel()

    async def _loop(self) -> None:
        """Run the config watch; on connection loss, resubscribe with
        backoff instead of exiting permanently (pre-fix, one dropped
        control-plane connection froze the disagg thresholds forever —
        the router kept serving on the last config, but could never see
        another hot-reload)."""
        backoff = Backoff(initial=0.1, max_delay=5.0)
        while True:
            started = asyncio.get_running_loop().time()
            try:
                await self._config_loop()
                return  # watch cancelled / closed cleanly (stop())
            except ConnectionError as exc:
                # a watch that survived a while before dying is a fresh,
                # independent outage — don't let attempts accumulate over a
                # long process lifetime until every blip pays the max delay
                if asyncio.get_running_loop().time() - started > 5.0:
                    backoff.reset()
                delay = backoff.next()
                logger.warning(
                    "disagg config watch lost (keeping last config; "
                    "resubscribing in %.1fs): %s", delay, exc,
                )
                await asyncio.sleep(delay)  # stop() cancels us here
                self._watch = self.runtime.plane.kv.watch_prefix(
                    disagg_config_key(self.model)
                )

    async def _config_loop(self) -> None:
        async for event in self._watch:
            if event.type != WatchEventType.PUT:
                continue
            try:
                d = json.loads(event.entry.value)
                self.config = DisaggConfig(
                    max_local_prefill_length=d.get(
                        "max_local_prefill_length", self.config.max_local_prefill_length
                    ),
                    max_prefill_queue_size=d.get(
                        "max_prefill_queue_size", self.config.max_prefill_queue_size
                    ),
                    max_transfer_seconds=d.get(
                        "max_transfer_seconds", self.config.max_transfer_seconds
                    ),
                )
                logger.info("disagg config reloaded: %s", self.config)
            except Exception:  # noqa: BLE001
                logger.exception("bad disagg config update")
                # a poison value that keeps getting re-emitted (e.g. a
                # config controller fighting the watch) must not spin this
                # loop hot
                await asyncio.sleep(0.1)

    def prefill_remote(
        self, prefill_length: int, queue_size: int, est_transfer_s: float = 0.0
    ) -> bool:
        return (
            prefill_length > self.config.max_local_prefill_length
            and queue_size < self.config.max_prefill_queue_size
            and (
                self.config.max_transfer_seconds <= 0
                or est_transfer_s <= self.config.max_transfer_seconds
            )
        )


class PrefillQueue:
    """Durable prefill work queue (JetStream-analog on the control-plane bus)."""

    def __init__(self, runtime: DistributedRuntime, namespace: str, component: str):
        self.runtime = runtime
        self.queue_name = f"{namespace}.{component}.prefill"

    async def enqueue(self, request: dict) -> None:
        await self.runtime.plane.bus.queue_publish(
            self.queue_name, json.dumps(request).encode()
        )

    async def dequeue(self, timeout: float | None = None) -> dict | None:
        raw = await self.runtime.plane.bus.queue_pop(self.queue_name, timeout)
        return json.loads(raw) if raw is not None else None

    async def dequeue_with_age(
        self, timeout: float | None = None
    ) -> tuple[dict, float | None] | None:
        """Dequeue plus the broker-measured queue age (None when the bus
        doesn't track enqueue times)."""
        item = await self.runtime.plane.bus.queue_pop_meta(self.queue_name, timeout)
        if item is None:
            return None
        raw, age = item
        return json.loads(raw), age

    async def size(self) -> int:
        return await self.runtime.plane.bus.queue_len(self.queue_name)


@dataclass
class _StreamAssembly:
    """Decode-side state for one in-flight multi-part KV stream.

    Parts may arrive out of order (a re-dialed client connection gets its
    own server task) and duplicated (an ack lost to a reset makes the
    client re-send over a fresh connection); ``received`` makes injection
    idempotent per part and completion order-free.  Timing splits into
    ``active_seconds`` (sum of per-part receive→inject work) and the
    exposure window after the closing part lands — their difference is the
    transfer time HIDDEN behind prefill compute, the quantity streaming
    exists to maximize."""

    received: set[int] = field(default_factory=set)   # arrival dedup
    injected: set[int] = field(default_factory=set)   # scatter completed
    # landing-block offsets whose KV has fully landed — the resume cursor
    # for re-enqueueing a stream whose prefill worker died mid-flight
    covered_blocks: set[int] = field(default_factory=set)
    last_index: int | None = None
    first_token: int | None = None
    first_token_logprob: float | None = None
    first_token_top_logprobs: list | None = None
    bytes: int = 0
    blocks_received: int = 0
    active_seconds: float = 0.0
    last_part_arrival: float | None = None  # monotonic; set when ``last`` lands
    inflight: int = 0                       # parts currently inside inject_blocks
    # set when the requester abandons the stream while a part is mid-inject:
    # the landing blocks stay reserved until the last in-flight inject
    # drains, then ITS handler releases them (never free under a writer)
    abandoned_blocks: list[int] | None = None
    span: object = None

    def contiguous_blocks(self) -> int:
        """Blocks 0..n-1 all fully injected — where a resumed prefill
        stream can safely skip to (anything past a gap must be re-shipped,
        so only the contiguous prefix counts)."""
        n = 0
        while n in self.covered_blocks:
            n += 1
        return n


class DisaggDecodeEngine:
    """Engine wrapper on the decode worker implementing the remote-prefill
    flow; wire-compatible AsyncEngine."""

    def __init__(
        self,
        runtime: DistributedRuntime,
        engine: JaxLlmEngine,
        router: DisaggRouter,
        queue: PrefillQueue,
        *,
        transfer_host: str = "127.0.0.1",
    ):
        self.runtime = runtime
        self.engine = engine
        self.router = router
        self.queue = queue
        # seq_id -> (future, reserved landing blocks, trace).  Ownership protocol
        # (all transitions are atomic dict pops on the one event loop):
        # whoever pops the entry owns the blocks' fate — the requester
        # releases on timeout, the transfer path injects and then releases
        # iff the requester's wait was already cancelled.  This is what
        # keeps a LATE transfer from scattering stale KV into blocks that
        # were released and re-allocated to a live sequence.
        self._pending: dict[str, tuple[asyncio.Future, list[int], object]] = {}
        # streamed transfers: seq_id -> partial assembly.  Intermediate
        # parts inject into their own landing-block subrange WITHOUT popping
        # _pending (the requester still owns the entry); only stream
        # completion — every part 0..last injected — claims it.
        self._assembly: dict[str, _StreamAssembly] = {}
        self.prefill_timeout_s = knobs.get("DYN_DISAGG_PREFILL_TIMEOUT_S")
        self.transfer_server = KvTransferServer(self._on_transfer, host=transfer_host)
        # link characterization for the router's transfer-cost model: hop
        # class this decode worker sits behind relative to the prefill pool
        # ("local"|"ici"|"dcn"; "" = unknown → the router keeps its prior).
        # DYN_TRANSFER_HOP is an explicit OVERRIDE; unset, the hop comes from
        # the discovered topology map (attach_topology) when one is wired.
        self._hop_override = knobs.get("DYN_TRANSFER_HOP")
        self._topology = None          # TopologyMap, when attached
        self._topo_self_id: int | None = None
        self._bytes_per_block: int | None = None  # lazy, for the transfer guard
        # observability
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_prefill_timeouts = 0
        self.remote_prefill_requeues = 0
        # KV-transfer observability (cumulative; per-request latency/bytes
        # also land on each trace's kv.transfer span)
        self.kv_transfer_bytes_total = 0
        self.kv_transfer_seconds_total = 0.0
        # streamed-transfer accounting: parts injected, duplicate parts
        # dropped, and inject seconds HIDDEN behind prefill compute (a
        # single-shot transfer hides nothing — its whole inject is exposed)
        self.kv_transfer_parts_total = 0
        self.kv_transfer_duplicate_parts_total = 0
        self.kv_transfer_hidden_seconds_total = 0.0
        self.kv_transfer_streams_total = 0

    async def start(self) -> None:
        await self.transfer_server.start()

    async def stop(self) -> None:
        await self.transfer_server.stop()

    def attach_topology(self, topo_map, *, self_worker_id: int) -> None:
        """Derive this worker's inbound hop class from a discovered
        TopologyMap (consulted only while informative — a single-host
        all-local map leaves ``transfer_hop`` empty, exactly as before)."""
        self._topology = topo_map
        self._topo_self_id = self_worker_id

    @property
    def transfer_hop(self) -> str:
        if self._hop_override:
            return self._hop_override
        topo = self._topology
        if topo is not None and self._topo_self_id is not None and topo.informative():
            return topo.inbound_hop(self._topo_self_id)
        return ""

    def _release_landing(self, seq_id: str, block_ids: list[int]) -> None:
        """Release a sequence's landing blocks — DEFERRED while any streamed
        part is still inside inject_blocks (freeing under a writer would let
        the allocator hand the blocks to a live sequence mid-scatter).  The
        last in-flight part's handler performs the actual release."""
        asm = self._assembly.pop(seq_id, None)
        if asm is not None and asm.span is not None:
            asm.span.end(status="error", error="abandoned")
            asm.span = None
        if asm is not None and asm.inflight > 0:
            asm.abandoned_blocks = list(block_ids)
        else:
            self.engine.release_blocks(block_ids)

    async def _on_transfer(self, payload: KvTransferPayload) -> None:
        # legacy fast path: a one-part stream with no assembly in progress
        # is exactly the pre-streaming wire contract (atomic pop-claim)
        if payload.part_index == 0 and payload.last and payload.seq_id not in self._assembly:
            await self._on_transfer_single(payload)
            return
        await self._on_transfer_part(payload)

    async def _on_transfer_single(self, payload: KvTransferPayload) -> None:
        entry = self._pending.pop(payload.seq_id, None)
        if entry is None:
            # the requester already gave up AND released the landing blocks
            # (they may belong to another sequence by now) — never inject
            logger.warning(
                "dropping late KV transfer for %s (request abandoned)",
                payload.seq_id,
            )
            return
        fut, block_ids, trace = entry
        nbytes = _payload_bytes(payload.blocks)
        span = get_recorder().start(
            "kv.transfer", trace, component="decode_worker",
            attrs={"bytes": nbytes, "blocks": len(payload.block_ids)},
        )
        t0 = time.monotonic()
        try:
            await self.engine.inject_blocks(payload.block_ids, payload.blocks)
        except Exception as exc:  # noqa: BLE001
            if span is not None:
                span.end(status="error", error=repr(exc))
            if fut.cancelled():
                self.engine.release_blocks(block_ids)
            elif not fut.done():
                fut.set_exception(exc)  # requester releases (generate())
            return
        self.kv_transfer_bytes_total += nbytes
        self.kv_transfer_seconds_total += time.monotonic() - t0
        self.kv_transfer_parts_total += 1
        self.kv_transfer_streams_total += 1
        if span is not None:
            span.end()
        if fut.cancelled():
            # requester's wait timed out between our pop and the inject
            # finishing; the blocks were still reserved (we owned them), so
            # the inject was harmless — free them now
            self.engine.release_blocks(block_ids)
        elif not fut.done():
            fut.set_result(
                (
                    payload.first_token,
                    payload.first_token_logprob,
                    payload.first_token_top_logprobs,
                )
            )

    async def _on_transfer_part(self, payload: KvTransferPayload) -> None:
        """One part of a streamed transfer: inject its block subrange while
        the requester still owns the pending entry, complete the stream when
        every part 0..last has been injected."""
        seq_id = payload.seq_id
        entry = self._pending.get(seq_id)
        if entry is None:
            # requester gone (timeout → local fallback, or cancel): drop the
            # part and forget any partial assembly — the blocks are released
            # (or pending deferred release) elsewhere
            self._assembly.pop(seq_id, None)
            logger.warning(
                "dropping late KV transfer part %d for %s (request abandoned)",
                payload.part_index, seq_id,
            )
            return
        fut, block_ids, trace = entry
        asm = self._assembly.get(seq_id)
        if asm is None:
            asm = self._assembly[seq_id] = _StreamAssembly()
            asm.span = get_recorder().start(
                "kv.transfer", trace, component="decode_worker",
                attrs={"streamed": True},
            )
        if payload.part_index in asm.received:
            # duplicate delivery (client re-send over a re-dialed
            # connection): the blocks are already injected — drop
            self.kv_transfer_duplicate_parts_total += 1
            return
        asm.received.add(payload.part_index)
        if payload.last:
            asm.last_index = payload.part_index
            asm.first_token = payload.first_token
            asm.first_token_logprob = payload.first_token_logprob
            asm.first_token_top_logprobs = payload.first_token_top_logprobs
            asm.last_part_arrival = time.monotonic()
        nbytes = _payload_bytes(payload.blocks)
        part_span = get_recorder().start(
            "kv.transfer.part", trace, component="decode_worker",
            attrs={
                "part": payload.part_index, "bytes": nbytes,
                "blocks": len(payload.block_ids), "last": payload.last,
            },
        )
        t0 = time.monotonic()
        asm.inflight += 1
        try:
            if payload.block_ids:
                await self.engine.inject_blocks(payload.block_ids, payload.blocks)
        except Exception as exc:  # noqa: BLE001
            asm.inflight -= 1
            if part_span is not None:
                part_span.end(status="error", error=repr(exc))
            if asm.abandoned_blocks is not None:
                # requester abandoned mid-inject; we may be the last writer
                if asm.inflight == 0:
                    blocks_to_free, asm.abandoned_blocks = asm.abandoned_blocks, None
                    self.engine.release_blocks(blocks_to_free)
                return
            entry2 = self._pending.pop(seq_id, None)
            if entry2 is None:
                return  # abandonment raced us; its release path owns the blocks
            if fut.cancelled():
                # requester is gone and can't run its release path — do it
                # here through the deferral protocol (sibling parts may
                # still be scattering into these blocks)
                self._release_landing(seq_id, block_ids)
            elif not fut.done():
                # requester wakes with the exception and releases through
                # _release_landing (generate()); the assembly stays in the
                # dict until then so the deferral state survives
                fut.set_exception(exc)
            return
        asm.inflight -= 1
        asm.injected.add(payload.part_index)
        asm.covered_blocks.update(
            range(payload.block_start, payload.block_start + len(payload.block_ids))
        )
        asm.active_seconds += time.monotonic() - t0
        asm.bytes += nbytes
        asm.blocks_received += len(payload.block_ids)
        self.kv_transfer_parts_total += 1
        if part_span is not None:
            part_span.end()
        if asm.abandoned_blocks is not None:
            # requester abandoned while we were injecting: blocks stayed
            # reserved (deferred release), so the scatter was harmless —
            # the last writer out frees them
            if asm.inflight == 0:
                blocks_to_free, asm.abandoned_blocks = asm.abandoned_blocks, None
                self.engine.release_blocks(blocks_to_free)
            return
        # completion gates on INJECTED parts (a part that has merely arrived
        # may still be mid-scatter on a concurrent handler — admitting the
        # sequence then would race decode against its own KV landing)
        if asm.last_index is not None and len(asm.injected) == asm.last_index + 1:
            self._finish_stream(seq_id, asm)

    def _finish_stream(self, seq_id: str, asm: _StreamAssembly) -> None:
        """All parts injected: claim the pending entry and admit the
        sequence.  Exposure = time since the closing part arrived (the tail
        the requester actually waited on); everything before it was hidden
        behind prefill compute on the remote worker."""
        entry = self._pending.pop(seq_id, None)
        self._assembly.pop(seq_id, None)
        if entry is None:
            return  # raced an abandonment; release was handled there
        fut, block_ids, trace = entry
        now = time.monotonic()
        exposed = max(0.0, now - (asm.last_part_arrival or now))
        hidden = max(0.0, asm.active_seconds - exposed)
        self.kv_transfer_bytes_total += asm.bytes
        self.kv_transfer_seconds_total += asm.active_seconds
        self.kv_transfer_hidden_seconds_total += hidden
        self.kv_transfer_streams_total += 1
        if asm.span is not None:
            asm.span.end(
                bytes=asm.bytes, blocks=asm.blocks_received,
                parts=len(asm.received), hidden_s=round(hidden, 6),
            )
            asm.span = None
        if fut.cancelled():
            self.engine.release_blocks(block_ids)
        elif not fut.done():
            fut.set_result(
                (asm.first_token, asm.first_token_logprob, asm.first_token_top_logprobs)
            )

    def _est_transfer_seconds(self, n_tokens: int) -> float:
        """Estimated inbound KV transfer time for a prompt, from measured
        bandwidth.  Unmeasured, an informative topology map supplies the
        discovered link's bandwidth (prior or probed) so the transfer guard
        can act before the first real shipment; with neither, 0.0 — never
        gate on a guess."""
        secs = self.kv_transfer_seconds_total
        bps = self.kv_transfer_bytes_total / secs if secs > 0 else 0.0
        if bps <= 0:
            topo = self._topology
            if (
                topo is not None and self._topo_self_id is not None
                and topo.informative()
            ):
                sources = [
                    c.worker_id for c in topo.nodes.values()
                    if c.role == "prefill" and c.worker_id != self._topo_self_id
                ]
                if sources:
                    bps = max(
                        topo.pair_bandwidth(src, self._topo_self_id)
                        for src in sources
                    )
        if bps <= 0:
            return 0.0
        if self._bytes_per_block is None:
            import jax

            self._bytes_per_block = sum(
                leaf.nbytes // max(leaf.shape[1], 1)
                for leaf in jax.tree.leaves(self.engine.cache)
            )
        blocks = self.engine.allocator.blocks_needed(n_tokens)
        return blocks * self._bytes_per_block / bps

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        pre = PreprocessedRequest.from_wire(request.data)
        queue_size = await self.queue.size()
        if not self.router.prefill_remote(
            len(pre.token_ids), queue_size,
            est_transfer_s=self._est_transfer_seconds(len(pre.token_ids)),
        ):
            self.local_prefills += 1
            return await self.engine.generate(request)

        # remote prefill: reserve the KV landing zone first
        block_ids = self.engine.reserve_blocks(len(pre.token_ids) + 1)
        if block_ids is None:
            logger.warning("no blocks free for remote prefill; falling back local")
            self.local_prefills += 1
            return await self.engine.generate(request)

        self.remote_prefills += 1
        seq_id = request.ctx.id or uuid.uuid4().hex
        trace = getattr(request.ctx, "trace", None)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[seq_id] = (fut, block_ids, trace)
        n_kv_blocks = self.engine.allocator.blocks_needed(len(pre.token_ids))
        # trace context rides the queue item (stamp_trace below) so the
        # prefill worker's span joins the same request tree
        await self.queue.enqueue(
            stamp_trace({
                "seq_id": seq_id,
                "request": request.data,
                "dst_block_ids": block_ids[:n_kv_blocks],
                "transfer_address": self.transfer_server.address,
                # staleness contract: a worker dequeuing after the requester
                # has timed out (and prefilled locally) must drop the item
                # rather than burn a prefill whose transfer would be
                # discarded.  ``ttl_s`` is a duration (skew-free); the
                # worker compares it against the queue broker's own
                # enqueue→pop age measurement.  ``deadline_ts`` is the
                # wall-clock fallback for buses without age metadata,
                # applied with a skew margin.
                "ttl_s": self.prefill_timeout_s,
                "deadline_ts": time.time() + self.prefill_timeout_s,
            }, trace)
        )
        requeued = False
        while True:
            try:
                first_token, first_lp, first_top = await asyncio.wait_for(
                    fut, timeout=self.prefill_timeout_s
                )
                break
            except (asyncio.TimeoutError, asyncio.CancelledError) as err:
                # resume cursor BEFORE abandoning the assembly: the
                # contiguous prefix of landing blocks already injected is
                # work a replacement prefill worker need not re-ship
                asm = self._assembly.get(seq_id)
                skip_blocks = asm.contiguous_blocks() if asm is not None else 0
                owned = self._pending.pop(seq_id, None) is not None
                if isinstance(err, asyncio.CancelledError):
                    if owned:
                        self._release_landing(seq_id, block_ids)
                    raise  # caller went away; nothing to serve
                # requeue only when a prefill worker demonstrably picked the
                # item up and started streaming (an assembly exists).  A dead
                # fleet leaves the original item queued — re-enqueueing would
                # duplicate it and still serve nobody; degrade to the local
                # prefill instead.
                if (
                    owned and not requeued and asm is not None
                    and knobs.get("DYN_RESUME")
                ):
                    # the prefill worker died (or stalled) mid-KV-stream:
                    # re-enqueue the REMAINING work for another prefill
                    # worker instead of burning a cold local prefill.  A
                    # fresh sub-stream id quarantines the dead stream (its
                    # late parts find no pending entry and drop); the
                    # landing blocks are KEPT — already-injected KV stays
                    # valid, and the replacement skips shipping it.  Old
                    # parts still mid-inject rewrite identical deterministic
                    # KV into the same blocks, which is harmless.
                    old = self._assembly.pop(seq_id, None)
                    if old is not None and old.span is not None:
                        old.span.end(status="error", error="requeued")
                        old.span = None
                    requeued = True
                    self.remote_prefill_requeues += 1
                    counters.incr("dyn_resume_prefill_requeues_total")
                    seq_id = f"{seq_id}#r1"
                    fut = asyncio.get_running_loop().create_future()
                    self._pending[seq_id] = (fut, block_ids, trace)
                    logger.warning(
                        "remote prefill stream stalled at %d contiguous "
                        "block(s); re-enqueueing remaining work as %s",
                        skip_blocks, seq_id,
                    )
                    try:
                        await self.queue.enqueue(
                            stamp_trace({
                                "seq_id": seq_id,
                                "request": request.data,
                                "dst_block_ids": block_ids[:n_kv_blocks],
                                "skip_blocks": skip_blocks,
                                "transfer_address": self.transfer_server.address,
                                "ttl_s": self.prefill_timeout_s,
                                "deadline_ts": time.time() + self.prefill_timeout_s,
                            }, trace)
                        )
                        continue
                    except Exception:  # noqa: BLE001 — queue down: go local
                        self._pending.pop(seq_id, None)
                        self._release_landing(seq_id, block_ids)
                elif owned:
                    # we still own the landing blocks — a transfer that
                    # arrives from here on finds no pending entry and is
                    # dropped.  (_release_landing defers the actual free
                    # while a streamed part is mid-inject into these blocks)
                    self._release_landing(seq_id, block_ids)
                # else: _on_transfer claimed the entry; it observes the
                # cancelled future and releases the blocks itself
                # the prefill fleet is slow/dead, but this worker still owns
                # the request and a whole engine: serve it locally (slower
                # TTFT beats a failed request — the reference's disagg also
                # degrades to aggregated serving when remote prefill is
                # unavailable)
                self.remote_prefill_timeouts += 1
                self.local_prefills += 1  # counted like the no-blocks fallback
                logger.warning(
                    "remote prefill for %s timed out after %.1fs; prefilling locally",
                    seq_id, self.prefill_timeout_s,
                )
                return await self.engine.generate(request)
            except Exception:
                # inject failed after the transfer claimed the entry; blocks
                # were never handed to a sequence — release here (deferred if
                # a sibling streamed part is still scattering into them)
                self._pending.pop(seq_id, None)
                self._release_landing(seq_id, block_ids)
                raise
        return await self.engine.generate_prefilled(
            request, block_ids, first_token, first_token_logprob=first_lp,
            first_token_top_logprobs=first_top,
        )

    def stats(self) -> dict:
        stats = self.engine.stats()
        stats["remote_prefills"] = self.remote_prefills
        stats["local_prefills"] = self.local_prefills
        stats["remote_prefill_timeouts"] = self.remote_prefill_timeouts
        stats["remote_prefill_requeues"] = self.remote_prefill_requeues
        stats["kv_transfer_bytes_total"] = self.kv_transfer_bytes_total
        stats["kv_transfer_seconds_total"] = self.kv_transfer_seconds_total
        # canonical dyn_disagg_* names (ForwardPassMetrics → metrics service)
        stats["disagg_remote_prefills_total"] = self.remote_prefills
        stats["disagg_local_prefills_total"] = self.local_prefills
        stats["disagg_prefill_timeouts_total"] = self.remote_prefill_timeouts
        stats["disagg_prefill_requeues_total"] = self.remote_prefill_requeues
        stats["disagg_kv_transfer_bytes_total"] = self.kv_transfer_bytes_total
        stats["disagg_kv_transfer_seconds_total"] = self.kv_transfer_seconds_total
        stats["disagg_kv_transfer_parts_total"] = self.kv_transfer_parts_total
        stats["disagg_kv_transfer_hidden_seconds_total"] = (
            self.kv_transfer_hidden_seconds_total
        )
        secs = self.kv_transfer_seconds_total
        stats["disagg_transfer_hidden_ratio"] = (
            self.kv_transfer_hidden_seconds_total / secs if secs > 0 else 0.0
        )
        # link characterization for the router's transfer-cost model:
        # measured inbound bandwidth (bytes over decode-side inject-active
        # seconds — a conservative floor for the link) + configured hop class
        stats["transfer_hop"] = self.transfer_hop
        stats["kv_transfer_bandwidth_bps"] = (
            self.kv_transfer_bytes_total / secs if secs > 0 else 0.0
        )
        return stats


class PrefillWorker:
    """Prefill-side pump: dequeue → prefill → ship KV → (decode worker
    continues).  One pump per prefill engine instance."""

    def __init__(
        self, runtime: DistributedRuntime, engine: JaxLlmEngine,
        queue: PrefillQueue, *, stream: bool | None = None,
    ):
        self.runtime = runtime
        self.engine = engine
        self.queue = queue
        self.client = KvTransferClient()
        self._task: asyncio.Task | None = None
        self._prober = None  # TopologyProber, when a map is attached
        self.prefills_done = 0
        self.stale_dropped = 0
        # streamed multi-part transfer: ship completed chunks while later
        # chunks compute.  None = DYN_KV_STREAM env gate; effective only
        # when the engine actually chunks prefill (otherwise there is one
        # chunk and the send degenerates to the single-part wire format).
        self.stream = kv_stream_enabled() if stream is None else stream
        self.kv_parts_sent_total = 0
        # tolerated cross-host clock disagreement: a dequeued item is only
        # dropped as stale once it is past its TTL by MORE than this margin,
        # so a skewed requester clock degrades to the occasional wasted
        # prefill instead of silently dropping all disagg traffic
        self.clock_skew_margin_s = knobs.get("DYN_DISAGG_CLOCK_SKEW_S")

    def attach_topology(self, topo_map, *, self_worker_id: int) -> None:
        """Run the bounded topology prober off this pump's own transfer
        client: active RTT/bandwidth probes of decode peers plus the
        client's passive per-destination send EWMAs (every real transfer
        is a measurement) fold into the attached TopologyMap."""
        from dynamo_tpu.topology import TopologyProber

        self._prober = TopologyProber(
            topo_map, self_worker_id=self_worker_id, client=self.client
        )
        if self._task is not None:
            spawn_logged(self._prober.start(), name="topology-prober-start")

    def start(self) -> None:
        if self._task is None:
            self._task = spawn_logged(self._loop())
            if self._prober is not None:
                spawn_logged(self._prober.start(), name="topology-prober-start")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._prober is not None:
            await self._prober.stop()
            self._prober = None
        await self.client.close()

    async def _loop(self) -> None:
        while True:
            try:
                # chaos seam: a failed dequeue exercises the sleep-and-retry
                # path below (the pump must survive broker churn)
                FAULTS.check(PREFILL_DEQUEUE)
                popped = await self.queue.dequeue_with_age(timeout=1.0)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("prefill queue pop failed")
                await asyncio.sleep(0.5)
                continue
            if popped is None:
                continue
            item, age = popped
            try:
                await self._handle(item, age)
            except Exception:  # noqa: BLE001
                logger.exception("remote prefill failed for %s", item.get("seq_id"))

    def _is_stale(self, item: dict, queue_age_s: float | None) -> bool:
        """True iff the requester has certainly timed out already.

        Preferred signal: the broker-measured queue age (enqueue→pop on the
        broker's own clock) against the item's relative TTL — two durations,
        no cross-host wall-clock comparison anywhere.  Buses without age
        metadata fall back to the absolute ``deadline_ts`` with a skew
        margin, which errs toward the wasted prefill (whose transfer the
        decode side drops harmlessly) rather than toward dropping live
        traffic when clocks disagree.
        """
        ttl = item.get("ttl_s")
        if queue_age_s is not None and ttl is not None:
            return queue_age_s > ttl
        deadline = item.get("deadline_ts")
        return deadline is not None and time.time() > deadline + self.clock_skew_margin_s

    def stats(self) -> dict:
        return {
            "prefills_done": self.prefills_done,
            "stale_dropped": self.stale_dropped,
            "kv_parts_sent_total": self.kv_parts_sent_total,
        }

    async def _handle(self, item: dict, queue_age_s: float | None = None) -> None:
        from dynamo_tpu.parallel.kv_transfer import LOCAL_SERVERS

        if self._is_stale(item, queue_age_s):
            # the requester already timed out and served itself locally; a
            # prefill now would be pure waste amplifying the overload that
            # caused the timeout (its transfer would be dropped anyway)
            self.stale_dropped += 1
            logger.warning(
                "dropping stale prefill request %s (stale_dropped=%d)",
                item.get("seq_id"), self.stale_dropped,
            )
            return
        pre = PreprocessedRequest.from_wire(item["request"])
        trace = read_trace(item)
        span = get_recorder().start(
            "prefill_worker.handle", trace, component="prefill_worker",
            attrs={"prompt_tokens": len(pre.token_ids)},
        )
        # strategy selection by destination locality (reference:
        # block/transfer/strategy.rs:345): same-process destinations keep
        # blocks on device (ICI-class copy), remote ones stage to host
        local = item["transfer_address"] in LOCAL_SERVERS
        address = item["transfer_address"]
        dst_ids = item["dst_block_ids"]
        # resumed stream (decode side re-enqueued after its first prefill
        # worker died mid-KV-stream): blocks below ``skip`` already landed —
        # compute everything (later chunks need the full KV context) but
        # don't re-ship chunks that land entirely inside the skipped prefix.
        # A chunk straddling the boundary ships whole: re-writing identical
        # deterministic KV is harmless, a hole is not.
        skip = int(item.get("skip_blocks", 0) or 0)
        # streamed transfer needs chunked prefill to have anything to
        # overlap; without it the single-part send below is the whole story
        streaming = self.stream and getattr(self.engine, "chunk_tokens", None) is not None
        loop = asyncio.get_running_loop()
        part_tasks: list[asyncio.Task] = []
        parts_sent = 0
        streamed_blocks = 0
        bytes_sent = 0

        def ship_part(payload: KvTransferPayload) -> None:
            part_tasks.append(asyncio.ensure_future(self.client.send(address, payload)))

        def on_chunk(start_b: int, leaves: dict, count: int) -> None:
            # DEVICE thread: build the part payload and hand the send to the
            # event loop.  call_soon_threadsafe is FIFO, so every part send
            # is scheduled before prefill_extract's own resolve callback —
            # the closing part below can never overtake an intermediate one
            # into the task list.
            nonlocal parts_sent, streamed_blocks, bytes_sent
            streamed_blocks = start_b + count
            if start_b + count <= skip:
                return  # decode side already holds these blocks (resume)
            payload = KvTransferPayload(
                seq_id=item["seq_id"],
                first_token=-1,  # only the closing part samples
                block_ids=list(dst_ids[start_b : start_b + count]),
                blocks=leaves,
                part_index=parts_sent,
                last=False,
                block_start=start_b,
            )
            parts_sent += 1
            bytes_sent += _payload_bytes(leaves)
            loop.call_soon_threadsafe(ship_part, payload)

        try:
            first_token, first_lp, first_top, blocks, n = await self.engine.prefill_extract(
                pre, device=local, on_chunk=on_chunk if streaming else None
            )
            # intermediate parts must have landed (or failed loudly) before
            # the closing part marks the stream complete — a lost part with
            # a delivered closing part would leave the decode side waiting
            # on an index that never comes
            if part_tasks:
                await asyncio.gather(*part_tasks)
            tail_start = min(streamed_blocks, n)
            bytes_sent += _payload_bytes(blocks)
            await self.client.send(
                address,
                KvTransferPayload(
                    seq_id=item["seq_id"],
                    first_token=first_token,
                    first_token_logprob=first_lp,
                    first_token_top_logprobs=first_top,
                    block_ids=list(dst_ids[tail_start:n]),
                    blocks=blocks,
                    part_index=parts_sent,
                    last=True,
                    block_start=tail_start,
                ),
            )
        except BaseException as exc:
            for t in part_tasks:
                t.cancel()
            if part_tasks:
                # retrieve outcomes so failed sends don't log as unawaited
                await asyncio.gather(*part_tasks, return_exceptions=True)
            if span is not None:
                span.end(status="error", error=repr(exc))
            raise
        self.kv_parts_sent_total += parts_sent + 1
        if span is not None:
            span.end(bytes=bytes_sent, blocks=n, parts=parts_sent + 1)
        self.prefills_done += 1  # actual prefills only, not dropped items
