"""Request template: server-side defaults applied to incoming OpenAI
requests (reference: lib/llm/src/request_template.rs — default model /
temperature / max tokens from a JSON file)."""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path


@dataclass
class RequestTemplate:
    model: str | None = None
    temperature: float | None = None
    max_completion_tokens: int | None = None

    @classmethod
    def load(cls, path: str | Path) -> "RequestTemplate":
        d = json.loads(Path(path).read_text())
        return cls(
            model=d.get("model"),
            temperature=d.get("temperature"),
            max_completion_tokens=d.get("max_completion_tokens") or d.get("max_tokens"),
        )

    def apply(self, body: dict) -> dict:
        """Fill missing fields in a raw request body (never overrides)."""
        if self.model and not body.get("model"):
            body["model"] = self.model
        if self.temperature is not None and body.get("temperature") is None:
            body["temperature"] = self.temperature
        if self.max_completion_tokens is not None and not (
            body.get("max_tokens") or body.get("max_completion_tokens")
        ):
            body["max_completion_tokens"] = self.max_completion_tokens
        return body
