"""dynamo_tpu.llm — the LLM domain library.

OpenAI-compatible protocol types + HTTP frontend, preprocessing (chat
templates, tokenization), detokenizing backend, model cards and discovery,
KV-aware routing, disaggregation, and the KV block manager.
(Reference: the ``dynamo-llm`` crate, lib/llm/.)
"""
