"""KvRouter: the routing decision plane.

Subscribes the component's KV-event and load-metrics subjects, feeds the
radix indexer and scheduler, and picks a worker per request (reference:
lib/llm/src/kv_router.rs:104 KvRouter, :220 KvPushRouter).
"""

from __future__ import annotations

import asyncio
from dynamo_tpu.llm.kv_router.cost import TransferCostModel
from dynamo_tpu.llm.kv_router.hashing import compute_block_hashes
from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.llm.kv_router.protocols import (
    KV_EVENT_SUBJECT,
    KV_HIT_RATE_SUBJECT,
    LOAD_METRICS_SUBJECT,
    ForwardPassMetrics,
    KvHitRateEvent,
    RouterEvent,
)
from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig, KvScheduler
from dynamo_tpu.observability import get_recorder
from dynamo_tpu.runtime.client import InstanceNotFound, PushRouter
from dynamo_tpu.runtime.component import Component
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("llm.kv_router")


class KvRouter:
    """Indexer + scheduler fed by bus subscriptions."""

    def __init__(
        self,
        component: Component,
        *,
        block_size: int = 16,
        config: KvRouterConfig | None = None,
        enable_prefetch: bool | None = None,
    ):
        self.component = component
        self.block_size = block_size
        self.indexer = KvIndexer()
        self.scheduler = KvScheduler(config)
        # KV-locality/link-cost selection: fed link fields from the workers'
        # load metrics (transfer_hop + measured inbound bandwidth); until any
        # link is characterized, scheduling stays overlap/load-only
        self.cost_model = TransferCostModel()
        self.topology = None  # TopologyMap, via attach_topology()
        self._subs = []
        self._tasks: list[asyncio.Task] = []
        # predictive prefetch (prefetch/forwarder.py): hints forwarded to
        # the worker whose radix index holds the offloaded prefix, plus
        # session next-turn prediction.  None = DYN_PREFETCH env gate.
        from dynamo_tpu.prefetch.hints import prefetch_enabled

        if enable_prefetch is None:
            enable_prefetch = prefetch_enabled()
        self.prefetch_forwarder = None
        if enable_prefetch:
            from dynamo_tpu.prefetch.forwarder import PrefetchForwarder

            self.prefetch_forwarder = PrefetchForwarder(component, self.indexer)

    async def start(self) -> None:
        bus = self.component.runtime.plane.bus
        self.indexer.start()
        kv_sub = await bus.subscribe(self.component.event_subject(KV_EVENT_SUBJECT))
        load_sub = await bus.subscribe(self.component.event_subject(LOAD_METRICS_SUBJECT))
        self._subs = [kv_sub, load_sub]
        self._tasks = [
            spawn_logged(self._kv_loop(kv_sub)),
            spawn_logged(self._load_loop(load_sub)),
        ]
        if self.prefetch_forwarder is not None:
            await self.prefetch_forwarder.start()

    async def stop(self) -> None:
        if self.prefetch_forwarder is not None:
            await self.prefetch_forwarder.stop()
        for sub in self._subs:
            await sub.unsubscribe()
        for task in self._tasks:
            task.cancel()
        await self.indexer.stop()

    async def _kv_loop(self, sub) -> None:
        async for msg in sub:
            try:
                self.indexer.push(RouterEvent.from_json(msg.payload))
            except Exception:  # noqa: BLE001
                logger.exception("bad kv event")

    async def _load_loop(self, sub) -> None:
        async for msg in sub:
            try:
                metrics = ForwardPassMetrics.from_json(msg.payload)
                self.scheduler.update_metrics(metrics)
                self.cost_model.update_from_metrics(metrics)
            except Exception:  # noqa: BLE001
                logger.exception("bad load metrics")

    def attach_topology(self, topo_map) -> None:
        """Let the cost model resolve unknown links from the discovered
        fleet TopologyMap (no-op for selection until the map is
        informative — an all-local map changes nothing)."""
        self.topology = topo_map
        self.cost_model.attach_topology(topo_map)

    def remove_worker(self, worker_id: int) -> None:
        self.indexer.remove_worker(worker_id)
        self.scheduler.remove_worker(worker_id)
        self.cost_model.remove_worker(worker_id)

    async def schedule(self, token_ids: list[int], worker_ids: list[int]) -> tuple[int, int]:
        """Pick a worker for a tokenized request.  Returns
        (worker_id, matched_prefix_blocks)."""
        hashes = compute_block_hashes(token_ids, self.block_size)
        overlap = self.indexer.find_matches(hashes)
        costs = None
        if self.cost_model.known():
            # a candidate's transfer bill is the prefix blocks it does NOT
            # already hold, priced by its link (hop prior or measured bps)
            missing = {
                wid: len(hashes) - overlap.scores.get(wid, 0)
                for wid in worker_ids
            }
            costs = self.cost_model.costs(worker_ids, missing)
        worker_id, ratio = self.scheduler.select_worker(
            worker_ids, overlap, len(hashes), transfer_costs=costs
        )
        matched = overlap.scores.get(worker_id, 0)
        # hit-rate observability event (best-effort)
        try:
            await self.component.runtime.plane.bus.publish(
                self.component.event_subject(KV_HIT_RATE_SUBJECT),
                KvHitRateEvent(
                    worker_id=worker_id, isl_blocks=len(hashes), overlap_blocks=matched
                ).to_json(),
            )
        except Exception:  # noqa: BLE001
            pass
        return worker_id, matched


class KvPushRouter:
    """AsyncEngine facade: schedules KV-aware, then dispatches direct to the
    chosen instance through a PushRouter (wire-dict PreprocessedRequests)."""

    def __init__(self, push_router: PushRouter, kv_router: KvRouter):
        self.push_router = push_router
        self.kv_router = kv_router

    def _candidates(self, tried: set[int]) -> list[int]:
        """Schedulable workers under PushRouter's shared routing policy
        (exclusion hard, quarantine soft): a dead worker stays in the
        instance view until its lease is reaped and would win tie-breaks
        again, costing every affine request a connect timeout."""
        return self.push_router.healthy_ids(tried)

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        token_ids = request.data.get("token_ids", [])
        # re-schedule-excluding-failed failover: direct dispatch disables
        # PushRouter's own re-pick (affinity must stay with the scheduler),
        # so a silently-dead worker — lease not yet reaped, subject dark —
        # is excluded here and the scheduler picks the next-best cache fit
        tried: set[int] = set()
        last_err: Exception | None = None
        while True:
            worker_ids = self._candidates(tried)
            if not worker_ids:
                raise last_err or RuntimeError(
                    "no instances available for kv-routed dispatch"
                )
            # routing-decision span: which worker, how much prefix it holds
            span = get_recorder().start(
                "router.schedule", getattr(request.ctx, "trace", None),
                component="router", attrs={"candidates": len(worker_ids)},
            )
            try:
                worker_id, matched = await self.kv_router.schedule(token_ids, worker_ids)
            except BaseException as exc:
                if span is not None:
                    span.end(status="error", error=repr(exc))
                raise
            if span is not None:
                span.end(worker=f"{worker_id:x}", overlap_blocks=matched)
            request.data["estimated_prefix_hit_blocks"] = matched
            try:
                return await self.push_router.generate(request, instance_id=worker_id)
            # InstanceNotFound: the worker deregistered between the
            # instance_ids snapshot and dispatch — same remedy as a dark
            # worker (which PushRouter already quarantined): reschedule.
            # Deliberately NOT a broad RuntimeError — a systemic plane
            # failure must surface, not darken the whole fleet worker by
            # worker.
            except (TimeoutError, InstanceNotFound) as err:
                tried.add(worker_id)
                last_err = err
                # drop the worker's blocks/load from the router state so
                # FOLLOWING requests don't also pay the timeout to discover
                # it (self-healing: a live worker's next KV event / metrics
                # publish re-adds it)
                self.kv_router.remove_worker(worker_id)
                logger.warning(
                    "kv-routed worker %x dark (%s); rescheduling", worker_id, err
                )
