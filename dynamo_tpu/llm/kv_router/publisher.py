"""Worker-side publishers: KV events + load metrics.

(Reference: lib/llm/src/kv_router/publisher.rs — there, events arrive from
vLLM over ZMQ; here the native engine calls straight into the publisher.)

Subjects are component-scoped event subjects on the control-plane bus:
``{ns}.{component}._events.kv_events`` and ``..._events.load_metrics``.
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.engine.kv_manager import KvEvent
from dynamo_tpu.llm.kv_router.protocols import (
    KV_EVENT_SUBJECT,
    LOAD_METRICS_SUBJECT,
    ForwardPassMetrics,
    KvCacheEvent,
    RouterEvent,
)
from dynamo_tpu.runtime.component import Component
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("llm.kv_router.publisher")


class KvEventPublisher:
    """Forwards engine allocator events to the bus, attributed to a worker.

    ``sink`` (a plain callable) is handed to the engine's BlockAllocator; it
    is thread-safe (the engine's device thread produces events) by hopping
    through ``loop.call_soon_threadsafe``.
    """

    def __init__(self, component: Component, worker_id: int):
        self.component = component
        self.worker_id = worker_id
        self.subject = component.event_subject(KV_EVENT_SUBJECT)
        self._loop = asyncio.get_event_loop()
        self._queue: asyncio.Queue[RouterEvent] = asyncio.Queue()
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._loop = asyncio.get_event_loop()
        if self._task is None:
            self._task = spawn_logged(self._pump())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def sink(self, event: KvEvent) -> None:
        """Engine-facing callback (called from the device thread)."""
        router_event = RouterEvent(
            worker_id=self.worker_id,
            event=KvCacheEvent(
                kind=event.kind,
                block_hashes=list(event.block_hashes),
                parent_hash=event.parent_hash,
                token_count=event.token_count,
            ),
        )
        self._loop.call_soon_threadsafe(self._queue.put_nowait, router_event)

    async def _pump(self) -> None:
        bus = self.component.runtime.plane.bus
        while True:
            event = await self._queue.get()
            try:
                await bus.publish(self.subject, event.to_json())
            except Exception:  # noqa: BLE001
                logger.exception("failed to publish kv event")


class WorkerMetricsPublisher:
    """Periodically publishes ForwardPassMetrics from an engine's stats."""

    def __init__(self, component: Component, worker_id: int, stats_fn, *, period_s: float = 1.0):
        self.component = component
        self.worker_id = worker_id
        self.stats_fn = stats_fn
        self.period_s = period_s
        self.subject = component.event_subject(LOAD_METRICS_SUBJECT)
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        if self._task is None:
            self._task = spawn_logged(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def publish_once(self) -> None:
        metrics = ForwardPassMetrics.from_stats(self.worker_id, self.stats_fn())
        await self.component.runtime.plane.bus.publish(self.subject, metrics.to_json())

    async def _loop(self) -> None:
        while True:
            try:
                await self.publish_once()
            except Exception:  # noqa: BLE001
                logger.exception("failed to publish metrics")
            await asyncio.sleep(self.period_s)


class ClearKvListener:
    """Worker-side subscriber for the admin cache-flush broadcast (reference:
    clear_kv_blocks admin endpoint, lib/llm/src/http/service/clear_kv_blocks.rs).

    The frontend publishes on the component's ``clear_kv_blocks`` event
    subject; every worker of that component flushes its published prefix
    state (which also emits a "cleared" RouterEvent to the indexers)."""

    def __init__(self, component: Component, engine):
        from dynamo_tpu.llm.kv_router.protocols import CLEAR_KV_SUBJECT

        self.component = component
        self.engine = engine
        self.subject = component.event_subject(CLEAR_KV_SUBJECT)
        self._task: asyncio.Task | None = None
        self._sub = None

    def start(self) -> None:
        self._task = spawn_logged(self._loop())

    async def stop(self) -> None:
        if self._sub is not None:
            await self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()

    async def _loop(self) -> None:
        bus = self.component.runtime.plane.bus
        while True:
            # a transient bus failure must not silently disable flush
            # handling for the worker's lifetime: resubscribe and keep going
            try:
                self._sub = await bus.subscribe(self.subject)
                async for _msg in self._sub:
                    try:
                        await self.engine.clear_kv_blocks()
                    except Exception:  # noqa: BLE001
                        logger.exception("clear_kv_blocks failed")
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("clear_kv listener lost its subscription; retrying")
            await asyncio.sleep(1.0)
