"""KV-cache-aware routing.

The feedback loop (reference: SURVEY.md §3.3, lib/llm/src/kv_router/):
engines publish block stored/removed events + load metrics onto the bus; the
router maintains a global radix index of block hashes per worker and a load
view, and scores workers as

    logit = overlap_weight * overlap_norm
          - usage_weight * cache_usage
          - waiting_weight * waiting_norm
          - transfer_cost_weight * transfer_cost

(reference: lib/llm/src/kv_router/scheduler.rs:248-330, weights
kv_router.rs:59-82), picking the argmax with random tie-break.  The
``transfer_cost`` term is the normalized KV-transfer cost of the missing
prefix blocks over the candidate's link (cost.TransferCostModel: ICI-vs-DCN
hop class + measured bandwidth EWMA); it is zero until any worker's link
has been characterized.
"""

from dynamo_tpu.llm.kv_router.cost import LinkEstimate, TransferCostModel
from dynamo_tpu.llm.kv_router.hashing import compute_block_hashes
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RadixTree
from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    OverlapScores,
    RouterEvent,
)
from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig, KvScheduler
from dynamo_tpu.llm.kv_router.router import KvPushRouter, KvRouter

__all__ = [
    "compute_block_hashes",
    "ForwardPassMetrics",
    "KvCacheEvent",
    "KvIndexer",
    "KvPushRouter",
    "KvRouter",
    "KvRouterConfig",
    "KvScheduler",
    "LinkEstimate",
    "OverlapScores",
    "RadixTree",
    "RouterEvent",
    "TransferCostModel",
]
