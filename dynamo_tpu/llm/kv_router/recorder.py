"""JSONL event recording + replay (reference: lib/llm/src/recorder.rs:37,
kv_router/recorder.rs) — capture live RouterEvents for offline router
reconstruction and workload studies."""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Iterator

from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RadixTree
from dynamo_tpu.llm.kv_router.protocols import KvCacheEvent, RouterEvent


class KvRecorder:
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._fh = open(self.path, "a", encoding="utf-8")
        self.count = 0

    def record(self, event: RouterEvent) -> None:
        entry = {
            "ts": time.time(),
            "worker_id": event.worker_id,
            "event": {
                "kind": event.event.kind,
                "block_hashes": event.event.block_hashes,
                "parent_hash": event.event.parent_hash,
                "token_count": event.event.token_count,
            },
        }
        self._fh.write(json.dumps(entry) + "\n")
        self.count += 1

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def iter_events(path: str | Path) -> Iterator[tuple[float, RouterEvent]]:
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            yield d["ts"], RouterEvent(worker_id=d["worker_id"], event=KvCacheEvent(**d["event"]))


def replay_into_tree(path: str | Path) -> RadixTree:
    """Rebuild the radix index offline from a recording."""
    tree = RadixTree()
    for _, event in iter_events(path):
        tree.apply(event)
    return tree


async def replay_into_indexer(path: str | Path, indexer: KvIndexer) -> int:
    n = 0
    for _, event in iter_events(path):
        indexer.push(event)
        n += 1
    return n
