"""KV-aware worker selection.

Scoring (reference: lib/llm/src/kv_router/scheduler.rs:202-330, weights
lib/llm/src/kv_router.rs:59-82):

    logit = overlap_weight * (matched_blocks / request_blocks)
          - usage_weight   * cache_usage
          - waiting_weight * (waiting / total_slots)
          - transfer_cost_weight * transfer_cost        # 0 when unknown

argmax with random tie-break.  ``transfer_cost`` is the normalized
KV-transfer cost of the candidate's missing prefix blocks over its link
(kv_router/cost.TransferCostModel — NetKV-style selection); the router
passes None until any link has been characterized, leaving selection
exactly overlap/load-driven.  Load comes from ForwardPassMetrics events
pushed by workers; staleness beyond ``metrics_ttl`` zeroes a worker's load
contribution rather than excluding it (prefer availability).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, OverlapScores
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("llm.kv_router.scheduler")


@dataclass
class KvRouterConfig:
    overlap_score_weight: float = 2.0
    gpu_cache_usage_weight: float = 1.0
    waiting_requests_weight: float = 1.0
    metrics_ttl_s: float = 10.0
    # weight on the normalized estimated KV-transfer cost of the missing
    # prefix blocks over the candidate's link (ICI-vs-DCN hop class +
    # measured bandwidth); only applies when the router's cost model has
    # link information for at least one worker
    transfer_cost_weight: float = 1.0


class KvScheduler:
    def __init__(self, config: KvRouterConfig | None = None, *, rng: random.Random | None = None):
        self.config = config or KvRouterConfig()
        self._metrics: dict[int, tuple[ForwardPassMetrics, float]] = {}
        self._rng = rng or random.Random()

    # -- load view ---------------------------------------------------------
    def update_metrics(self, metrics: ForwardPassMetrics) -> None:
        self._metrics[metrics.worker_id] = (metrics, time.monotonic())

    def remove_worker(self, worker_id: int) -> None:
        self._metrics.pop(worker_id, None)

    def _load(self, worker_id: int) -> tuple[float, float]:
        """(cache_usage, waiting_norm) with staleness handling."""
        entry = self._metrics.get(worker_id)
        if entry is None:
            return 0.0, 0.0
        metrics, stamp = entry
        if time.monotonic() - stamp > self.config.metrics_ttl_s:
            return 0.0, 0.0
        waiting_norm = (
            metrics.num_requests_waiting / metrics.request_total_slots
            if metrics.request_total_slots
            else float(metrics.num_requests_waiting)
        )
        return metrics.gpu_cache_usage_perc, waiting_norm

    # -- selection ---------------------------------------------------------
    def select_worker(
        self,
        worker_ids: list[int],
        overlap: OverlapScores,
        request_blocks: int,
        transfer_costs: dict[int, float] | None = None,
    ) -> tuple[int, float]:
        """Returns (worker_id, matched_block_ratio_of_winner).

        ``transfer_costs``: normalized [0,1] per-candidate KV-transfer cost
        (TransferCostModel.costs); None or a missing key contributes 0."""
        if not worker_ids:
            raise RuntimeError("no workers available")
        cfg = self.config
        best: list[int] = []
        best_logit = float("-inf")
        denom = max(request_blocks, 1)
        for wid in worker_ids:
            overlap_norm = overlap.scores.get(wid, 0) / denom
            usage, waiting = self._load(wid)
            logit = (
                cfg.overlap_score_weight * overlap_norm
                - cfg.gpu_cache_usage_weight * usage
                - cfg.waiting_requests_weight * waiting
            )
            if transfer_costs is not None:
                logit -= cfg.transfer_cost_weight * transfer_costs.get(wid, 0.0)
            if logit > best_logit + 1e-12:
                best, best_logit = [wid], logit
            elif abs(logit - best_logit) <= 1e-12:
                best.append(wid)
        winner = self._rng.choice(best)
        return winner, overlap.scores.get(winner, 0) / denom
