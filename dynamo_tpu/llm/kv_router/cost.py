"""Transfer-cost model for KV-locality decode selection.

Network-aware decode-instance selection (NetKV, arxiv 2606.03910): a
candidate that already holds the request's prefix blocks needs fewer KV
bytes shipped to it, and a candidate behind an ICI-class hop receives them
far faster than one behind DCN.  The model estimates, per candidate, the
relative cost of moving the MISSING prefix blocks over its link:

    cost(w) = missing_blocks(w) * bytes_per_block / bandwidth(w)

normalized to [0, 1] across the candidate set, which the scheduler folds
into its logit with ``transfer_cost_weight``.  Bandwidth per worker is the
measured EWMA when available (KvTransferClient exchanges, or the decode
worker's own inbound accounting published via ForwardPassMetrics) and a
hop-class prior until then.
"""

from __future__ import annotations

from dataclasses import dataclass

from dynamo_tpu.utils.logging import get_logger

logger = get_logger("llm.kv_router.cost")

# hop-class bandwidth priors, bytes/second: same-chip HBM copy, ICI
# slice-neighbor, and cross-host DCN — order-of-magnitude placements whose
# RATIO is what the normalized cost consumes (measurement replaces them)
HOP_BANDWIDTH_BPS = {
    "local": 400e9,
    "ici": 100e9,
    "dcn": 10e9,
}
DEFAULT_HOP = "dcn"  # assume the worst link until told otherwise


@dataclass
class LinkEstimate:
    """What the model knows about one worker's inbound link."""

    hop: str = ""                 # "local" | "ici" | "dcn" | "" (unknown)
    measured_bps: float = 0.0     # EWMA of observed transfers; 0 = unmeasured

    def bandwidth_bps(self) -> float:
        if self.measured_bps > 0:
            return self.measured_bps
        return HOP_BANDWIDTH_BPS.get(self.hop, HOP_BANDWIDTH_BPS[DEFAULT_HOP])


class TransferCostModel:
    """Per-worker link estimates + normalized transfer-cost scoring.

    Link state layers, per candidate, cheapest-information-first:

    1. explicit per-worker estimates (`DYN_TRANSFER_HOP` override published
       through metrics, observed transfers) — exactly the pre-topology model;
    2. an attached :class:`TopologyMap` (discovery + probing), consulted only
       while it is *informative* (at least one non-local pair) — a
       single-host all-local map changes nothing;
    3. the ``DEFAULT_HOP`` worst-case prior.
    """

    def __init__(self, *, ewma_alpha: float = 0.25) -> None:
        self._links: dict[int, LinkEstimate] = {}
        self._ewma_alpha = ewma_alpha
        self._topology = None          # TopologyMap, when attached
        self._self_worker_id: int | None = None

    def attach_topology(self, topo_map, *, self_worker_id: int | None = None) -> None:
        """Resolve unknown links from a discovered TopologyMap.

        ``self_worker_id`` names the node transfers originate from (a decode
        engine scoring its own inbound link); routers scoring many
        candidates leave it unset and each candidate is priced by its best
        link from the fleet's prefill nodes.
        """
        self._topology = topo_map
        self._self_worker_id = self_worker_id

    def _topology_bandwidth(self, worker_id: int) -> float | None:
        topo = self._topology
        if topo is None or not topo.informative():
            return None
        if worker_id not in topo.nodes:
            return None
        if self._self_worker_id is not None:
            return topo.pair_bandwidth(self._self_worker_id, worker_id)
        sources = [
            c.worker_id for c in topo.nodes.values()
            if c.role == "prefill" and c.worker_id != worker_id
        ] or [wid for wid in topo.nodes if wid != worker_id]
        if not sources:
            return None
        # a candidate is as near as its best prefill source
        return max(topo.pair_bandwidth(src, worker_id) for src in sources)

    # -- link state --------------------------------------------------------
    def update_link(
        self, worker_id: int, *, hop: str | None = None,
        bandwidth_bps: float | None = None,
    ) -> None:
        link = self._links.setdefault(worker_id, LinkEstimate())
        if hop:
            link.hop = hop
        if bandwidth_bps is not None and bandwidth_bps > 0:
            # already-smoothed source (a worker's cumulative mean): set
            link.measured_bps = bandwidth_bps

    def observe_transfer(self, worker_id: int, nbytes: int, seconds: float) -> None:
        """Fold one raw transfer observation into the worker's EWMA."""
        if nbytes <= 0 or seconds <= 0:
            return
        link = self._links.setdefault(worker_id, LinkEstimate())
        bps = nbytes / seconds
        link.measured_bps = (
            bps if link.measured_bps <= 0
            else link.measured_bps + self._ewma_alpha * (bps - link.measured_bps)
        )

    def update_from_metrics(self, metrics) -> None:
        """Ingest a ForwardPassMetrics load snapshot's link fields."""
        hop = getattr(metrics, "transfer_hop", "") or None
        bps = getattr(metrics, "kv_transfer_bandwidth_bps", 0.0)
        if hop or bps > 0:
            self.update_link(
                metrics.worker_id, hop=hop,
                bandwidth_bps=bps if bps > 0 else None,
            )

    def remove_worker(self, worker_id: int) -> None:
        self._links.pop(worker_id, None)

    def known(self) -> bool:
        """True once ANY worker has link information — before that, costs
        would be uniform noise and selection stays overlap/load-only.  An
        attached topology map counts only while informative: an all-local
        map leaves selection exactly overlap/load-only."""
        if any(link.hop or link.measured_bps > 0 for link in self._links.values()):
            return True
        return self._topology is not None and self._topology.informative()

    def bandwidth_bps(self, worker_id: int) -> float:
        link = self._links.get(worker_id)
        if link is not None and (link.hop or link.measured_bps > 0):
            return link.bandwidth_bps()
        topo_bps = self._topology_bandwidth(worker_id)
        if topo_bps is not None:
            return topo_bps
        return HOP_BANDWIDTH_BPS[DEFAULT_HOP]

    def estimate_seconds(self, worker_id: int, transfer_bytes: int) -> float:
        return transfer_bytes / self.bandwidth_bps(worker_id)

    # -- scoring -----------------------------------------------------------
    def costs(
        self, worker_ids: list[int], missing_blocks: dict[int, int],
        *, bytes_per_block: float = 1.0,
    ) -> dict[int, float]:
        """Normalized [0, 1] relative transfer cost per candidate (0 =
        cheapest possible, 1 = the worst candidate in this set)."""
        bpb = bytes_per_block if bytes_per_block > 0 else 1.0
        raw = {
            wid: missing_blocks.get(wid, 0) * bpb / self.bandwidth_bps(wid)
            for wid in worker_ids
        }
        worst = max(raw.values(), default=0.0)
        if worst <= 0:
            return {wid: 0.0 for wid in worker_ids}
        return {wid: v / worst for wid, v in raw.items()}
