"""Global KV prefix index.

A radix tree over chained block hashes, tracking which workers hold which
cached prefixes (reference: lib/llm/src/kv_router/indexer.rs:187 RadixTree,
:518 KvIndexer).  Because hashes chain their parents, each node is uniquely
addressed by its block hash; matching walks the request's hash sequence until
the first miss and counts per-worker holdings.

The indexer applies events from a single consumer task — same
single-writer-by-construction design as the reference's event loop
(indexer.rs:36-44).  A C++ twin (csrc/radix_index.cpp) accelerates
find_matches for large trees; this Python implementation is the always-
available fallback and the behavioral spec.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from dynamo_tpu.llm.kv_router.protocols import OverlapScores, RouterEvent
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("llm.kv_router.indexer")


@dataclass
class _Node:
    block_hash: int
    parent: int | None = None
    children: set[int] = field(default_factory=set)
    workers: set[int] = field(default_factory=set)


class RadixTree:
    def __init__(self) -> None:
        self._nodes: dict[int, _Node] = {}
        self._worker_blocks: dict[int, set[int]] = {}

    # -- event application -------------------------------------------------
    def apply(self, event: RouterEvent) -> None:
        kv = event.event
        if kv.kind == "stored":
            parent = kv.parent_hash
            for h in kv.block_hashes:
                node = self._nodes.get(h)
                if node is None:
                    node = _Node(block_hash=h, parent=parent)
                    self._nodes[h] = node
                    if parent is not None and parent in self._nodes:
                        self._nodes[parent].children.add(h)
                node.workers.add(event.worker_id)
                self._worker_blocks.setdefault(event.worker_id, set()).add(h)
                parent = h
        elif kv.kind == "removed":
            for h in kv.block_hashes:
                self._remove_worker_block(event.worker_id, h)
        elif kv.kind == "cleared":
            self.remove_worker(event.worker_id)

    def _remove_worker_block(self, worker_id: int, block_hash: int) -> None:
        node = self._nodes.get(block_hash)
        if node is None:
            return
        node.workers.discard(worker_id)
        blocks = self._worker_blocks.get(worker_id)
        if blocks is not None:
            blocks.discard(block_hash)
        if not node.workers and not node.children:
            self._prune(block_hash)

    def _prune(self, block_hash: int) -> None:
        node = self._nodes.pop(block_hash, None)
        if node is None:
            return
        if node.parent is not None:
            parent = self._nodes.get(node.parent)
            if parent is not None:
                parent.children.discard(block_hash)
                if not parent.workers and not parent.children:
                    self._prune(node.parent)

    def remove_worker(self, worker_id: int) -> None:
        for h in list(self._worker_blocks.get(worker_id, ())):
            self._remove_worker_block(worker_id, h)
        self._worker_blocks.pop(worker_id, None)

    # -- matching ----------------------------------------------------------
    def find_matches(self, block_hashes: list[int]) -> OverlapScores:
        """Walk the request's prefix hashes; count per-worker consecutive
        matches (a worker's score only grows while it still holds the
        prefix)."""
        scores: dict[int, int] = {}
        active: set[int] | None = None
        for h in block_hashes:
            node = self._nodes.get(h)
            if node is None or not node.workers:
                break
            holders = node.workers if active is None else node.workers & active
            if not holders:
                break
            for w in holders:
                scores[w] = scores.get(w, 0) + 1
            active = set(holders)
        return OverlapScores(scores=scores, total_blocks=len(block_hashes))

    # -- introspection -----------------------------------------------------
    def size(self) -> int:
        return len(self._nodes)

    def worker_block_count(self, worker_id: int) -> int:
        return len(self._worker_blocks.get(worker_id, ()))


class KvIndexer:
    """Owns a RadixTree and applies RouterEvents from a queue (single
    consumer).  ``find_matches`` is safe to call from the event loop since
    application and matching interleave cooperatively."""

    def __init__(self, *, native: bool | None = None) -> None:
        tree = None
        if native is not False:
            try:
                from dynamo_tpu.native.radix import NativeRadixTree

                tree = NativeRadixTree()
            except Exception:  # noqa: BLE001 — fall back to the Python spec
                if native is True:
                    raise
        self.tree = tree if tree is not None else RadixTree()
        self._queue: asyncio.Queue[RouterEvent | None] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self.events_applied = 0

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    async def _loop(self) -> None:
        while True:
            event = await self._queue.get()
            if event is None:
                return
            try:
                self.tree.apply(event)
                self.events_applied += 1
            except Exception:  # noqa: BLE001
                logger.exception("failed to apply router event")

    def push(self, event: RouterEvent) -> None:
        self._queue.put_nowait(event)

    async def stop(self) -> None:
        if self._task is not None:
            self._queue.put_nowait(None)
            await self._task
            self._task = None

    def find_matches(self, block_hashes: list[int]) -> OverlapScores:
        return self.tree.find_matches(block_hashes)

    def remove_worker(self, worker_id: int) -> None:
        self.tree.remove_worker(worker_id)
