"""Aggregated load view across a component's workers (reference:
lib/llm/src/kv_router/metrics_aggregator.rs, scoring.rs ProcessedEndpoints).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from dynamo_tpu.llm.kv_router.protocols import LOAD_METRICS_SUBJECT, ForwardPassMetrics
from dynamo_tpu.runtime.component import Component
from dynamo_tpu.utils.tasks import spawn_logged


@dataclass
class ProcessedEndpoints:
    """Snapshot of all known workers' load."""

    workers: dict[int, ForwardPassMetrics] = field(default_factory=dict)

    @property
    def worker_ids(self) -> list[int]:
        return list(self.workers)

    @property
    def total_active_blocks(self) -> int:
        return sum(m.kv_active_blocks for m in self.workers.values())

    @property
    def total_waiting(self) -> int:
        return sum(m.num_requests_waiting for m in self.workers.values())

    @property
    def average_cache_usage(self) -> float:
        if not self.workers:
            return 0.0
        return sum(m.gpu_cache_usage_perc for m in self.workers.values()) / len(self.workers)


class KvMetricsAggregator:
    """Subscribes a component's load_metrics events into a live snapshot."""

    def __init__(self, component: Component, *, ttl_s: float = 10.0):
        self.component = component
        self.ttl_s = ttl_s
        self._metrics: dict[int, tuple[ForwardPassMetrics, float]] = {}
        self._sub = None
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        bus = self.component.runtime.plane.bus
        self._sub = await bus.subscribe(self.component.event_subject(LOAD_METRICS_SUBJECT))
        self._task = spawn_logged(self._loop())

    async def stop(self) -> None:
        if self._sub is not None:
            await self._sub.unsubscribe()
        if self._task is not None:
            self._task.cancel()

    async def _loop(self) -> None:
        async for msg in self._sub:
            try:
                metrics = ForwardPassMetrics.from_json(msg.payload)
            except Exception:  # noqa: BLE001
                continue
            self._metrics[metrics.worker_id] = (metrics, time.monotonic())

    def snapshot(self) -> ProcessedEndpoints:
        now = time.monotonic()
        return ProcessedEndpoints(
            workers={
                wid: m
                for wid, (m, stamp) in self._metrics.items()
                if now - stamp <= self.ttl_s
            }
        )
