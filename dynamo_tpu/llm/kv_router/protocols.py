"""KV-routing wire protocols (reference: lib/llm/src/kv_router/protocols.rs)."""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field


@dataclass
class KvCacheEvent:
    """A stored/removed block event from an engine."""

    kind: str                        # "stored" | "removed" | "cleared"
    block_hashes: list[int] = field(default_factory=list)
    parent_hash: int | None = None
    token_count: int = 0

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "KvCacheEvent":
        return cls(**json.loads(data))


@dataclass
class RouterEvent:
    """A KvCacheEvent attributed to a worker instance."""

    worker_id: int
    event: KvCacheEvent

    def to_json(self) -> bytes:
        return json.dumps({"worker_id": self.worker_id, "event": asdict(self.event)}).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "RouterEvent":
        d = json.loads(data)
        return cls(worker_id=d["worker_id"], event=KvCacheEvent(**d["event"]))


@dataclass
class ForwardPassMetrics:
    """Per-engine load snapshot (reference: protocols.rs:43-59; the
    ``gpu_cache_usage_perc`` name is kept for wire parity — on TPU it is HBM
    cache usage)."""

    worker_id: int = 0
    # disagg pool membership ("prefill"/"decode", "" = serves both): lets
    # planner.sample_from_endpoints split a mixed fleet into per-pool
    # capacity/occupancy without an out-of-band role map
    role: str = ""
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    gpu_cache_usage_perc: float = 0.0
    num_requests_waiting: int = 0
    num_requests_running: int = 0
    request_total_slots: int = 0
    iterations_total: int = 0
    # engine-side reuse/speculation evidence (cumulative)
    prefix_hits_total: int = 0
    prefix_cached_tokens_total: int = 0
    spec_accepted_tokens_total: int = 0
    # step telemetry (observability.step_metrics): decode-lane occupancy of
    # the latest step and cumulative preemption count
    batch_occupancy_perc: float = 0.0
    num_preemptions_total: int = 0
    # ragged unified-batch step: mixed prefill+decode windows served by one
    # dispatch, and pipeline drains forced by new-sequence admission (the
    # sync point the unified step exists to remove — flat while unified)
    decode_windows_unified_total: int = 0
    admission_drains_total: int = 0
    # unified-batch fallbacks by reason slug ({reason: count} — why windows
    # took the split path: init-time disables like "speculative"/"mesh" and
    # per-step route checks like "guided"/"slot_oom"; empty while every
    # window rides the unified step)
    unified_fallbacks: dict = field(default_factory=dict)
    # utilization accounting (observability.perf): rolling rates + token
    # totals + wasted-work counters, and the opt-in engine phase timings
    # (DYN_ENGINE_PHASE_TIMING=1) as {phase: cumulative seconds}
    mfu_perc: float = 0.0
    bandwidth_util_perc: float = 0.0
    goodput_tokens_per_second: float = 0.0
    prefill_tokens_per_second: float = 0.0
    prefill_tokens_total: int = 0
    decode_tokens_total: int = 0
    tokens_emitted_total: int = 0
    preempted_tokens_total: int = 0
    spec_rejected_tokens_total: int = 0
    wasted_tokens_total: int = 0
    phase_seconds: dict = field(default_factory=dict)
    # predictive prefetch (prefetch/pager.py) + offload-tier occupancy
    # ({tier: {"blocks": total, "used": n, "pinned": n?}} — empty when no
    # offload tier is mounted)
    prefetch_hits_total: int = 0
    prefetch_misses_total: int = 0
    prefetch_stale_total: int = 0
    prefetch_hidden_seconds_total: float = 0.0
    prefetch_blocks_restored_total: int = 0
    prefetch_blocks_onboarded_total: int = 0
    offload_tiers: dict = field(default_factory=dict)
    # disagg streamed KV transfer (llm/disagg.DisaggDecodeEngine): decode-side
    # prefill routing outcomes + transfer totals, and the link fields the
    # router's transfer-cost model consumes (hop class + measured inbound
    # bandwidth; "" / 0.0 = uncharacterized)
    disagg_remote_prefills_total: int = 0
    disagg_local_prefills_total: int = 0
    disagg_prefill_timeouts_total: int = 0
    disagg_kv_transfer_bytes_total: int = 0
    disagg_kv_transfer_seconds_total: float = 0.0
    disagg_kv_transfer_hidden_seconds_total: float = 0.0
    disagg_kv_transfer_parts_total: int = 0
    disagg_transfer_hidden_ratio: float = 0.0
    transfer_hop: str = ""
    kv_transfer_bandwidth_bps: float = 0.0
    # perf flight recorder (observability.flight): ring bookkeeping + the
    # last dump's trigger reason ("" until something dumped)
    flight_records_total: int = 0
    flight_dropped_total: int = 0
    flight_dumps_total: int = 0
    flight_buffer_bytes: int = 0
    flight_last_dump_reason: str = ""

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "ForwardPassMetrics":
        d = json.loads(data)
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_stats(cls, worker_id: int, stats: dict) -> "ForwardPassMetrics":
        return cls(
            worker_id=worker_id,
            role=str(stats.get("role", "") or ""),
            kv_active_blocks=stats.get("kv_active_blocks", 0),
            kv_total_blocks=stats.get("kv_total_blocks", 0),
            gpu_cache_usage_perc=stats.get("gpu_cache_usage_perc", 0.0),
            num_requests_waiting=stats.get("num_requests_waiting", 0),
            num_requests_running=stats.get("num_requests_running", 0),
            request_total_slots=stats.get("request_total_slots", 0),
            iterations_total=stats.get("iterations_total", 0),
            prefix_hits_total=stats.get("prefix_hits_total", 0),
            prefix_cached_tokens_total=stats.get("prefix_cached_tokens_total", 0),
            spec_accepted_tokens_total=stats.get("spec_accepted_tokens_total", 0),
            batch_occupancy_perc=stats.get("batch_occupancy_perc", 0.0),
            num_preemptions_total=stats.get("num_preemptions_total", 0),
            decode_windows_unified_total=stats.get(
                "decode_windows_unified_total", 0
            ),
            admission_drains_total=stats.get("admission_drains_total", 0),
            unified_fallbacks={
                str(reason): int(count)
                for reason, count in (stats.get("unified_fallbacks") or {}).items()
            },
            mfu_perc=stats.get("mfu_perc", 0.0),
            bandwidth_util_perc=stats.get("bandwidth_util_perc", 0.0),
            goodput_tokens_per_second=stats.get("goodput_tokens_per_second", 0.0),
            prefill_tokens_per_second=stats.get("prefill_tokens_per_second", 0.0),
            prefill_tokens_total=stats.get("prefill_tokens_total", 0),
            decode_tokens_total=stats.get("decode_tokens_total", 0),
            tokens_emitted_total=stats.get("tokens_emitted_total", 0),
            preempted_tokens_total=stats.get("preempted_tokens_total", 0),
            spec_rejected_tokens_total=stats.get("spec_rejected_tokens_total", 0),
            wasted_tokens_total=stats.get("wasted_tokens_total", 0),
            phase_seconds={
                str(name): float(row.get("total_ms", 0.0)) / 1e3
                for name, row in (stats.get("phase_ms") or {}).items()
                if isinstance(row, dict)
            },
            prefetch_hits_total=stats.get("prefetch_hits_total", 0),
            prefetch_misses_total=stats.get("prefetch_misses_total", 0),
            prefetch_stale_total=stats.get("prefetch_stale_total", 0),
            prefetch_hidden_seconds_total=stats.get(
                "prefetch_hidden_seconds_total", 0.0
            ),
            prefetch_blocks_restored_total=stats.get(
                "prefetch_blocks_restored_total", 0
            ),
            prefetch_blocks_onboarded_total=stats.get(
                "prefetch_blocks_onboarded_total", 0
            ),
            offload_tiers={
                str(tier): row
                for tier, row in (stats.get("offload_tiers") or {}).items()
                if isinstance(row, dict)
            },
            disagg_remote_prefills_total=stats.get("disagg_remote_prefills_total", 0),
            disagg_local_prefills_total=stats.get("disagg_local_prefills_total", 0),
            disagg_prefill_timeouts_total=stats.get(
                "disagg_prefill_timeouts_total", 0
            ),
            disagg_kv_transfer_bytes_total=stats.get(
                "disagg_kv_transfer_bytes_total", 0
            ),
            disagg_kv_transfer_seconds_total=stats.get(
                "disagg_kv_transfer_seconds_total", 0.0
            ),
            disagg_kv_transfer_hidden_seconds_total=stats.get(
                "disagg_kv_transfer_hidden_seconds_total", 0.0
            ),
            disagg_kv_transfer_parts_total=stats.get(
                "disagg_kv_transfer_parts_total", 0
            ),
            disagg_transfer_hidden_ratio=stats.get(
                "disagg_transfer_hidden_ratio", 0.0
            ),
            transfer_hop=str(stats.get("transfer_hop", "") or ""),
            kv_transfer_bandwidth_bps=stats.get("kv_transfer_bandwidth_bps", 0.0),
            flight_records_total=stats.get("flight_records_total", 0),
            flight_dropped_total=stats.get("flight_dropped_total", 0),
            flight_dumps_total=stats.get("flight_dumps_total", 0),
            flight_buffer_bytes=stats.get("flight_buffer_bytes", 0),
            flight_last_dump_reason=str(
                stats.get("flight_last_dump_reason", "") or ""
            ),
        )


@dataclass
class OverlapScores:
    """find_matches result: worker → number of matched prefix blocks."""

    scores: dict[int, int] = field(default_factory=dict)
    total_blocks: int = 0


@dataclass
class KvHitRateEvent:
    """Per-request routing outcome for observability (reference:
    lib/llm/src/kv_router/scheduler.rs:32)."""

    worker_id: int
    isl_blocks: int
    overlap_blocks: int

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "KvHitRateEvent":
        return cls(**json.loads(data))


KV_EVENT_SUBJECT = "kv_events"
LOAD_METRICS_SUBJECT = "load_metrics"
CLEAR_KV_SUBJECT = "clear_kv_blocks"
KV_HIT_RATE_SUBJECT = "kv_hit_rate"
