"""Chained block-content hashing.

xxh3_64(parent_hash || token bytes) with seed 1337, matching the engine's
allocator so router index lookups line up with engine cache contents
(reference: lib/llm/src/kv_router/indexer.rs:64, compute_block_hash_for_seq
:122).
"""

from __future__ import annotations

import xxhash

HASH_SEED = 1337


def compute_block_hashes(token_ids: list[int], block_size: int) -> list[int]:
    """Hash each FULL block; each hash chains its parent, so a hash uniquely
    identifies the whole prefix ending at that block."""
    hashes: list[int] = []
    parent = 0
    full = len(token_ids) - len(token_ids) % block_size
    for start in range(0, full, block_size):
        block = token_ids[start : start + block_size]
        h = xxhash.xxh3_64(
            parent.to_bytes(8, "little")
            + b"".join(t.to_bytes(4, "little", signed=False) for t in block),
            seed=HASH_SEED,
        ).intdigest()
        hashes.append(h)
        parent = h
    return hashes
