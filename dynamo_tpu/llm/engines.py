"""Built-in trivial engines (reference: lib/llm/src/engines.rs:83-161).

- ``EchoEngineCore`` — token-level echo: streams the prompt's token ids back
  one per step.  Sits behind the full preprocessor/backend pipeline, so it
  exercises tokenization, detokenization, stop handling, SSE — everything but
  a real model.
- ``EchoEngineFull`` — text-level echo implementing the OpenAI-typed engine
  directly (no pre/post processing).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from dynamo_tpu.llm.protocols.common import (
    Annotated,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.engine import Context, ResponseStream

# matches the reference's simulated token cadence (engines.rs: token delay)
DEFAULT_TOKEN_DELAY_S = 0.0


class EchoEngineCore:
    """PreprocessedRequest wire dicts in → Annotated[LLMEngineOutput] wire out."""

    def __init__(self, token_delay_s: float = DEFAULT_TOKEN_DELAY_S):
        self.token_delay_s = token_delay_s

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        if request.data.get("image") is not None or request.data.get("video") is not None:
            # same contract as JaxLlmEngine.generate: a modality payload
            # reaching a text-only engine is a deployment without an
            # encoder, not a payload to silently drop
            raise ValueError(
                "this model deployment does not accept image/video input"
            )
        if request.data.get("output_format"):
            # echoed prompt tokens are not constrained output — reject like
            # an engine without a mask table would
            raise ValueError(
                "this model deployment does not support guided decoding"
            )
        pre = PreprocessedRequest.from_wire(request.data)
        ctx = request.ctx

        async def gen() -> AsyncIterator[dict]:
            budget = pre.stop.max_tokens or len(pre.token_ids)
            emitted = 0
            for token_id in pre.token_ids:
                if ctx.is_stopped or emitted >= budget:
                    break
                if self.token_delay_s:
                    await asyncio.sleep(self.token_delay_s)
                emitted += 1
                finish = FinishReason.LENGTH if emitted >= budget else None
                yield Annotated.from_data(
                    LLMEngineOutput(token_ids=[token_id], finish_reason=finish)
                ).to_wire(LLMEngineOutput.to_wire)
            else:
                if emitted < budget:
                    yield Annotated.from_data(
                        LLMEngineOutput(token_ids=[], finish_reason=FinishReason.STOP)
                    ).to_wire(LLMEngineOutput.to_wire)

        return ResponseStream(gen(), ctx)
