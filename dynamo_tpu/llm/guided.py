"""Guided (constrained) decoding: JSON mode.

``response_format: {"type": "json_object"}`` means every sampled token must
keep the output a prefix of some valid JSON document.  The reference stack
delegates this to its engines (vLLM guided decoding); here the engine is
native, so the constraint machinery is too — designed for the TPU execution
model:

- **All vocab-sized work happens once, off the hot path.**  A char-level
  JSON automaton is compiled against the tokenizer into a boolean mask
  table ``[num_modes, vocab]`` (``JsonTokenMasks.build``): row m = the
  tokens admissible in automaton mode m.  The table is uploaded to the
  device once.
- **Per step, the host sends one int per lane.**  The engine's decode jit
  indexes the resident table with each lane's mode id and masks logits to
  -inf before sampling (engine/engine.py); lanes with mode -1 are
  unguided.  No per-step vocab-sized host↔device traffic.
- **The host advances the real automaton between steps** (``JsonCursor``):
  it tracks the full container stack, so nesting is unbounded even though
  the mask table is finite.

Finite-mode trick: a mask row cannot depend on the unbounded stack, so
modes are (char-state × top-of-stack-container) pairs.  Tokens whose
characters would pop PAST the current container (e.g. ``"}]}``) are
conservatively masked unless everything after the pop is whitespace —
single-char structural tokens always exist in practice, so generation
never wedges; the host cursor, which knows the whole stack, then computes
the true next mode.  Same trick for strings: special tokens (``<|eos|>``
and friends) are never admissible inside a document — their markup chars
would otherwise be legal STRING content — and become admissible only in
the terminal mode, so the model can stop.

Token strings come from per-id ``decode``; byte-fallback tokens that
decode to replacement chars are masked (conservative: the bytes may split
a UTF-8 sequence across tokens, which this char-level automaton cannot
validate).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
from dynamo_tpu.utils import knobs

WS = " \t\n\r"
DIGITS = "0123456789"
HEX = "0123456789abcdefABCDEF"
# chars banned inside JSON strings (control chars); '"' and '\\' handled
_CTRL = {chr(c) for c in range(0x20)}

# (kind, extra) char-level states.  Container context is threaded
# separately; see _step_char.
_LIT_SUFFIXES = ("rue", "ue", "e", "alse", "lse", "se", "ull", "ll", "l")
_NUM_SUBS = ("sign", "zero", "int", "dot", "frac", "e", "esign", "exp")

# number sub-states from which the number is already a complete value
# (a terminator char or end-of-token is legal there)
_NUM_TERMINAL = {"zero", "int", "frac", "exp"}


class _Pop(Exception):
    """Internal signal: the char closed the current container."""


class _Bad(Exception):
    """Internal signal: the char is not admissible in this state."""


def _step_char(kind: str, extra, ch: str, top: str | None):
    """One character through the automaton.

    Returns (kind', extra', action) where action is None, ("push", c), or
    ("pop",).  ``top`` is the current innermost container ("obj" | "arr" |
    None).  Raises _Bad for inadmissible chars."""
    # -- inside strings ----------------------------------------------------
    if kind in ("str", "keystr"):
        if ch == '"':
            return (("colon", None, None) if kind == "keystr"
                    else ("after", None, None))
        if ch == "\\":
            return ("esc" if kind == "str" else "keyesc", None, None)
        if ch in _CTRL:
            raise _Bad
        return (kind, None, None)
    if kind in ("esc", "keyesc"):
        target = "str" if kind == "esc" else "keystr"
        if ch in '"\\/bfnrt':
            return (target, None, None)
        if ch == "u":
            return ("stru" if kind == "esc" else "keyu", 4, None)
        raise _Bad
    if kind in ("stru", "keyu"):
        if ch not in HEX:
            raise _Bad
        if extra == 1:
            return ("str" if kind == "stru" else "keystr", None, None)
        return (kind, extra - 1, None)

    # -- literals ----------------------------------------------------------
    if kind == "lit":
        if ch != extra[0]:
            raise _Bad
        if len(extra) == 1:
            return ("after", None, None)
        return ("lit", extra[1:], None)

    # -- numbers -----------------------------------------------------------
    if kind == "num":
        sub = extra
        if sub == "sign":
            if ch == "0":
                return ("num", "zero", None)
            if ch in DIGITS:
                return ("num", "int", None)
            raise _Bad
        if sub in ("zero", "int"):
            if sub == "int" and ch in DIGITS:
                return ("num", "int", None)
            if ch == ".":
                return ("num", "dot", None)
            if ch in "eE":
                return ("num", "e", None)
            return _end_value_char(ch, top)
        if sub == "dot":
            if ch in DIGITS:
                return ("num", "frac", None)
            raise _Bad
        if sub == "frac":
            if ch in DIGITS:
                return ("num", "frac", None)
            if ch in "eE":
                return ("num", "e", None)
            return _end_value_char(ch, top)
        if sub == "e":
            if ch in "+-":
                return ("num", "esign", None)
            if ch in DIGITS:
                return ("num", "exp", None)
            raise _Bad
        if sub == "esign":
            if ch in DIGITS:
                return ("num", "exp", None)
            raise _Bad
        if sub == "exp":
            if ch in DIGITS:
                return ("num", "exp", None)
            return _end_value_char(ch, top)

    # -- structure ---------------------------------------------------------
    # "value": expecting a value (after ':' , document start, or an array
    # comma).  "arrfirst": right after '[' — a value OR an immediate ']'
    # (empty array).  Keeping these distinct is what makes trailing commas
    # ("[1,]") inadmissible: after a comma the state is plain "value",
    # which never admits a close.
    if kind in ("value", "arrfirst"):
        if ch in WS:
            return (kind, None, None)
        if ch == "]" and kind == "arrfirst" and top == "arr":
            return ("after", None, ("pop",))
        if ch == '"':
            return ("str", None, None)
        if ch == "{":
            return ("objopen", None, ("push", "obj"))
        if ch == "[":
            return ("arrfirst", None, ("push", "arr"))
        if ch == "-":
            return ("num", "sign", None)
        if ch == "0":
            return ("num", "zero", None)
        if ch in DIGITS:
            return ("num", "int", None)
        if ch == "t":
            return ("lit", "rue", None)
        if ch == "f":
            return ("lit", "alse", None)
        if ch == "n":
            return ("lit", "ull", None)
        raise _Bad
    # "objopen": right after '{' — a key or an immediate '}' (empty
    # object).  "objkey": after an object comma — a key ONLY, so "{...,}"
    # is inadmissible.
    if kind in ("objopen", "objkey"):
        if ch in WS:
            return (kind, None, None)
        if ch == '"':
            return ("keystr", None, None)
        if ch == "}" and kind == "objopen":
            return ("after", None, ("pop",))
        raise _Bad
    if kind == "colon":
        if ch in WS:
            return ("colon", None, None)
        if ch == ":":
            return ("value", None, None)
        raise _Bad
    if kind == "after":
        return _end_value_char(ch, top)
    raise AssertionError(f"unknown state {kind!r}")


def _end_value_char(ch: str, top: str | None):
    """A char arriving right after a complete value."""
    if ch in WS:
        return ("after", None, None)
    if top == "obj":
        if ch == ",":
            return ("objkey", None, None)   # a key MUST follow (no "{a:1,}")
        if ch == "}":
            return ("after", None, ("pop",))
    elif top == "arr":
        if ch == ",":
            return ("value", None, None)    # a value MUST follow (no "[1,]")
        if ch == "]":
            return ("after", None, ("pop",))
    raise _Bad


def _modes_universe() -> list[tuple[str, object, str | None]]:
    """Every (kind, extra, top) combination a mask row may be needed for."""
    kinds: list[tuple[str, object]] = [
        ("value", None), ("arrfirst", None), ("after", None),
        ("objopen", None), ("objkey", None), ("colon", None),
        ("str", None), ("esc", None), ("keystr", None), ("keyesc", None),
    ]
    kinds += [("stru", k) for k in (1, 2, 3, 4)]
    kinds += [("keyu", k) for k in (1, 2, 3, 4)]
    kinds += [("num", s) for s in _NUM_SUBS]
    kinds += [("lit", s) for s in _LIT_SUFFIXES]
    return [(k, e, top) for k, e in kinds for top in (None, "obj", "arr")]


def _token_admissible(
    text: str, kind: str, extra, top: str | None
) -> bool:
    """Simulate a whole token's chars from (kind, extra, top).

    Pushes within the token are tracked exactly (the in-token stack is
    known); a pop beyond the in-token stack leaves the surrounding
    container unknown, after which only whitespace is admissible (the
    conservative finite-mode rule from the module docstring)."""
    if not text:
        return False
    stack: list[str] = []      # containers opened inside this token
    popped_out = False          # popped past the starting container?
    for ch in text:
        if popped_out:
            if ch in WS:
                continue
            return False
        cur_top = stack[-1] if stack else top
        try:
            kind, extra, action = _step_char(kind, extra, ch, cur_top)
        except _Bad:
            return False
        if action is not None:
            if action[0] == "push":
                stack.append(action[1])
            else:  # pop
                if stack:
                    stack.pop()
                else:
                    if top is None:
                        return False  # nothing to close
                    popped_out = True
    return True


@dataclass
class JsonTokenMasks:
    """Compiled admissible-token table for one tokenizer."""

    mask: np.ndarray                 # [num_modes, vocab] bool
    mode_index: dict[tuple, int]
    eos_allowed_modes: list[int] = field(default_factory=list)

    TERMINAL = ("after", None, None)  # document complete

    @classmethod
    def build(
        cls,
        token_strings: list[str],
        *,
        special_ids: set[int] | frozenset[int] = frozenset(),
        eos_ids: list[int] | None = None,
    ) -> "JsonTokenMasks":
        modes = _modes_universe()
        vocab = len(token_strings)
        mask = np.zeros((len(modes), vocab), bool)
        specials = set(special_ids)
        clean: list[str | None] = []
        for tid, text in enumerate(token_strings):
            if tid in specials or not text or "�" in text:
                clean.append(None)  # never admissible inside a document
            else:
                clean.append(text)
        for m, (kind, extra, top) in enumerate(modes):
            row = mask[m]
            for tid, text in enumerate(clean):
                if text is not None and _token_admissible(text, kind, extra, top):
                    row[tid] = True
        index = {mode: i for i, mode in enumerate(modes)}
        # terminal mode: whitespace continues to be admissible (handled by
        # the simulation) and EOS specials become sample-able so the model
        # can stop
        terminal = index[cls.TERMINAL]
        for eos in eos_ids or []:
            if 0 <= eos < vocab:
                mask[terminal, eos] = True
        return cls(mask=mask, mode_index=index,
                   eos_allowed_modes=[terminal])

    @classmethod
    def from_tokenizer(cls, tokenizer) -> "JsonTokenMasks":
        """Build from an HfTokenizer (llm/tokenizer.py)."""
        return build_for_tokenizer(tokenizer)[0]


def token_strings(tokenizer) -> list[str]:
    """Per-id decoded strings (the automaton's view of the vocab)."""
    return [
        tokenizer.decode([i], skip_special_tokens=False)
        for i in range(tokenizer.vocab_size)
    ]


# bump when the automaton's semantics change: stale cached tables must
# not survive an upgrade
_MASK_CACHE_VERSION = 2


def build_for_tokenizer(
    tokenizer, *, cache_dir: str | None = None
) -> tuple["JsonTokenMasks", list[str]]:
    """(masks, token_strings) for a tokenizer, with a persisted table cache.

    The table is a pure function of (vocab strings, special ids, eos ids,
    automaton version) and costs O(modes × vocab) pure-Python simulation —
    ~minutes for a 128k vocab — so it is cached on disk keyed by a content
    hash (``DYN_CACHE_DIR``, default ``~/.cache/dynamo_tpu``).  Every
    worker in a fleet after the first boot loads it in milliseconds."""
    import hashlib
    import os
    from pathlib import Path

    strings = token_strings(tokenizer)
    specials = sorted(
        i for i, s in enumerate(strings) if s and not tokenizer.decode([i])
    )
    eos_ids = list(tokenizer.eos_token_ids)

    digest = hashlib.sha256()
    digest.update(str(_MASK_CACHE_VERSION).encode())
    for s in strings:
        digest.update(s.encode())
        digest.update(b"\x00")
    digest.update(repr((specials, eos_ids)).encode())
    cache_root = Path(
        cache_dir
        or knobs.get("DYN_CACHE_DIR")
        or os.path.expanduser("~/.cache/dynamo_tpu")
    )
    cache_path = cache_root / f"json_masks_{digest.hexdigest()[:24]}.npz"
    if cache_path.exists():
        try:
            with np.load(cache_path) as data:
                mask = data["mask"]
            modes = _modes_universe()
            if mask.shape == (len(modes), len(strings)):
                masks = JsonTokenMasks(
                    mask=mask, mode_index={m: i for i, m in enumerate(modes)},
                )
                terminal = masks.mode_index[JsonTokenMasks.TERMINAL]
                masks.eos_allowed_modes = [terminal]
                return masks, strings
        except Exception:  # noqa: BLE001 — corrupt cache: rebuild below
            pass
    masks = JsonTokenMasks.build(
        strings, special_ids=set(specials), eos_ids=eos_ids
    )
    try:
        cache_root.mkdir(parents=True, exist_ok=True)
        # tmp name keeps the .npz suffix (np.savez appends it otherwise);
        # atomic rename so concurrent fleet boots never read a torn file
        tmp = cache_root / f".{cache_path.stem}.tmp.npz"
        np.savez_compressed(tmp, mask=masks.mask)
        os.replace(tmp, cache_path)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass
    return masks, strings


class JsonCursor:
    """Host-side automaton state for one guided sequence."""

    def __init__(self, masks: JsonTokenMasks, token_strings: list[str],
                 eos_ids: list[int] | None = None):
        self.masks = masks
        self._strings = token_strings
        self._eos = set(eos_ids or [])
        self.kind: str = "value"
        self.extra = None
        self.stack: list[str] = []
        self.failed = False

    @property
    def complete(self) -> bool:
        return self.kind == "after" and not self.stack and not self.failed

    @property
    def mode_id(self) -> int:
        """The mask-table row for the current state (-1 once failed: the
        engine then treats the lane as unguided rather than wedging)."""
        if self.failed:
            return -1
        top = self.stack[-1] if self.stack else None
        return self.masks.mode_index[(self.kind, self.extra, top)]

    def advance(self, token_id: int) -> None:
        """Consume one sampled token (full-stack-aware transition)."""
        if self.failed:
            return
        if token_id in self._eos:
            return  # stream end; complete-ness already reflects validity
        text = self._strings[token_id] if token_id < len(self._strings) else ""
        for ch in text:
            top = self.stack[-1] if self.stack else None
            try:
                self.kind, self.extra, action = _step_char(
                    self.kind, self.extra, ch, top
                )
            except _Bad:
                # a masked-off token can only arrive here if the caller
                # bypassed the mask (unguided fallback); record and bail
                self.failed = True
                return
            if action is not None:
                if action[0] == "push":
                    self.stack.append(action[1])
                elif self.stack:
                    self.stack.pop()
                else:
                    self.failed = True
                    return
