from dynamo_tpu.llm.http.service import HttpService, ModelManager

__all__ = ["HttpService", "ModelManager"]
