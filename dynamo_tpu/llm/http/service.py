"""OpenAI-compatible HTTP frontend (aiohttp).

Routes (reference: lib/llm/src/http/service/openai.rs, service_v2.rs):
- ``POST /v1/chat/completions``  (streaming SSE + unary)
- ``POST /v1/completions``
- ``POST /v1/embeddings``
- ``GET  /v1/models``
- ``GET  /health`` / ``GET /live``
- ``GET  /metrics``              (Prometheus)

``ModelManager`` holds per-model typed engines, added/removed dynamically by
the discovery watcher (reference: lib/llm/src/discovery/model_manager.rs).
"""

from __future__ import annotations

import asyncio
import json
import uuid
from typing import Any

from aiohttp import web

from dynamo_tpu.llm.http.metrics import FrontendMetrics
from dynamo_tpu.observability import get_recorder
from dynamo_tpu.observability.trace import sanitize_request_id
from dynamo_tpu.robustness.admission import (
    AdmissionConfig,
    AdmissionController,
    Overloaded,
)
from dynamo_tpu.llm.protocols import sse
from dynamo_tpu.llm.protocols.aggregator import (
    aggregate_chat_stream,
    aggregate_completion_stream,
)
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    EmbeddingRequest,
    ModelInfo,
    ModelList,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.utils.logging import get_logger, log_fields
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("llm.http")

REQUEST_ID_HEADER = "x-request-id"


class ModelManager:
    """Per-model engine registry, mutated live by discovery."""

    def __init__(self) -> None:
        self.chat_engines: dict[str, Any] = {}
        self.completion_engines: dict[str, Any] = {}
        self.embedding_engines: dict[str, Any] = {}

    def add_chat_model(self, name: str, engine: Any) -> None:
        self.chat_engines[name] = engine

    def add_completion_model(self, name: str, engine: Any) -> None:
        self.completion_engines[name] = engine

    def add_embedding_model(self, name: str, engine: Any) -> None:
        self.embedding_engines[name] = engine

    def remove_model(self, name: str) -> None:
        self.chat_engines.pop(name, None)
        self.completion_engines.pop(name, None)
        self.embedding_engines.pop(name, None)

    def model_names(self) -> list[str]:
        return sorted(
            set(self.chat_engines) | set(self.completion_engines) | set(self.embedding_engines)
        )


def _error(
    status: int,
    message: str,
    err_type: str = "invalid_request_error",
    *,
    param: str | None = None,
    code: str | None = None,
    headers: dict[str, str] | None = None,
) -> web.Response:
    """Structured OpenAI-shaped error body: ``{"error": {message, type,
    param, code}}`` with ``param`` naming the offending field and ``code``
    a machine-readable string (the reference returns the same typed shape,
    lib/llm/src/http/service/error.rs)."""
    return web.json_response(
        {"error": {"message": message, "type": err_type, "param": param, "code": code}},
        status=status,
        headers=headers,
    )


def _validation_error(exc: Exception) -> web.Response:
    """Pydantic ValidationError → 400 with the first violation's field as
    ``param`` (contract-tested in tests/llm/test_protocol_validation.py)."""
    try:
        first = exc.errors()[0]
        loc = [str(p) for p in first.get("loc", ()) if not isinstance(p, int)]
        # union branches show up as synthetic loc tails (e.g. "str",
        # "list[str]") — keep the leading concrete field path
        param = loc[0] if loc else None
        message = f"{'.'.join(loc) or 'request'}: {first.get('msg', 'invalid')}"
    except (AttributeError, IndexError, TypeError):
        param, message = None, f"invalid request: {exc}"
    return _error(400, message, param=param, code="invalid_value")


class HttpService:
    def __init__(
        self,
        manager: ModelManager | None = None,
        *,
        host: str = "0.0.0.0",
        port: int = 8080,
        metrics: FrontendMetrics | None = None,
        request_template=None,
        clear_kv=None,
        admission: AdmissionConfig | None = None,
        prefetch_hinter=None,
    ):
        self.manager = manager or ModelManager()
        self.host = host
        self.port = port
        self.metrics = metrics or FrontendMetrics()
        self.request_template = request_template
        # predictive prefetch (prefetch/frontend.py FrontendHinter): a hint
        # is emitted the moment a validated request enters the admission
        # path — before preprocessing/queueing/dispatch — so the target
        # worker pages the prefix up-tier during that window.  None = off.
        self.prefetch_hinter = prefetch_hinter
        # async () -> list[str]: broadcast a cache flush to every backing
        # worker component (reference: lib/llm/src/http/service/clear_kv_blocks.rs)
        self.clear_kv = clear_kv
        # load shedding on the inference routes (429/503 + Retry-After);
        # disabled unless configured or DYN_ADMISSION_MAX_INFLIGHT is set.
        # The SLO tracker's burn rate feeds it (DYN_SLO_SHED_BURN): when the
        # error budget is burning fast, shed instead of queueing deeper.
        self.admission = AdmissionController(admission)
        self.admission.burn_rate_fn = self.metrics.slo.worst_burn_rate
        self.admission.shed_burn_threshold = (
            self.metrics.slo.config.shed_burn_threshold
        )
        self.app = web.Application(
            client_max_size=64 * 1024 * 1024,
            middlewares=[self._request_id_middleware, self._admission_middleware],
        )
        self.app.router.add_post("/v1/chat/completions", self.handle_chat)
        self.app.router.add_post("/v1/completions", self.handle_completions)
        self.app.router.add_post("/v1/embeddings", self.handle_embeddings)
        self.app.router.add_get("/v1/models", self.handle_models)
        self.app.router.add_get("/health", self.handle_health)
        self.app.router.add_get("/live", self.handle_health)
        self.app.router.add_get("/metrics", self.handle_metrics)
        self.app.router.add_get("/slo", self.handle_slo)
        self.app.router.add_post("/clear_kv_blocks", self.handle_clear_kv_blocks)
        self._runner: web.AppRunner | None = None

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:  # resolve ephemeral port
            self.port = s.getsockname()[1]
            break
        logger.info("HTTP frontend on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- request identity / tracing ---------------------------------------
    @web.middleware
    async def _request_id_middleware(self, request: web.Request, handler):
        """Assign every request an id (honoring an incoming ``x-request-id``)
        and echo it on the response — including error responses.  Streaming
        responses prepare inside their handler, so ``_stream_sse`` sets the
        header itself before ``prepare()``."""
        rid = sanitize_request_id(request.headers.get(REQUEST_ID_HEADER))
        request["request_id"] = rid or uuid.uuid4().hex
        try:
            response = await handler(request)
        except web.HTTPException as exc:
            exc.headers.setdefault(REQUEST_ID_HEADER, request["request_id"])
            raise
        if not response.prepared:
            response.headers.setdefault(REQUEST_ID_HEADER, request["request_id"])
        return response

    @web.middleware
    async def _admission_middleware(self, request: web.Request, handler):
        """Admission control on the inference routes only — health, metrics
        and admin endpoints must stay reachable exactly when the service is
        overloaded."""
        if request.method != "POST" or not request.path.startswith("/v1/"):
            return await handler(request)
        try:
            await self.admission.acquire()
        except Overloaded as exc:
            return _error(
                exc.status, str(exc), "overloaded_error", code="overloaded",
                headers={"Retry-After": f"{max(int(exc.retry_after_s), 1)}"},
            )
        try:
            return await handler(request)
        finally:
            # streaming handlers return only after the SSE body is fully
            # written, so the slot covers the whole stream lifetime
            await self.admission.release()

    def _trace_root(self, request: web.Request, endpoint: str, model: str):
        """Root span of the request's trace tree; the request id IS the
        trace id, so a client-supplied ``x-request-id`` correlates client
        logs, server logs, and the exported span tree."""
        return get_recorder().start(
            "http.request", None, component="frontend",
            root_trace_id=request["request_id"],
            attrs={"endpoint": endpoint, "model": model},
        )

    def _finish_request(self, request: web.Request, root, guard) -> None:
        """Close the root span with the lifecycle facts the guard gathered
        and emit one structured per-request log record."""
        if root is not None:
            root.end(
                status=guard.status,
                ttft_s=guard.ttft_s,
                tokens_out=guard.token_count,
            )
        logger.info(
            "%s %s -> %s",
            guard.endpoint, guard.model, guard.status,
            extra=log_fields(
                request_id=request["request_id"],
                model=guard.model,
                endpoint=guard.endpoint,
                request_type=guard.request_type,
                status=guard.status,
                duration_s=round(guard.duration_s, 6),
                ttft_s=None if guard.ttft_s is None else round(guard.ttft_s, 6),
                tokens_out=guard.token_count,
            ),
        )

    # -- handlers ----------------------------------------------------------
    async def handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "models": self.manager.model_names()})

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(body=self.metrics.render(), content_type="text/plain")

    async def handle_slo(self, request: web.Request) -> web.Response:
        """SLO burn rates + histogram-bucket exemplars as JSON — the
        machine-readable twin of the ``dyn_slo_*`` exposition (consumed by
        scripts/dyn_top.py and autoscalers)."""
        return web.json_response(self.metrics.slo_status())

    async def handle_clear_kv_blocks(self, request: web.Request) -> web.Response:
        """Admin: flush every worker's published KV-cache state (reference:
        lib/llm/src/http/service/clear_kv_blocks.rs — frontend route that
        fans the flush out to all workers)."""
        if self.clear_kv is None:
            return _error(501, "clear_kv_blocks not wired on this frontend")
        try:
            cleared = await self.clear_kv()
        except Exception as exc:  # noqa: BLE001
            return _error(500, f"clear_kv_blocks failed: {exc}", "internal_error")
        return web.json_response({"status": "ok", "cleared": cleared})

    async def handle_models(self, request: web.Request) -> web.Response:
        models = ModelList(data=[ModelInfo(id=name) for name in self.manager.model_names()])
        return web.json_response(models.model_dump())

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
            if self.request_template is not None:
                body = self.request_template.apply(body)
        except Exception as exc:  # noqa: BLE001
            return _error(400, f"invalid request body: {exc}", code="invalid_json")
        try:
            chat_request = ChatCompletionRequest.model_validate(body)
        except Exception as exc:  # noqa: BLE001
            return _validation_error(exc)
        if chat_request.top_logprobs and not chat_request.logprobs:
            return _error(
                400, "top_logprobs requires logprobs=true", param="top_logprobs",
                code="invalid_value",
            )
        rf_type = (chat_request.response_format or {}).get("type", "text")
        if rf_type not in ("text", "json_object"):
            # json_object rides guided decoding (llm/guided.py; workers
            # without the mask table reject and this surfaces as a 400
            # below).  json_schema is not implemented: silently ignoring it
            # would hand the client unconstrained text it believes is
            # schema-guaranteed
            return _error(
                400,
                f"response_format type {rf_type!r} is not supported "
                "(json_object is; schema-constrained decoding is not)",
                param="response_format", code="unsupported_value",
            )
        engine = self.manager.chat_engines.get(chat_request.model)
        if engine is None:
            return _error(
                404, f"model '{chat_request.model}' not found",
                param="model", code="model_not_found",
            )
        if self.prefetch_hinter is not None:
            self.prefetch_hinter.on_request(chat_request.model, chat_request)

        guard = self.metrics.guard(
            chat_request.model, "chat_completions",
            "stream" if chat_request.stream else "unary",
            trace_id=request["request_id"],
        )
        root = self._trace_root(request, "chat_completions", chat_request.model)
        if not chat_request.stream:
            # non-streaming responses always carry usage (OpenAI semantics)
            chat_request.stream_options = {**(chat_request.stream_options or {}), "include_usage": True}
        ctx = None
        try:
            try:
                stream, ctx = await _start_generation(engine, chat_request, root)
            except ValueError as exc:
                guard.mark_client_error()
                return _error(400, str(exc))
            if chat_request.stream:
                return await self._stream_sse(request, stream, ctx, guard, chat_request.model)
            chunks = _data_only(stream, guard)
            response = await aggregate_chat_stream(chunks)
            guard.mark_ok()
            self._observe_usage(chat_request.model, response.usage)
            return web.json_response(response.model_dump(exclude_none=True))
        except asyncio.CancelledError:
            guard.mark_cancelled()
            if ctx is not None:
                ctx.ctx.kill()
            raise
        except Exception as exc:  # noqa: BLE001
            logger.exception("chat request failed")
            return _error(500, repr(exc), "internal_error")
        finally:
            guard.done()
            self._finish_request(request, root, guard)

    async def handle_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
            if self.request_template is not None:
                body = self.request_template.apply(body)
        except Exception as exc:  # noqa: BLE001
            return _error(400, f"invalid request body: {exc}", code="invalid_json")
        try:
            completion_request = CompletionRequest.model_validate(body)
        except Exception as exc:  # noqa: BLE001
            return _validation_error(exc)
        if completion_request.echo:
            # echo prepends the prompt to the completion text (OpenAI
            # completions semantics); supported for unary string prompts
            if completion_request.stream:
                return _error(400, "echo is not supported with stream", param="echo")
            if not isinstance(completion_request.prompt, str):
                return _error(400, "echo requires a string prompt", param="echo")
            if completion_request.logprobs:
                # prompt-token logprobs are not computed, and prepending the
                # prompt would desync text_offset; reject rather than return
                # silently-wrong scoring data
                return _error(400, "echo is not supported with logprobs", param="echo")
        engine = self.manager.completion_engines.get(completion_request.model)
        if engine is None:
            return _error(
                404, f"model '{completion_request.model}' not found",
                param="model", code="model_not_found",
            )
        if self.prefetch_hinter is not None:
            self.prefetch_hinter.on_request(
                completion_request.model, completion_request
            )

        guard = self.metrics.guard(
            completion_request.model, "completions",
            "stream" if completion_request.stream else "unary",
            trace_id=request["request_id"],
        )
        root = self._trace_root(request, "completions", completion_request.model)
        if not completion_request.stream:
            completion_request.stream_options = {**(completion_request.stream_options or {}), "include_usage": True}
        ctx = None
        try:
            try:
                stream, ctx = await _start_generation(engine, completion_request, root)
            except ValueError as exc:
                guard.mark_client_error()
                return _error(400, str(exc))
            if completion_request.stream:
                return await self._stream_sse(request, stream, ctx, guard, completion_request.model)
            chunks = _data_only(stream, guard)
            response = await aggregate_completion_stream(chunks)
            if completion_request.echo:
                for choice in response.choices:
                    choice.text = completion_request.prompt + (choice.text or "")
            guard.mark_ok()
            self._observe_usage(completion_request.model, response.usage)
            return web.json_response(response.model_dump(exclude_none=True))
        except asyncio.CancelledError:
            guard.mark_cancelled()
            if ctx is not None:
                ctx.ctx.kill()
            raise
        except Exception as exc:  # noqa: BLE001
            logger.exception("completion request failed")
            return _error(500, repr(exc), "internal_error")
        finally:
            guard.done()
            self._finish_request(request, root, guard)

    async def handle_embeddings(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception as exc:  # noqa: BLE001
            return _error(400, f"invalid request body: {exc}", code="invalid_json")
        try:
            embedding_request = EmbeddingRequest.model_validate(body)
        except Exception as exc:  # noqa: BLE001
            return _validation_error(exc)
        engine = self.manager.embedding_engines.get(embedding_request.model)
        if engine is None:
            return _error(
                404, f"model '{embedding_request.model}' not found",
                param="model", code="model_not_found",
            )
        guard = self.metrics.guard(
            embedding_request.model, "embeddings", "unary",
            trace_id=request["request_id"],
        )
        root = self._trace_root(request, "embeddings", embedding_request.model)
        try:
            try:
                response = await engine.embed(embedding_request)
            except ValueError as exc:
                guard.mark_client_error()
                return _error(400, str(exc))
            guard.mark_ok()
            return web.json_response(response.model_dump(exclude_none=True))
        except Exception as exc:  # noqa: BLE001
            logger.exception("embedding request failed")
            return _error(500, repr(exc), "internal_error")
        finally:
            guard.done()
            self._finish_request(request, root, guard)

    # -- streaming ---------------------------------------------------------
    async def _stream_sse(self, request, stream, ctx, guard, model: str) -> web.StreamResponse:
        response = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                # echoed here (not in the middleware): an SSE response is
                # already prepared by the time the middleware sees it
                REQUEST_ID_HEADER: request["request_id"],
            }
        )
        await response.prepare(request)
        completion_tokens = 0
        try:
            async for ann in stream:
                if ann.is_annotation():
                    await response.write(
                        sse.encode_event(event=ann.event, comments=ann.comment).encode()
                    )
                    continue
                # usage-only final chunks (include_usage) carry no choices:
                # counting them would inflate ITL samples and the output-
                # token histogram by one per stream
                if getattr(ann.data, "choices", None):
                    guard.token_observed()
                    completion_tokens += 1
                # pydantic-core's Rust serializer: ~3x faster than
                # model_dump() + json.dumps() (measured 4us vs 12us per
                # chunk), and this runs once per streamed chunk, squarely
                # on the per-token serving path
                payload = ann.data.model_dump_json(exclude_none=True)
                await response.write(sse.encode_event(data=payload).encode())
            await response.write(sse.encode_done().encode())
            guard.mark_ok()
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: propagate kill upstream; not a server error
            guard.mark_cancelled()
            ctx.ctx.kill()
        except Exception as exc:  # noqa: BLE001 — engine failure mid-stream:
            # the SSE response already started, so surface an error event
            # (never a fake finish) and stop generation
            logger.exception("stream failed mid-flight")
            try:
                payload = json.dumps(
                    {"error": {"message": repr(exc), "type": "internal_error"}}
                )
                await response.write(sse.encode_event(data=payload).encode())
            except Exception:  # noqa: BLE001 — connection may be gone too
                pass
            ctx.ctx.kill()
        finally:
            self.metrics.output_tokens.labels(model).observe(completion_tokens)
        await response.write_eof()
        return response

    def _observe_usage(self, model: str, usage) -> None:
        if usage is None:
            return
        self.metrics.input_tokens.labels(model).observe(usage.prompt_tokens)
        self.metrics.output_tokens.labels(model).observe(usage.completion_tokens)


def _data_only(stream, guard):
    """Strip annotations; count tokens for metrics (usage-only chunks have
    no choices and pass through uncounted)."""

    async def gen():
        async for ann in stream:
            if ann.is_annotation() or ann.data is None:
                continue
            if getattr(ann.data, "choices", None):
                guard.token_observed()
            yield ann.data

    return gen()


async def _start_generation(engine, request_model, root=None):
    """One dispatch for both OpenAI endpoints: validates ``n``, fans out
    when n>1, else a plain single-choice generate.  ``root`` is the
    request's root span handle; its context rides the EngineContext into
    every downstream layer.  Returns (stream, ctx); raises ValueError for
    400-class problems."""
    n = request_model.n if request_model.n is not None else 1
    if n < 1:
        raise ValueError("n must be >= 1")
    if n > 16:
        raise ValueError("n must be <= 16")
    trace_ctx = root.ctx if root is not None else None
    if n > 1:
        return await _generate_fanout(engine, request_model, n, trace_ctx)
    ctx = Context(request_model)
    ctx.ctx.trace = trace_ctx
    return await engine.generate(ctx), ctx


async def _generate_fanout(engine, request_model, n: int, trace_ctx=None):
    """OpenAI ``n>1``: issue n independent single-choice requests (seeded
    requests get seed+i per choice, like vLLM) and merge the streams with
    choice indices rewritten; per-choice usage chunks are summed into one.
    Returns (merged_annotated_stream, parent_ctx); cancelling the parent
    context fans out to every sub-request through link_child."""
    subs = []
    for i in range(n):
        sub = request_model.model_copy(deep=True)
        sub.n = 1
        if getattr(sub, "seed", None) is not None:
            sub.seed = sub.seed + i
        subs.append(sub)
    parent = Context(request_model)
    parent.ctx.trace = trace_ctx
    ctxs = [Context(sub) for sub in subs]
    for c in ctxs:
        # all sub-requests parent to the one root span: the trace tree shows
        # n parallel dispatch/worker/engine branches under one http.request
        c.ctx.trace = trace_ctx
        parent.ctx.link_child(c.ctx)
    streams = []
    try:
        for c in ctxs:
            streams.append(await engine.generate(c))
    except BaseException:
        # sub-requests already submitted must not decode to max_tokens
        # with nobody consuming them
        for c in ctxs:
            c.ctx.kill()
        raise

    queue: asyncio.Queue = asyncio.Queue()

    async def pump(i, stream):
        try:
            async for ann in stream:
                await queue.put((i, ann))
        except Exception as exc:  # noqa: BLE001 — surface to the consumer
            await queue.put((i, exc))
        finally:
            await queue.put((i, None))

    tasks = [spawn_logged(pump(i, st)) for i, st in enumerate(streams)]

    async def gen():
        done = 0
        usage_sum = None
        proto = None   # any data chunk: template for the final usage chunk
        resp_id = None  # one response id for the whole merged stream
        try:
            while done < len(streams):
                i, ann = await queue.get()
                if ann is None:
                    done += 1
                    continue
                if isinstance(ann, Exception):
                    raise ann
                if ann.is_annotation():
                    if i == 0:  # identical per sub-request: emit once
                        yield ann
                    continue
                data = ann.data
                if data is None:
                    continue
                if getattr(data, "usage", None) is not None and not data.choices:
                    u = data.usage
                    if usage_sum is None:
                        usage_sum = u.model_copy()
                    else:
                        # one shared prompt, n completions
                        usage_sum.completion_tokens += u.completion_tokens
                        usage_sum.total_tokens += u.completion_tokens
                    continue
                # every sub-request minted its own id: present ONE id so
                # clients grouping deltas by response id see one stream
                if resp_id is None:
                    resp_id = data.id
                data.id = resp_id
                proto = proto or data
                for choice in data.choices:
                    choice.index = i
                yield ann
            if usage_sum is not None and proto is not None:
                final = type(proto)(
                    id=resp_id, model=proto.model, choices=[], usage=usage_sum
                )
                from dynamo_tpu.llm.protocols.common import Annotated

                yield Annotated.from_data(final)
        except BaseException:
            # one sub-stream failed or the consumer went away: the healthy
            # sub-requests must not keep decoding into dead air
            for c in ctxs:
                c.ctx.kill()
            raise
        finally:
            for t in tasks:
                t.cancel()

    from dynamo_tpu.runtime.engine import ResponseStream

    return ResponseStream(gen(), parent.ctx), parent
