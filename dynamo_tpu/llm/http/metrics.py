"""Frontend Prometheus metrics.

Per-model request/latency/token metrics under the ``dyn_llm`` prefix
(reference: lib/llm/src/http/service/metrics.rs:94-260, prefix ``nv_llm``).
``InflightGuard`` bumps the inflight gauge and records status + duration on
drop, like the reference's RAII guard.

Layered on top (one scrape surface, ``FrontendMetrics.render``):

- **SLO tracking** (observability/slo.py): every TTFT/ITL observation and
  request outcome also feeds the burn-rate tracker, rendered as
  ``dyn_slo_*`` families and served as JSON on the frontend's ``/slo``.
- **Exemplars** (:class:`ExemplarStore`): each latency observation records
  the request's ``x-request-id`` trace id against the histogram bucket it
  landed in — so the operator staring at a p99 bucket can jump straight to
  that request's span tree in the recorder.  Rendered as parse-safe
  ``# EXEMPLAR`` comment lines after the exposition, and structurally in
  the ``/slo`` payload.
"""

from __future__ import annotations

import time

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.utils import floatToGoString

from dynamo_tpu.observability.slo import SloTracker
from dynamo_tpu.robustness import counters as robustness_counters

PREFIX = "dyn_llm"

DURATION_BUCKETS = (0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)

TTFT_FAMILY = f"{PREFIX}_http_service_time_to_first_token_seconds"
ITL_FAMILY = f"{PREFIX}_http_service_inter_token_latency_seconds"
DURATION_FAMILY = f"{PREFIX}_http_service_request_duration_seconds"


class ExemplarStore:
    """Latest trace id per histogram bucket: the metric↔trace join point.

    Bounded by construction (one entry per (family, bucket) pair), so a
    long serve window cannot grow it.  The frontend runs single-threaded on
    the event loop — plain dict updates suffice."""

    def __init__(self) -> None:
        self._data: dict[str, dict[str, dict]] = {}

    def observe(
        self, family: str, buckets: tuple, value: float, trace_id: str | None
    ) -> None:
        if not trace_id:
            return
        # same spelling prometheus_client uses for the histogram's own
        # _bucket le labels ("5.0", not "5") so the string join holds
        le = "+Inf"
        for b in buckets:
            if value <= b:
                le = floatToGoString(b)
                break
        self._data.setdefault(family, {})[le] = {
            "le": le,
            "trace_id": trace_id,
            "value": value,
            "ts": time.time(),
        }

    def snapshot(self) -> dict[str, list[dict]]:
        """{family: [exemplar, ...]} sorted by bucket bound (for /slo)."""
        def _key(e: dict) -> float:
            return float("inf") if e["le"] == "+Inf" else float(e["le"])

        return {
            family: sorted(by_le.values(), key=_key)
            for family, by_le in self._data.items()
        }

    def render(self) -> bytes:
        """Parse-safe comment lines appended to the text exposition (plain
        ``#`` comments are legal Prometheus text format; OpenMetrics-native
        exemplar syntax needs a different content type end-to-end)."""
        lines = []
        for family, exemplars in sorted(self.snapshot().items()):
            for e in exemplars:
                lines.append(
                    f'# EXEMPLAR {family}_bucket{{le="{e["le"]}"}} '
                    f'trace_id="{e["trace_id"]}" value={e["value"]:.6g} '
                    f"ts={e['ts']:.3f}"
                )
        if not lines:
            return b""
        return ("\n".join(lines) + "\n").encode()


class FrontendMetrics:
    def __init__(
        self,
        registry: CollectorRegistry | None = None,
        slo: SloTracker | None = None,
    ):
        self.registry = registry or CollectorRegistry()
        self.slo = slo or SloTracker()
        self.exemplars = ExemplarStore()
        # fleet TopologyMap (attach_topology): rendered as dyn_topology_*
        # families; None still declares the families with zero samples
        self.topology = None
        self.requests_total = Counter(
            f"{PREFIX}_http_service_requests_total",
            "Total HTTP LLM requests",
            ["model", "endpoint", "request_type", "status"],
            registry=self.registry,
        )
        self.inflight = Gauge(
            f"{PREFIX}_http_service_inflight_requests",
            "In-flight HTTP LLM requests",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.request_duration = Histogram(
            f"{PREFIX}_http_service_request_duration_seconds",
            "Request duration",
            ["model", "endpoint"],
            registry=self.registry,
            buckets=DURATION_BUCKETS,
        )
        self.time_to_first_token = Histogram(
            f"{PREFIX}_http_service_time_to_first_token_seconds",
            "Time to first streamed token",
            ["model"],
            registry=self.registry,
            buckets=TTFT_BUCKETS,
        )
        self.inter_token_latency = Histogram(
            f"{PREFIX}_http_service_inter_token_latency_seconds",
            "Latency between streamed tokens",
            ["model"],
            registry=self.registry,
            buckets=ITL_BUCKETS,
        )
        self.input_tokens = Histogram(
            f"{PREFIX}_http_service_input_sequence_tokens",
            "Prompt token count",
            ["model"],
            registry=self.registry,
            buckets=(16, 64, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768, 131072),
        )
        self.output_tokens = Histogram(
            f"{PREFIX}_http_service_output_sequence_tokens",
            "Completion token count",
            ["model"],
            registry=self.registry,
            buckets=(1, 4, 16, 64, 128, 256, 512, 1024, 2048, 8192),
        )

    def guard(
        self,
        model: str,
        endpoint: str,
        request_type: str,
        trace_id: str | None = None,
    ) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_type, trace_id)

    def slo_status(self) -> dict:
        """The frontend ``/slo`` payload: burn rates + exemplars."""
        status = self.slo.status()
        status["exemplars"] = self.exemplars.snapshot()
        return status

    def attach_topology(self, topo_map) -> None:
        self.topology = topo_map

    def render(self) -> bytes:
        # one scrape surface: per-model serving metrics plus the process-
        # wide resilience counters (retries, sheds, control-plane
        # reconnects), the SLO burn-rate families, topology-map gauges,
        # flight-recorder summary, and bucket exemplars
        from dynamo_tpu.observability import flight
        from dynamo_tpu.topology import metrics as topology_metrics

        return (
            generate_latest(self.registry)
            + robustness_counters.render()
            + self.slo.render()
            + topology_metrics.render(self.topology)
            + flight.render()
            + self.exemplars.render()
        )


class InflightGuard:
    def __init__(
        self,
        metrics: FrontendMetrics,
        model: str,
        endpoint: str,
        request_type: str,
        trace_id: str | None = None,
    ):
        self.metrics = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = request_type
        self.trace_id = trace_id
        self.status = "error"
        self._start = time.monotonic()
        self._last_token: float | None = None
        # per-request lifecycle facts, readable after done() (span summary
        # + structured request log)
        self.ttft_s: float | None = None
        self.token_count = 0
        metrics.inflight.labels(model, endpoint).inc()

    def mark_ok(self) -> None:
        self.status = "success"

    def mark_client_error(self) -> None:
        """Request failed because of the caller (400-class): visible in
        requests_total, but not a server SLO violation."""
        self.status = "client_error"

    def mark_cancelled(self) -> None:
        """Caller went away (stream reset / request cancelled): not a
        server SLO violation."""
        self.status = "cancelled"

    def token_observed(self) -> None:
        now = time.monotonic()
        m = self.metrics
        if self._last_token is None:
            self.ttft_s = now - self._start
            m.time_to_first_token.labels(self.model).observe(self.ttft_s)
            m.slo.observe_latency("ttft", self.ttft_s)
            m.exemplars.observe(TTFT_FAMILY, TTFT_BUCKETS, self.ttft_s, self.trace_id)
        else:
            itl = now - self._last_token
            m.inter_token_latency.labels(self.model).observe(itl)
            m.slo.observe_latency("itl", itl)
            m.exemplars.observe(ITL_FAMILY, ITL_BUCKETS, itl, self.trace_id)
        self._last_token = now
        self.token_count += 1

    @property
    def duration_s(self) -> float:
        return time.monotonic() - self._start

    def done(self) -> None:
        duration = time.monotonic() - self._start
        m = self.metrics
        m.inflight.labels(self.model, self.endpoint).dec()
        m.requests_total.labels(
            self.model, self.endpoint, self.request_type, self.status
        ).inc()
        m.request_duration.labels(self.model, self.endpoint).observe(duration)
        m.exemplars.observe(DURATION_FAMILY, DURATION_BUCKETS, duration, self.trace_id)
        # error-rate SLO: only SERVER failures burn budget — client-caused
        # outcomes (client_error, cancelled) must not trip the shed hook
        m.slo.observe_outcome("error_rate", self.status != "error")
        # flight-recorder burn trigger: a worst-window burn rate above
        # DYN_FLIGHT_BURN auto-dumps every live recorder (rate-limited
        # inside — this runs per finished request)
        from dynamo_tpu.observability import flight

        flight.check_burn(m.slo)
