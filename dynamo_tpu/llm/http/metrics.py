"""Frontend Prometheus metrics.

Per-model request/latency/token metrics under the ``dyn_llm`` prefix
(reference: lib/llm/src/http/service/metrics.rs:94-260, prefix ``nv_llm``).
``InflightGuard`` bumps the inflight gauge and records status + duration on
drop, like the reference's RAII guard.
"""

from __future__ import annotations

import time

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from dynamo_tpu.robustness import counters as robustness_counters

PREFIX = "dyn_llm"


class FrontendMetrics:
    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.requests_total = Counter(
            f"{PREFIX}_http_service_requests_total",
            "Total HTTP LLM requests",
            ["model", "endpoint", "request_type", "status"],
            registry=self.registry,
        )
        self.inflight = Gauge(
            f"{PREFIX}_http_service_inflight_requests",
            "In-flight HTTP LLM requests",
            ["model", "endpoint"],
            registry=self.registry,
        )
        self.request_duration = Histogram(
            f"{PREFIX}_http_service_request_duration_seconds",
            "Request duration",
            ["model", "endpoint"],
            registry=self.registry,
            buckets=(0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
        )
        self.time_to_first_token = Histogram(
            f"{PREFIX}_http_service_time_to_first_token_seconds",
            "Time to first streamed token",
            ["model"],
            registry=self.registry,
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
        )
        self.inter_token_latency = Histogram(
            f"{PREFIX}_http_service_inter_token_latency_seconds",
            "Latency between streamed tokens",
            ["model"],
            registry=self.registry,
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
        )
        self.input_tokens = Histogram(
            f"{PREFIX}_http_service_input_sequence_tokens",
            "Prompt token count",
            ["model"],
            registry=self.registry,
            buckets=(16, 64, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768, 131072),
        )
        self.output_tokens = Histogram(
            f"{PREFIX}_http_service_output_sequence_tokens",
            "Completion token count",
            ["model"],
            registry=self.registry,
            buckets=(1, 4, 16, 64, 128, 256, 512, 1024, 2048, 8192),
        )

    def guard(self, model: str, endpoint: str, request_type: str) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, request_type)

    def render(self) -> bytes:
        # one scrape surface: per-model serving metrics plus the process-
        # wide resilience counters (retries, sheds, control-plane reconnects)
        return generate_latest(self.registry) + robustness_counters.render()


class InflightGuard:
    def __init__(self, metrics: FrontendMetrics, model: str, endpoint: str, request_type: str):
        self.metrics = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = request_type
        self.status = "error"
        self._start = time.monotonic()
        self._last_token: float | None = None
        # per-request lifecycle facts, readable after done() (span summary
        # + structured request log)
        self.ttft_s: float | None = None
        self.token_count = 0
        metrics.inflight.labels(model, endpoint).inc()

    def mark_ok(self) -> None:
        self.status = "success"

    def token_observed(self) -> None:
        now = time.monotonic()
        if self._last_token is None:
            self.ttft_s = now - self._start
            self.metrics.time_to_first_token.labels(self.model).observe(self.ttft_s)
        else:
            self.metrics.inter_token_latency.labels(self.model).observe(now - self._last_token)
        self._last_token = now
        self.token_count += 1

    @property
    def duration_s(self) -> float:
        return time.monotonic() - self._start

    def done(self) -> None:
        self.metrics.inflight.labels(self.model, self.endpoint).dec()
        self.metrics.requests_total.labels(
            self.model, self.endpoint, self.request_type, self.status
        ).inc()
        self.metrics.request_duration.labels(self.model, self.endpoint).observe(
            time.monotonic() - self._start
        )
