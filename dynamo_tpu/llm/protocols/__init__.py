from dynamo_tpu.llm.protocols.common import (
    Annotated,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

__all__ = [
    "Annotated",
    "FinishReason",
    "LLMEngineOutput",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
]
