"""Delta aggregation: fold a streamed response into a unary response for
non-streaming clients (reference:
lib/llm/src/protocols/openai/chat_completions/aggregator.rs,
completions/aggregator.rs).
"""

from __future__ import annotations

from typing import AsyncIterator

from dynamo_tpu.llm.protocols.openai import (
    ChatChoice,
    ChatCompletionChunk,
    ChatCompletionResponse,
    ChatMessage,
    CompletionChoice,
    CompletionResponse,
    Usage,
)


async def aggregate_chat_stream(
    chunks: AsyncIterator[ChatCompletionChunk],
) -> ChatCompletionResponse:
    response_id = ""
    model = ""
    created = 0
    usage: Usage | None = None
    # per-choice accumulation
    contents: dict[int, list[str]] = {}
    roles: dict[int, str] = {}
    finish: dict[int, str | None] = {}
    tool_calls: dict[int, list[dict]] = {}
    logprob_content: dict[int, list[dict]] = {}

    async for chunk in chunks:
        response_id = chunk.id or response_id
        model = chunk.model or model
        created = chunk.created or created
        if chunk.usage is not None:
            usage = chunk.usage
        for choice in chunk.choices:
            idx = choice.index
            contents.setdefault(idx, [])
            if choice.delta.role:
                roles[idx] = choice.delta.role
            if choice.delta.content:
                contents[idx].append(choice.delta.content)
            if choice.delta.tool_calls:
                tool_calls.setdefault(idx, []).extend(choice.delta.tool_calls)
            if choice.finish_reason is not None:
                finish[idx] = choice.finish_reason
            if choice.logprobs and choice.logprobs.get("content"):
                logprob_content.setdefault(idx, []).extend(choice.logprobs["content"])

    choices = [
        ChatChoice(
            index=idx,
            message=ChatMessage(
                role=roles.get(idx, "assistant"),  # type: ignore[arg-type]
                content="".join(parts),
                tool_calls=tool_calls.get(idx) or None,
            ),
            finish_reason=finish.get(idx),
            logprobs=(
                {"content": logprob_content[idx]} if idx in logprob_content else None
            ),
        )
        for idx, parts in sorted(contents.items())
    ]
    return ChatCompletionResponse(
        id=response_id, model=model, created=created, choices=choices, usage=usage
    )


async def aggregate_completion_stream(
    chunks: AsyncIterator[CompletionResponse],
) -> CompletionResponse:
    response_id = ""
    model = ""
    created = 0
    usage: Usage | None = None
    texts: dict[int, list[str]] = {}
    finish: dict[int, str | None] = {}
    lp_tokens: dict[int, list[str]] = {}
    lp_values: dict[int, list[float]] = {}
    lp_offsets: dict[int, list[int]] = {}
    lp_top: dict[int, list] = {}

    async for chunk in chunks:
        response_id = chunk.id or response_id
        model = chunk.model or model
        created = chunk.created or created
        if chunk.usage is not None:
            usage = chunk.usage
        for choice in chunk.choices:
            texts.setdefault(choice.index, [])
            if choice.text:
                texts[choice.index].append(choice.text)
            if choice.finish_reason is not None:
                finish[choice.index] = choice.finish_reason
            if choice.logprobs:
                lp_tokens.setdefault(choice.index, []).extend(
                    choice.logprobs.get("tokens", [])
                )
                lp_values.setdefault(choice.index, []).extend(
                    choice.logprobs.get("token_logprobs", [])
                )
                lp_offsets.setdefault(choice.index, []).extend(
                    choice.logprobs.get("text_offset") or []
                )
                # keep top rows PARALLEL to tokens: a chunk without
                # alternatives contributes empty rows, never a shift
                n_toks = len(choice.logprobs.get("tokens", []))
                rows = choice.logprobs.get("top_logprobs") or []
                rows = list(rows[:n_toks]) + [{}] * max(0, n_toks - len(rows))
                lp_top.setdefault(choice.index, []).extend(rows)

    choices = [
        CompletionChoice(
            index=idx, text="".join(parts), finish_reason=finish.get(idx),
            logprobs=(
                {
                    "tokens": lp_tokens[idx],
                    "token_logprobs": lp_values[idx],
                    "top_logprobs": (
                        lp_top[idx]
                        if idx in lp_top and any(lp_top[idx])
                        else None
                    ),
                    "text_offset": lp_offsets.get(idx, []),
                }
                if idx in lp_tokens
                else None
            ),
        )
        for idx, parts in sorted(texts.items())
    ]
    return CompletionResponse(
        id=response_id, model=model, created=created, choices=choices, usage=usage
    )
