"""Internal LLM protocol types.

The engine-facing request/response contract that every backend speaks after
preprocessing, mirroring the reference's common protocol types (reference:
lib/llm/src/protocols/common.rs: SamplingOptions / StopConditions /
PreprocessedRequest / LLMEngineOutput) and the ``Annotated`` streaming
envelope (lib/llm/src/protocols/annotated.rs).

Everything round-trips through plain dicts (``to_wire`` / ``from_wire``) for
msgpack transport on the data plane.
"""

from __future__ import annotations

import enum
from dataclasses import asdict, dataclass, field
from typing import Any, Generic, TypeVar

T = TypeVar("T")


class FinishReason(str, enum.Enum):
    STOP = "stop"            # hit a stop condition (eos / stop sequence)
    LENGTH = "length"        # hit max_tokens / context limit
    CANCELLED = "cancelled"  # caller stopped generation
    ERROR = "error"
    CONTENT_FILTER = "content_filter"


@dataclass
class SamplingOptions:
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    frequency_penalty: float | None = None
    presence_penalty: float | None = None
    repetition_penalty: float | None = None
    seed: int | None = None
    n: int = 1
    use_greedy: bool = False
    # number of per-token alternatives to report (OpenAI top_logprobs);
    # capped by the engine's compile-time K
    top_logprobs: int = 0
    # OpenAI logit_bias: {token_id: bias}.  Keys go over the wire as
    # STRINGS (the msgpack envelope unpacks with strict string map keys;
    # JSON does the same) — consumers must int() them.  Entries beyond the
    # engine's compile bucket are dropped (largest-magnitude first
    # retained).
    logit_bias: dict | None = None

    def to_wire(self) -> dict:
        d = {k: v for k, v in asdict(self).items() if v not in (None,)}
        if d.get("logit_bias"):
            d["logit_bias"] = {str(k): float(v) for k, v in d["logit_bias"].items()}
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "SamplingOptions":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


@dataclass
class StopConditions:
    max_tokens: int | None = None
    stop: list[str] = field(default_factory=list)
    stop_token_ids: list[int] = field(default_factory=list)
    min_tokens: int | None = None
    ignore_eos: bool = False

    def to_wire(self) -> dict:
        return asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "StopConditions":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


@dataclass
class PreprocessedRequest:
    """What the frontend hands to a backend engine: token ids + options."""

    token_ids: list[int]
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    eos_token_ids: list[int] = field(default_factory=list)
    model: str | None = None
    annotations: list[str] = field(default_factory=list)
    # router/disagg hints
    estimated_prefix_hit_blocks: int | None = None
    disagg_mode: str | None = None  # None | "prefill" | "decode"
    mdc_sum: str | None = None
    # guided decoding: "json" constrains sampling to valid-JSON prefixes
    # (OpenAI response_format json_object; engines without the compiled
    # mask table reject rather than silently ignore)
    output_format: str | None = None

    def to_wire(self) -> dict:
        return {
            "token_ids": self.token_ids,
            "sampling": self.sampling.to_wire(),
            "stop": self.stop.to_wire(),
            "eos_token_ids": self.eos_token_ids,
            "model": self.model,
            "annotations": self.annotations,
            "estimated_prefix_hit_blocks": self.estimated_prefix_hit_blocks,
            "disagg_mode": self.disagg_mode,
            "mdc_sum": self.mdc_sum,
            "output_format": self.output_format,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d["token_ids"]),
            sampling=SamplingOptions.from_wire(d.get("sampling", {})),
            stop=StopConditions.from_wire(d.get("stop", {})),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            model=d.get("model"),
            annotations=list(d.get("annotations", [])),
            estimated_prefix_hit_blocks=d.get("estimated_prefix_hit_blocks"),
            disagg_mode=d.get("disagg_mode"),
            mdc_sum=d.get("mdc_sum"),
            output_format=d.get("output_format"),
        )


@dataclass
class LLMEngineOutput:
    """One streamed step of engine output (usually one token)."""

    token_ids: list[int] = field(default_factory=list)
    # engines may emit text directly (echo/full engines); normally the
    # detokenizing backend fills ``text`` from ``token_ids``
    text: str | None = None
    cum_log_probs: float | None = None
    finish_reason: FinishReason | None = None
    # kv-cache stats piggybacked for metrics annotations
    completion_tokens: int | None = None
    # engine-side failure detail (finish_reason == ERROR)
    error: str | None = None
    # per-token logprobs parallel to token_ids (engines fill when available)
    logprobs: list[float] | None = None
    # per-token top-k alternatives: list (parallel to token_ids) of
    # [[token_id, logprob], ...] rows
    top_logprobs: list[list[list]] | None = None

    def to_wire(self) -> dict:
        d: dict[str, Any] = {"token_ids": self.token_ids}
        if self.text is not None:
            d["text"] = self.text
        if self.cum_log_probs is not None:
            d["cum_log_probs"] = self.cum_log_probs
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason.value
        if self.completion_tokens is not None:
            d["completion_tokens"] = self.completion_tokens
        if self.error is not None:
            d["error"] = self.error
        if self.logprobs is not None:
            d["logprobs"] = self.logprobs
        if self.top_logprobs is not None:
            d["top_logprobs"] = self.top_logprobs
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "LLMEngineOutput":
        fr = d.get("finish_reason")
        return cls(
            token_ids=list(d.get("token_ids", [])),
            text=d.get("text"),
            cum_log_probs=d.get("cum_log_probs"),
            finish_reason=FinishReason(fr) if fr else None,
            completion_tokens=d.get("completion_tokens"),
            error=d.get("error"),
            logprobs=d.get("logprobs"),
            top_logprobs=d.get("top_logprobs"),
        )


@dataclass
class Annotated(Generic[T]):
    """Streaming envelope: a data item or an out-of-band annotation event
    (``formatted_prompt``, ``token_ids``, ``llm_metrics``...; reference:
    lib/llm/src/preprocessor.rs:61-63)."""

    data: T | None = None
    id: str | None = None
    event: str | None = None
    comment: list[str] = field(default_factory=list)

    @classmethod
    def from_data(cls, data: T) -> "Annotated[T]":
        return cls(data=data)

    @classmethod
    def from_annotation(cls, event: str, value: Any) -> "Annotated[T]":
        import json

        return cls(data=None, event=event, comment=[json.dumps(value)])

    def is_annotation(self) -> bool:
        return self.event is not None

    def to_wire(self, data_to_wire=None) -> dict:
        d: dict[str, Any] = {}
        if self.data is not None:
            d["data"] = data_to_wire(self.data) if data_to_wire else self.data
        if self.id is not None:
            d["id"] = self.id
        if self.event is not None:
            d["event"] = self.event
        if self.comment:
            d["comment"] = self.comment
        return d

    @classmethod
    def from_wire(cls, d: dict, data_from_wire=None) -> "Annotated":
        data = d.get("data")
        if data is not None and data_from_wire is not None:
            data = data_from_wire(data)
        return cls(
            data=data,
            id=d.get("id"),
            event=d.get("event"),
            comment=list(d.get("comment", [])),
        )
