"""OpenAI-compatible API types (pydantic).

Request/response surface of the HTTP frontend (reference:
lib/llm/src/protocols/openai.rs and openai/{chat_completions,completions,
embeddings}).  The ``ext`` field mirrors the reference's ``nvext`` extension
block (annotations, ignore_eos, greedy sampling).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Literal, Union

from pydantic import BaseModel, ConfigDict, Field

from dynamo_tpu.llm.protocols.common import (
    FinishReason,
    SamplingOptions,
    StopConditions,
)


class Ext(BaseModel):
    """Extension block (reference: nvext)."""

    model_config = ConfigDict(extra="allow")
    annotations: list[str] = Field(default_factory=list)
    ignore_eos: bool | None = None
    greed_sampling: bool | None = None
    use_raw_prompt: bool | None = None


class ContentPart(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: str
    text: str | None = None
    image_url: dict[str, Any] | None = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: Literal["system", "user", "assistant", "tool", "developer"]
    content: Union[str, list[ContentPart], None] = None
    name: str | None = None
    tool_calls: list[dict[str, Any]] | None = None
    tool_call_id: str | None = None

    def text(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if self.content is None:
            return ""
        return "".join(p.text or "" for p in self.content if p.type == "text")


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    messages: list[ChatMessage]
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None  # extension accepted by most servers
    n: int | None = 1
    stream: bool = False
    stream_options: dict[str, Any] | None = None
    stop: Union[str, list[str], None] = None
    max_tokens: int | None = None
    max_completion_tokens: int | None = None
    presence_penalty: float | None = None
    frequency_penalty: float | None = None
    seed: int | None = None
    logprobs: bool | None = None
    top_logprobs: int | None = None
    logit_bias: dict[str, float] | None = None
    user: str | None = None
    tools: list[dict[str, Any]] | None = None
    tool_choice: Any | None = None
    response_format: dict[str, Any] | None = None
    ext: Ext | None = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def sampling_options(self) -> SamplingOptions:
        return SamplingOptions(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            frequency_penalty=self.frequency_penalty,
            presence_penalty=self.presence_penalty,
            seed=self.seed,
            n=self.n or 1,
            use_greedy=bool(self.ext and self.ext.greed_sampling),
            top_logprobs=(self.top_logprobs or 0) if self.logprobs else 0,
            logit_bias=(
                {int(k): float(v) for k, v in self.logit_bias.items()}
                if self.logit_bias else None
            ),
        )

    def stop_conditions(self) -> StopConditions:
        return StopConditions(
            max_tokens=self.max_completion_tokens or self.max_tokens,
            stop=self.stop_list(),
            ignore_eos=bool(self.ext and self.ext.ignore_eos),
        )


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    prompt: Union[str, list[str], list[int], list[list[int]]]
    suffix: str | None = None
    max_tokens: int | None = 16
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    n: int | None = 1
    stream: bool = False
    stream_options: dict[str, Any] | None = None
    logprobs: int | None = None
    logit_bias: dict[str, float] | None = None
    echo: bool | None = None
    stop: Union[str, list[str], None] = None
    presence_penalty: float | None = None
    frequency_penalty: float | None = None
    seed: int | None = None
    user: str | None = None
    ext: Ext | None = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def sampling_options(self) -> SamplingOptions:
        return SamplingOptions(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            frequency_penalty=self.frequency_penalty,
            presence_penalty=self.presence_penalty,
            seed=self.seed,
            n=self.n or 1,
            use_greedy=bool(self.ext and self.ext.greed_sampling),
            top_logprobs=self.logprobs or 0,
            logit_bias=(
                {int(k): float(v) for k, v in self.logit_bias.items()}
                if self.logit_bias else None
            ),
        )

    def stop_conditions(self) -> StopConditions:
        return StopConditions(
            max_tokens=self.max_tokens,
            stop=self.stop_list(),
            ignore_eos=bool(self.ext and self.ext.ignore_eos),
        )


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: Union[str, list[str], list[int], list[list[int]]]
    encoding_format: Literal["float", "base64"] = "float"
    user: str | None = None


# ---------------------------------------------------------------------------
# Responses
# ---------------------------------------------------------------------------


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ChatDelta(BaseModel):
    role: str | None = None
    content: str | None = None
    tool_calls: list[dict[str, Any]] | None = None


class ChatChunkChoice(BaseModel):
    index: int = 0
    delta: ChatDelta
    finish_reason: str | None = None
    logprobs: Any | None = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[ChatChunkChoice] = Field(default_factory=list)
    usage: Usage | None = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: str | None = None
    logprobs: Any | None = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[ChatChoice] = Field(default_factory=list)
    usage: Usage | None = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: str | None = None
    logprobs: Any | None = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[CompletionChoice] = Field(default_factory=list)
    usage: Usage | None = None


class EmbeddingData(BaseModel):
    object: Literal["embedding"] = "embedding"
    index: int
    # list of floats, or a base64-packed float32 buffer (encoding_format=base64)
    embedding: list[float] | str


class EmbeddingResponse(BaseModel):
    object: Literal["list"] = "list"
    data: list[EmbeddingData] = Field(default_factory=list)
    model: str = ""
    usage: Usage | None = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "dynamo-tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: list[ModelInfo] = Field(default_factory=list)


def new_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def finish_reason_to_openai(reason: FinishReason | None) -> str | None:
    if reason is None:
        return None
    return {
        FinishReason.STOP: "stop",
        FinishReason.LENGTH: "length",
        FinishReason.CANCELLED: "stop",
        FinishReason.ERROR: "stop",
        FinishReason.CONTENT_FILTER: "content_filter",
    }[reason]
