"""OpenAI-compatible API types (pydantic).

Request/response surface of the HTTP frontend (reference:
lib/llm/src/protocols/openai.rs and openai/{chat_completions,completions,
embeddings}).  The ``ext`` field mirrors the reference's ``nvext`` extension
block (annotations, ignore_eos, greedy sampling).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Literal, Union

from pydantic import BaseModel, ConfigDict, Field, field_validator

from dynamo_tpu.llm.protocols.common import (
    FinishReason,
    SamplingOptions,
    StopConditions,
)


class Ext(BaseModel):
    """Extension block (reference: nvext)."""

    model_config = ConfigDict(extra="allow")
    annotations: list[str] = Field(default_factory=list)
    ignore_eos: bool | None = None
    greed_sampling: bool | None = None
    use_raw_prompt: bool | None = None


class ContentPart(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: str
    text: str | None = None
    image_url: dict[str, Any] | None = None


class FunctionDef(BaseModel):
    """A callable tool's schema (OpenAI function-calling surface)."""

    model_config = ConfigDict(extra="allow")
    name: str
    description: str | None = None
    parameters: dict[str, Any] | None = None
    strict: bool | None = None


class ToolDef(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: Literal["function"]
    function: FunctionDef


class NamedToolChoice(BaseModel):
    """``tool_choice={"type": "function", "function": {"name": ...}}``."""

    model_config = ConfigDict(extra="allow")
    type: Literal["function"]
    function: FunctionDef


# "none" | "auto" | "required" | a specific named function — typed instead
# of Any so a malformed tool_choice is a structured 400 at the protocol
# boundary, not a downstream surprise (reference validates in
# lib/llm/src/protocols/openai/chat_completions.rs via typed serde enums)
ToolChoice = Union[Literal["none", "auto", "required"], NamedToolChoice]


class _SamplingValidators(BaseModel):
    """Shared range checks for the sampling fields both request surfaces
    carry.  Ranges follow the OpenAI API contract (the reference enforces
    the same bounds in its typed request structs,
    lib/llm/src/protocols/common.rs); violations become structured 400s
    with the offending ``param`` named (llm/http/service.py)."""

    temperature: float | None = Field(None, ge=0.0, le=2.0)
    top_p: float | None = Field(None, ge=0.0, le=1.0)
    # extension accepted by most servers; -1 = disabled (vLLM convention)
    top_k: int | None = None
    presence_penalty: float | None = Field(None, ge=-2.0, le=2.0)
    frequency_penalty: float | None = Field(None, ge=-2.0, le=2.0)
    n: int | None = Field(1, ge=1, le=16)
    logit_bias: dict[str, float] | None = None
    stop: Union[str, list[str], None] = None

    @field_validator("top_k")
    @classmethod
    def _top_k_range(cls, v):
        if v is not None and v != -1 and v < 1:
            raise ValueError("top_k must be -1 (disabled) or >= 1")
        return v

    @field_validator("logit_bias")
    @classmethod
    def _logit_bias_range(cls, v):
        if v is None:
            return v
        for key, bias in v.items():
            try:
                int(key)
            except ValueError:
                raise ValueError(
                    f"logit_bias keys must be token ids, got {key!r}"
                ) from None
            if not -100.0 <= bias <= 100.0:
                raise ValueError(
                    f"logit_bias values must be in [-100, 100], got {bias}"
                )
        return v

    @field_validator("stop")
    @classmethod
    def _stop_shape(cls, v):
        if isinstance(v, list):
            if len(v) > 4:
                raise ValueError("stop accepts at most 4 sequences")
            if any(not s for s in v):
                raise ValueError("stop sequences must be non-empty")
        elif v == "":
            raise ValueError("stop sequences must be non-empty")
        return v


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: Literal["system", "user", "assistant", "tool", "developer"]
    content: Union[str, list[ContentPart], None] = None
    name: str | None = None
    tool_calls: list[dict[str, Any]] | None = None
    tool_call_id: str | None = None

    def text(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if self.content is None:
            return ""
        return "".join(p.text or "" for p in self.content if p.type == "text")


class ChatCompletionRequest(_SamplingValidators):
    model_config = ConfigDict(extra="allow")
    model: str
    messages: list[ChatMessage] = Field(min_length=1)
    stream: bool = False
    stream_options: dict[str, Any] | None = None
    max_tokens: int | None = Field(None, ge=1)
    max_completion_tokens: int | None = Field(None, ge=1)
    seed: int | None = None
    logprobs: bool | None = None
    top_logprobs: int | None = Field(None, ge=0, le=20)
    user: str | None = None
    tools: list[ToolDef] | None = None
    tool_choice: ToolChoice | None = None
    response_format: dict[str, Any] | None = None
    ext: Ext | None = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def sampling_options(self) -> SamplingOptions:
        return SamplingOptions(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            frequency_penalty=self.frequency_penalty,
            presence_penalty=self.presence_penalty,
            seed=self.seed,
            n=self.n or 1,
            use_greedy=bool(self.ext and self.ext.greed_sampling),
            top_logprobs=(self.top_logprobs or 0) if self.logprobs else 0,
            logit_bias=(
                {int(k): float(v) for k, v in self.logit_bias.items()}
                if self.logit_bias else None
            ),
        )

    def stop_conditions(self) -> StopConditions:
        return StopConditions(
            max_tokens=self.max_completion_tokens or self.max_tokens,
            stop=self.stop_list(),
            ignore_eos=bool(self.ext and self.ext.ignore_eos),
        )


class CompletionRequest(_SamplingValidators):
    model_config = ConfigDict(extra="allow")
    model: str
    prompt: Union[str, list[str], list[int], list[list[int]]]
    suffix: str | None = None
    max_tokens: int | None = Field(16, ge=1)
    stream: bool = False
    stream_options: dict[str, Any] | None = None
    logprobs: int | None = Field(None, ge=0, le=5)
    echo: bool | None = None
    seed: int | None = None
    user: str | None = None
    ext: Ext | None = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def sampling_options(self) -> SamplingOptions:
        return SamplingOptions(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            frequency_penalty=self.frequency_penalty,
            presence_penalty=self.presence_penalty,
            seed=self.seed,
            n=self.n or 1,
            use_greedy=bool(self.ext and self.ext.greed_sampling),
            top_logprobs=self.logprobs or 0,
            logit_bias=(
                {int(k): float(v) for k, v in self.logit_bias.items()}
                if self.logit_bias else None
            ),
        )

    def stop_conditions(self) -> StopConditions:
        return StopConditions(
            max_tokens=self.max_tokens,
            stop=self.stop_list(),
            ignore_eos=bool(self.ext and self.ext.ignore_eos),
        )


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: Union[str, list[str], list[int], list[list[int]]]
    encoding_format: Literal["float", "base64"] = "float"
    user: str | None = None


# ---------------------------------------------------------------------------
# Responses
# ---------------------------------------------------------------------------


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ChatDelta(BaseModel):
    role: str | None = None
    content: str | None = None
    tool_calls: list[dict[str, Any]] | None = None


class ChatChunkChoice(BaseModel):
    index: int = 0
    delta: ChatDelta
    finish_reason: str | None = None
    logprobs: Any | None = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[ChatChunkChoice] = Field(default_factory=list)
    usage: Usage | None = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: str | None = None
    logprobs: Any | None = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[ChatChoice] = Field(default_factory=list)
    usage: Usage | None = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: str | None = None
    logprobs: Any | None = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: list[CompletionChoice] = Field(default_factory=list)
    usage: Usage | None = None


class EmbeddingData(BaseModel):
    object: Literal["embedding"] = "embedding"
    index: int
    # list of floats, or a base64-packed float32 buffer (encoding_format=base64)
    embedding: list[float] | str


class EmbeddingResponse(BaseModel):
    object: Literal["list"] = "list"
    data: list[EmbeddingData] = Field(default_factory=list)
    model: str = ""
    usage: Usage | None = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "dynamo-tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: list[ModelInfo] = Field(default_factory=list)


def new_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def finish_reason_to_openai(reason: FinishReason | None) -> str | None:
    if reason is None:
        return None
    return {
        FinishReason.STOP: "stop",
        FinishReason.LENGTH: "length",
        FinishReason.CANCELLED: "stop",
        FinishReason.ERROR: "stop",
        FinishReason.CONTENT_FILTER: "content_filter",
    }[reason]
