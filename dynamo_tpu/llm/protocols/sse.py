"""Server-Sent Events codec (reference: lib/llm/src/protocols/codec.rs).

Encodes ``Annotated`` items into SSE wire lines and decodes them back —
data lines carry JSON payloads, ``event:``/``comment`` lines carry
annotations, and the stream terminates with ``data: [DONE]``.
"""

from __future__ import annotations

import json
from typing import AsyncIterator

DONE = "[DONE]"


def encode_event(data: str | None = None, event: str | None = None, comments: list[str] | None = None) -> str:
    lines: list[str] = []
    for comment in comments or []:
        lines.append(f": {comment}")
    if event is not None:
        lines.append(f"event: {event}")
    if data is not None:
        lines.append(f"data: {data}")
    return "\n".join(lines) + "\n\n"


def encode_done() -> str:
    return encode_event(data=DONE)


class SseDecoder:
    """Incremental SSE parser: feed bytes, get (event, data, comments) tuples."""

    def __init__(self) -> None:
        self._buffer = ""

    def feed(self, chunk: bytes | str) -> list[dict]:
        if isinstance(chunk, bytes):
            chunk = chunk.decode("utf-8")
        self._buffer += chunk
        events: list[dict] = []
        while "\n\n" in self._buffer:
            raw, _, self._buffer = self._buffer.partition("\n\n")
            event: dict = {"event": None, "data": None, "comments": []}
            data_lines: list[str] = []
            for line in raw.split("\n"):
                if line.startswith(": "):
                    event["comments"].append(line[2:])
                elif line.startswith(":"):
                    event["comments"].append(line[1:])
                elif line.startswith("event:"):
                    event["event"] = line[len("event:"):].strip()
                elif line.startswith("data:"):
                    data_lines.append(line[len("data:"):].strip())
            if data_lines:
                event["data"] = "\n".join(data_lines)
            if event["data"] is not None or event["event"] is not None or event["comments"]:
                events.append(event)
        return events


async def sse_json_stream(byte_stream: AsyncIterator[bytes]) -> AsyncIterator[dict]:
    """Decode an SSE byte stream into parsed-JSON data events (stops at DONE)."""
    decoder = SseDecoder()
    async for chunk in byte_stream:
        for event in decoder.feed(chunk):
            if event["data"] == DONE:
                return
            if event["data"] is not None:
                yield json.loads(event["data"])
