"""Mocker: a simulated engine for infrastructure testing at scale.

Mirrors the reference's mocker (lib/llm/src/mocker/: watermark+budget
scheduler, KV manager with prefix bookkeeping, cost model "prefill quadratic,
decode ∝ active blocks", scheduler.rs:31-33) without any device work: it
reuses the real BlockAllocator + Scheduler host logic, sleeps according to
the cost model, emits deterministic tokens, and publishes the same KV/load
events as the real engine — so routers, disagg and planners can be exercised
with hundreds of simulated workers on one CPU.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import AsyncIterator, Callable

from dynamo_tpu.engine.kv_manager import BlockAllocator, KvEvent
from dynamo_tpu.engine.scheduler import Scheduler
from dynamo_tpu.observability.flight import FlightRecorder
from dynamo_tpu.engine.sequence import Sequence, SeqStatus
from dynamo_tpu.llm.protocols.common import (
    Annotated,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.engine import Context, ResponseStream
from dynamo_tpu.runtime.resume import ack_item, apply_resume
from dynamo_tpu.utils.tasks import spawn_logged


@dataclass
class MockerConfig:
    num_blocks: int = 512
    block_size: int = 16
    max_batch_size: int = 16
    speedup: float = 100.0               # simulation time compression
    # cost model (seconds at speedup=1)
    prefill_linear_s: float = 0.0002     # per prompt token
    prefill_quadratic_s: float = 2e-8    # per token^2 (attention)
    decode_base_s: float = 0.01          # per decode iteration
    decode_per_block_s: float = 0.00005  # per active KV block
    # disagg pool membership reported through stats()/ForwardPassMetrics
    # ("prefill"/"decode", "" = serves both)
    role: str = ""
    # emulated inbound KV-transfer latency (seconds at speedup=1) added per
    # prefill — how multi-slice soaks make a worker behind a DCN hop pay
    # for the prefix bytes shipped to it (scenarios/fleet.py sets it from
    # FleetSpec.link_delay_s by the worker's link class)
    transfer_delay_s: float = 0.0
    # rolling window (wall seconds) for the goodput/prefill-rate/MFU stats
    util_window_s: float = 2.0


class MockerEngine:
    """Wire-compatible with JaxLlmEngine (PreprocessedRequest dicts in,
    Annotated[LLMEngineOutput] wire dicts out) but fully simulated."""

    def __init__(
        self,
        config: MockerConfig | None = None,
        *,
        event_sink: Callable[[KvEvent], None] | None = None,
    ):
        self.config = config or MockerConfig()
        self._event_sink = event_sink
        self.allocator = BlockAllocator(
            self.config.num_blocks, self.config.block_size, event_sink=self._sink
        )
        self.scheduler = Scheduler(self.allocator, max_batch_size=self.config.max_batch_size)
        self._task: asyncio.Task | None = None
        self._wake = asyncio.Event()
        self._iterations = 0
        # utilization accounting: per-iteration samples of (wall_t, tokens
        # emitted, prefill tokens served, simulated busy seconds) feed the
        # rolling goodput/prefill-rate/MFU window; totals are cumulative
        self._util: deque = deque()
        self._t0: float | None = None
        self._tokens_emitted_total = 0
        self._prefill_tokens_total = 0
        self._decode_tokens_total = 0
        # perf flight recorder: same ring + dump triggers as the real engine
        # so soak fleets produce replayable load traces (DYN_FLIGHT=0 = off)
        self.flight = FlightRecorder(source="mocker")
        self._flight_preemptions = 0

    def _sink(self, event: KvEvent) -> None:
        if self._event_sink is not None:
            self._event_sink(event)

    def start(self) -> None:
        if self._task is None:
            self._t0 = time.monotonic()
            self._task = spawn_logged(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def _util_rates(self) -> tuple[float, float, float]:
        """(goodput tok/s, prefill tok/s, mfu fraction) over the rolling
        window — wall-clock rates, so at speedup=S they read S× the
        simulated-time rates (same compression as the cost model)."""
        cfg = self.config
        now = time.monotonic()
        horizon = now - cfg.util_window_s
        while self._util and self._util[0][0] < horizon:
            self._util.popleft()
        elapsed = cfg.util_window_s
        if self._t0 is not None:
            elapsed = min(elapsed, max(now - self._t0, 1e-3))
        tokens = sum(s[1] for s in self._util)
        prefill = sum(s[2] for s in self._util)
        busy_sim = sum(s[3] for s in self._util)
        # busy fraction in SIMULATED time: sim busy seconds / sim elapsed
        # seconds — the mocker's stand-in for model FLOPs utilization
        mfu = min(busy_sim / (elapsed * cfg.speedup), 1.0)
        return tokens / elapsed, prefill / elapsed, mfu

    def stats(self) -> dict:
        goodput, prefill_rate, mfu = self._util_rates()
        return {
            "role": self.config.role,
            "kv_active_blocks": self.allocator.used_blocks,
            "kv_total_blocks": self.allocator.num_blocks,
            "gpu_cache_usage_perc": self.allocator.usage,
            "num_requests_waiting": self.scheduler.num_waiting,
            "num_requests_running": self.scheduler.num_running,
            "request_total_slots": self.config.max_batch_size,
            "iterations_total": self._iterations,
            # same step-telemetry names as the real engine so mocker fleets
            # light up the dyn_worker occupancy/preemption gauges too
            "batch_occupancy_perc": (
                self.scheduler.num_running / max(self.config.max_batch_size, 1)
            ),
            "num_preemptions_total": self.scheduler.preemptions_total,
            # utilization accounting (same names as observability.perf) so
            # planner capacity sampling and the soak's MFU/goodput floors
            # work against mocker fleets
            "goodput_tokens_per_second": goodput,
            "prefill_tokens_per_second": prefill_rate,
            "mfu_perc": mfu,
            "tokens_emitted_total": self._tokens_emitted_total,
            "prefill_tokens_total": self._prefill_tokens_total,
            "decode_tokens_total": self._decode_tokens_total,
            **self.flight.stats(),
        }

    async def generate(self, request: Context[dict]) -> ResponseStream[dict]:
        # continuation-mode resume: a re-dispatched stream carries the
        # accepted tokens in ``resume_from`` — extend the prompt with them,
        # shrink the remaining budget, and ack as the FIRST item so the
        # dispatcher's dedupe cursor knows not to drop anything.  The
        # (last+1) mod 1000 "model" makes continuation exactly equal to a
        # replay's tail, which is what resume-aware real engines promise.
        wire, accepted = apply_resume(request.data)
        pre = PreprocessedRequest.from_wire(wire)
        ctx = request.ctx
        out_q: asyncio.Queue = asyncio.Queue()
        if accepted:
            out_q.put_nowait(ack_item(accepted))
        seq = Sequence(seq_id=ctx.id or uuid.uuid4().hex, request=pre)

        def emit(tokens: list[int], finish: FinishReason | None) -> None:
            wire = Annotated.from_data(
                LLMEngineOutput(token_ids=tokens, finish_reason=finish)
            ).to_wire(LLMEngineOutput.to_wire)
            out_q.put_nowait(wire)
            if finish is not None:
                out_q.put_nowait(None)

        seq.emit = emit
        self.scheduler.add(seq)
        self._wake.set()

        watcher = spawn_logged(self._watch_cancel(ctx, seq))

        async def gen() -> AsyncIterator[dict]:
            try:
                while True:
                    item = await out_q.get()
                    if item is None:
                        return
                    yield item
            finally:
                watcher.cancel()

        return ResponseStream(gen(), ctx)

    async def _watch_cancel(self, ctx, seq: Sequence) -> None:
        await ctx.stopped()
        if seq.status != SeqStatus.FINISHED:
            self.scheduler.abort(seq)
            seq.status = SeqStatus.FINISHED
            if seq.emit:
                seq.emit([], FinishReason.CANCELLED)

    async def _loop(self) -> None:
        cfg = self.config
        while True:
            if not self.scheduler.has_work():
                self._wake.clear()
                await self._wake.wait()
            decision = self.scheduler.schedule()
            cost = 0.0
            prefill_tokens = 0
            for seq in decision.prefills:
                # prefix-cache hits only pay for the NEW tokens, attending
                # over the full context (reference: mocker/scheduler.rs:31
                # "prefill compute = (cached_tokens + new_tokens) *
                # new_tokens") — this is the mechanism a KV-aware router
                # exploits, so the simulation must credit it
                cached = seq.cached_tokens
                new = max(seq.context_len - cached, 0)
                prefill_tokens += new
                cost += (
                    cfg.prefill_linear_s * new
                    + cfg.prefill_quadratic_s * (cached + new) * new
                    + cfg.transfer_delay_s
                )
            decodes = [s for s in self.scheduler.running if s.status == SeqStatus.RUNNING]
            if decodes:
                cost += cfg.decode_base_s + cfg.decode_per_block_s * self.allocator.used_blocks
            # simulate the compute FIRST, then emit: a request's first token
            # must arrive after its prefill cost (TTFT is the whole point of
            # the simulation — emitting before sleeping made every TTFT ~0
            # regardless of prompt length or cache state)
            self._iterations += 1
            await asyncio.sleep(cost / cfg.speedup)
            emitted_before = self._tokens_emitted_total
            for seq in decision.prefills:
                if seq.status == SeqStatus.FINISHED:  # cancelled mid-sleep
                    continue
                self.allocator.publish_stored(seq.seq_id, seq.all_token_ids)
                self._emit_next(seq)
            decode_before = self._tokens_emitted_total
            for seq in decodes:
                # FINISHED (cancelled mid-sleep) or PREEMPTED (victimized by
                # an EARLIER seq's ensure_slot in this very loop — its blocks
                # are gone, touching the allocator would KeyError): skip; a
                # preempted seq is already queued for recompute.
                if seq.status != SeqStatus.RUNNING:
                    continue
                slot = self.scheduler.ensure_slot(seq)
                if slot is None:
                    self.scheduler.preempt(seq)
                    continue
                self._emit_next(seq)
            self._prefill_tokens_total += prefill_tokens
            self._decode_tokens_total += self._tokens_emitted_total - decode_before
            self._util.append((
                time.monotonic(),
                self._tokens_emitted_total - emitted_before,
                prefill_tokens,
                cost,
            ))
            if self.flight.enabled:
                preempted = self.scheduler.preemptions_total
                if preempted > self._flight_preemptions:
                    self.flight.record_event(
                        "preemption",
                        count=preempted - self._flight_preemptions,
                        total=preempted,
                    )
                    self._flight_preemptions = preempted
                goodput, prefill_rate, mfu = self._util_rates()
                self.flight.record_step(
                    iteration=self._iterations,
                    num_running=self.scheduler.num_running,
                    num_waiting=self.scheduler.num_waiting,
                    kv_usage=self.allocator.usage,
                    prefill_tokens=prefill_tokens,
                    decode_tokens=self._tokens_emitted_total - decode_before,
                    emitted_tokens=self._tokens_emitted_total - emitted_before,
                    step_duration_s=cost / cfg.speedup,
                    mfu=mfu,
                    goodput_tok_s=goodput,
                )

    def _emit_next(self, seq: Sequence) -> None:
        # deterministic "generation": next token = (last + 1) mod 1000
        token = (seq.all_token_ids[-1] + 1) % 1000 if seq.all_token_ids else 0
        seq.output_ids.append(token)
        self._tokens_emitted_total += 1
        finish = seq.hit_stop(token)
        if seq.emit:
            seq.emit([token], finish)
        if finish is not None:
            self.scheduler.finish(seq)
        elif seq.context_len % self.config.block_size == 0:
            self.allocator.publish_stored(seq.seq_id, seq.all_token_ids)
