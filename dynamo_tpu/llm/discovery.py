"""Frontend-side model discovery.

Workers register their models under ``dynamo://models/`` with their liveness
lease; the frontend's ModelWatcher builds/tears down the per-model client
pipeline (preprocessor → backend → remote push router) as entries come and go
(reference: lib/llm/src/discovery/{model_entry.rs,watcher.rs},
model_manager.rs; registration lib/bindings register_llm).
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from pathlib import Path

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.http.service import ModelManager
from dynamo_tpu.llm.kv_router.router import KvPushRouter, KvRouter
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import ChatPreprocessor, CompletionPreprocessor
from dynamo_tpu.llm.tokenizer import HfTokenizer
from dynamo_tpu.runtime.client import PushRouter, RemoteEngine, RouterMode
from dynamo_tpu.runtime.component import ROOT_PATH
from dynamo_tpu.runtime.controlplane.interface import WatchEventType
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged
from dynamo_tpu.utils import knobs

logger = get_logger("llm.discovery")

MODELS_PREFIX = f"{ROOT_PATH}models/"


@dataclass
class ModelEntry:
    name: str
    namespace: str
    component: str
    endpoint: str
    instance_id: int
    model_types: list[str] = field(default_factory=lambda: ["chat", "completions"])
    mdc: dict | None = None

    def key(self) -> str:
        return (
            f"{MODELS_PREFIX}{self.name}/"
            f"{self.namespace}.{self.component}.{self.endpoint}/{self.instance_id:016x}"
        )

    def endpoint_path(self) -> str:
        return f"{self.namespace}.{self.component}.{self.endpoint}"

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "ModelEntry":
        return cls(**json.loads(data))


async def register_llm(
    service,  # EndpointService returned by Endpoint.serve
    mdc: ModelDeploymentCard,
    *,
    model_types: list[str] | None = None,
) -> ModelEntry:
    """Register a served endpoint as an LLM model (worker side)."""
    instance = service.instance
    entry = ModelEntry(
        name=mdc.name,
        namespace=instance.namespace,
        component=instance.component,
        endpoint=instance.endpoint,
        instance_id=instance.instance_id,
        model_types=model_types or ["chat", "completions"],
        mdc=json.loads(mdc.to_json()),
    )
    # artifacts first, registration second: a frontend that sees the entry
    # can always complete the fetch (reference: transports/nats.rs:123-211)
    try:
        n = await mdc.publish_artifacts(service.runtime.plane.bus)
        logger.info("published %d artifact(s) for %s", n, mdc.name)
    except Exception:  # noqa: BLE001 — same-filesystem serving still works
        logger.exception("artifact publish failed for %s", mdc.name)
    # registered under the instance's lease: model entries vanish with the worker
    await service.runtime.plane.kv.put(entry.key(), entry.to_json(), service._lease.id)
    logger.info("registered model %s on %s", mdc.name, instance.subject)
    return entry


class ModelWatcher:
    """Watches model registrations and maintains the ModelManager."""

    def __init__(
        self,
        runtime: DistributedRuntime,
        manager: ModelManager,
        *,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
        prefetch_hinter=None,
    ):
        self.runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        # prefetch/frontend.py FrontendHinter: each model pipeline registers
        # its tokenizer + chat template here so arrival hints hash exactly
        # the token stream the preprocessor will produce
        self.prefetch_hinter = prefetch_hinter
        self._watch = None
        self._task: asyncio.Task | None = None
        # model name -> set of entry keys backing it
        self._backing: dict[str, set[str]] = {}
        self._entries: dict[str, ModelEntry] = {}  # entry key -> entry
        self._pipelines: dict[str, dict] = {}  # model name -> {"router": ..., "kv": ...}
        # fleet topology plane: one card watcher shared by every KV router
        # this frontend builds (DYN_TOPO; started alongside model discovery)
        self._topology_watcher = None

    @property
    def topology(self):
        """The live TopologyMap, or None when the plane is off."""
        return (
            self._topology_watcher.map
            if self._topology_watcher is not None else None
        )

    async def start(self) -> None:
        if knobs.get("DYN_TOPO"):
            from dynamo_tpu.topology import TopologyWatcher

            self._topology_watcher = TopologyWatcher(self.runtime)
            await self._topology_watcher.start()
        self._watch = self.runtime.plane.kv.watch_prefix(MODELS_PREFIX)
        self._task = spawn_logged(self._loop())

    async def stop(self) -> None:
        if self._watch is not None:
            self._watch.cancel()
        if self._task is not None:
            self._task.cancel()
        if self._topology_watcher is not None:
            await self._topology_watcher.stop()
            self._topology_watcher = None
        for state in self._pipelines.values():
            kv_router = state.get("kv")
            if kv_router is not None:
                await kv_router.stop()
            router = state.get("router")
            if router is not None and router.migrations is not None:
                await router.migrations.stop()

    async def _loop(self) -> None:
        try:
            async for event in self._watch:
                try:
                    entry = ModelEntry.from_json(event.entry.value)
                except Exception:  # noqa: BLE001
                    continue
                if event.type == WatchEventType.PUT:
                    await self._handle_put(event.entry.key, entry)
                else:
                    await self._handle_delete(event.entry.key, entry)
        except ConnectionError as exc:
            # keep serving the pipelines we already built on a lost watch
            logger.warning("model discovery watch lost: %s", exc)

    async def clear_kv_blocks(self) -> list[str]:
        """Broadcast a KV-cache flush to every worker component backing a
        registered model; each worker's ClearKvListener flushes its engine
        and re-announces the cleared state to the indexers (reference:
        lib/llm/src/http/service/clear_kv_blocks.rs)."""
        from dynamo_tpu.llm.kv_router.protocols import CLEAR_KV_SUBJECT

        subjects = sorted(
            {
                self.runtime.namespace(e.namespace)
                .component(e.component)
                .event_subject(CLEAR_KV_SUBJECT)
                for e in self._entries.values()
            }
        )
        bus = self.runtime.plane.bus
        for subject in subjects:
            await bus.publish(subject, b"{}")
        return subjects

    async def _handle_put(self, key: str, entry: ModelEntry) -> None:
        backing = self._backing.setdefault(entry.name, set())
        backing.add(key)
        self._entries[key] = entry
        if entry.name in self._pipelines:
            return
        try:
            await self._build_pipeline(entry)
        except Exception:  # noqa: BLE001
            logger.exception("failed to build pipeline for model %s", entry.name)
            backing.discard(key)

    async def _handle_delete(self, key: str, entry: ModelEntry) -> None:
        self._entries.pop(key, None)
        backing = self._backing.get(entry.name)
        if backing is None:
            return
        backing.discard(key)
        if backing:
            return
        # last instance gone: tear down
        self._backing.pop(entry.name, None)
        state = self._pipelines.pop(entry.name, None)
        if state is not None and state.get("kv") is not None:
            await state["kv"].stop()
        if state is not None and state.get("router") is not None:
            router = state["router"]
            if router.migrations is not None:
                await router.migrations.stop()
        if self.prefetch_hinter is not None:
            self.prefetch_hinter.remove_model(entry.name)
        self.manager.remove_model(entry.name)
        logger.info("model %s removed (no instances left)", entry.name)

    async def _build_pipeline(self, entry: ModelEntry) -> None:
        mdc = ModelDeploymentCard(**entry.mdc)
        if not mdc.path or not (
            Path(mdc.path, "tokenizer.json").exists()
            or Path(mdc.path, "tokenizer.model").exists()
        ):
            # no shared filesystem with the worker: pull the tokenizer/config
            # artifacts the worker published to the object store
            fetched = await mdc.fetch_artifacts(self.runtime.plane.bus)
            if fetched is None:
                raise FileNotFoundError(f"model artifacts not found at {mdc.path}")
            logger.info("fetched artifacts for %s into %s", entry.name, fetched)
        tokenizer = HfTokenizer.from_model_dir(mdc.path)

        ns = self.runtime.namespace(entry.namespace)
        endpoint = ns.component(entry.component).endpoint(entry.endpoint)
        push_router = await PushRouter.from_endpoint(endpoint, self.router_mode)
        if push_router.migrations is not None:
            # live-migration control verb (dynctl migrate) + topology-priced
            # destination picking; the lambda keeps reading the watcher's
            # map as probes refine it
            if self._topology_watcher is not None:
                push_router.migrations.attach_topology(lambda: self.topology)
            await push_router.migrations.serve_ctl(self.runtime.plane.bus)

        kv_router = None
        if self.router_mode == RouterMode.KV:
            kv_router = KvRouter(endpoint.component, block_size=mdc.kv_block_size)
            if self._topology_watcher is not None:
                kv_router.attach_topology(self._topology_watcher.map)
            await kv_router.start()
            engine: object = KvPushRouter(push_router, kv_router)
        else:
            engine = RemoteEngine(push_router)

        backend = Backend(tokenizer)
        if "chat" in entry.model_types:
            self.manager.add_chat_model(
                entry.name, ChatPreprocessor(mdc, tokenizer).wrap(backend.wrap(engine))
            )
        if "completions" in entry.model_types:
            self.manager.add_completion_model(
                entry.name, CompletionPreprocessor(mdc, tokenizer).wrap(backend.wrap(engine))
            )
        if self.prefetch_hinter is not None:
            self._register_hinter(entry, mdc, tokenizer, endpoint)
        self._pipelines[entry.name] = {"router": push_router, "kv": kv_router}
        logger.info(
            "model %s wired to %s (mode=%s)", entry.name, entry.endpoint_path(), self.router_mode.value
        )

    def _register_hinter(self, entry: ModelEntry, mdc, tokenizer, endpoint) -> None:
        """Wire this model into the frontend's prefetch hinter: tokenize a
        validated request the same way the preprocessor will (chat template
        for chat, raw prompt for completions) and publish the hash chain on
        the component's hint subject."""
        from dynamo_tpu.llm.preprocessor import PromptFormatter
        from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
        from dynamo_tpu.prefetch.hints import PREFETCH_HINT_SUBJECT

        import os

        formatter = PromptFormatter(mdc.chat_template)
        bus = self.runtime.plane.bus
        subject = endpoint.component.event_subject(PREFETCH_HINT_SUBJECT)
        # hint tokenization runs synchronously on the frontend event loop
        # (it must leave before dispatch starts): cap the rendered text so
        # a long-context prompt costs bounded work.  The hint then covers
        # the prompt's leading blocks — the part offload tiers hold the
        # longest — and truncation can at worst invalidate the final
        # partial block's hash
        max_chars = knobs.get("DYN_PREFETCH_HINT_CHARS")

        def tokenize(request_model) -> list[int] | None:
            if isinstance(request_model, ChatCompletionRequest):
                text = formatter.render(request_model)
            elif isinstance(getattr(request_model, "prompt", None), str):
                text = request_model.prompt
            else:
                return None
            return tokenizer.encode(text[:max_chars])

        async def publish(payload: bytes) -> None:
            await bus.publish(subject, payload)

        self.prefetch_hinter.register_model(
            entry.name, tokenize, mdc.kv_block_size, publish
        )
