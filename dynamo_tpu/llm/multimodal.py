"""Multimodal front door: ``image_url`` content parts → encoder input.

The reference's multimodal processor pulls ``image_url`` out of the chat
request and drives encode→prefill→decode (reference:
examples/multimodal/components/processor.py:107-217,
encode_worker.py:61).  Here the OpenAI frontend does the I/O half —
extract the URL, fetch/decode the bytes, normalize to a float RGB array —
and attaches it to the preprocessed request; the engine half
(examples/multimodal/pipeline.py ``MultimodalEngine``) encodes it with
the ViT (in-process or on a separate encode-worker component) and splices
the patch embeddings ahead of the text tokens.

Split rationale (TPU-first): image I/O and PNG/JPEG decode are host work
that belongs at the frontend; geometry (resize to the ViT's square input)
belongs next to the encoder that knows its ``image_size`` — so the wire
carries decoded [H, W, 3] float32 in [0, 1], unresized.

Supported URL forms:
- ``data:image/...;base64,<payload>`` — decoded inline (no network);
- ``http://`` / ``https://`` — fetched with a size cap and timeout.
Anything else (``file://``, relative paths) is rejected: a frontend that
dereferences arbitrary schemes is an SSRF/file-exfiltration hole.
"""

from __future__ import annotations

import base64
import binascii
import io

import numpy as np

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils import knobs

logger = get_logger("llm.multimodal")

MAX_IMAGE_BYTES = 16 * 1024 * 1024
# decompressed-size guard: a 16MB PNG can decode to ~90M pixels (~1GB as
# float32) — cap pixels independently of the compressed byte cap
MAX_IMAGE_PIXELS = 4096 * 4096
FETCH_TIMEOUT_S = 30.0
# http(s) image fetch resolves to private/loopback/link-local addresses
# only when explicitly allowed (SSRF guard); data: URLs need no opt-in
ALLOW_PRIVATE_ENV = "DYN_ALLOW_PRIVATE_IMAGE_URLS"


def extract_image_url(request) -> str | None:
    """The request's single image URL, or None for text-only requests.

    One image per request in v1 (the LLM engine splices one patch-embedding
    block ahead of the text); two or more is a loud error, not a silent
    drop of all but one."""
    urls: list[str] = []
    for message in request.messages:
        content = message.content
        if not isinstance(content, list):
            continue
        for part in content:
            if part.type != "image_url":
                continue
            url = (part.image_url or {}).get("url")
            if not url:
                raise ValueError("image_url content part carries no url")
            urls.append(url)
    if len(urls) > 1:
        raise ValueError(
            f"request carries {len(urls)} images; one image per request is "
            "supported"
        )
    return urls[0] if urls else None


def decode_image_bytes(data: bytes) -> np.ndarray:
    """Image bytes → RGB float32 [H, W, 3] in [0, 1]."""
    from PIL import Image, UnidentifiedImageError

    try:
        with Image.open(io.BytesIO(data)) as img:
            # size is known from the header BEFORE pixel decode: reject
            # decompression bombs without paying for the decode
            w, h = img.size
            if w * h > MAX_IMAGE_PIXELS:
                raise ValueError(
                    f"image is {w}x{h} = {w * h} pixels; limit is "
                    f"{MAX_IMAGE_PIXELS}"
                )
            rgb = img.convert("RGB")
            arr = np.asarray(rgb, np.float32) / 255.0
    except UnidentifiedImageError:
        raise ValueError("image bytes are not a decodable image") from None
    if arr.ndim != 3:  # pragma: no cover — convert("RGB") guarantees 3 channels
        raise ValueError(f"decoded image has shape {arr.shape}, want [H, W, 3]")
    return arr


def encode_image_wire(arr: np.ndarray) -> dict:
    """Compact wire form for a decoded image: raw bytes + shape, base64.

    ``ndarray.tolist()`` turns a 2MP photo into ~200MB of Python float
    objects; this stays within ~4/3 of the raw buffer size."""
    arr = np.ascontiguousarray(arr, np.float32)
    return {
        "shape": list(arr.shape),
        "dtype": "float32",
        "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def decode_image_wire(obj) -> np.ndarray:
    """Inverse of :func:`encode_image_wire`; also accepts a plain nested
    list / array (direct API callers attaching ``image`` themselves)."""
    if isinstance(obj, dict):
        data = base64.b64decode(obj["b64"])
        arr = np.frombuffer(data, dtype=obj.get("dtype", "float32"))
        return arr.reshape(obj["shape"]).astype(np.float32, copy=False)
    return np.asarray(obj, np.float32)


def _decode_data_url(url: str) -> bytes:
    header, _, payload = url.partition(",")
    if not payload:
        raise ValueError("data: URL has no payload")
    if ";base64" not in header:
        raise ValueError("data: image URLs must be base64-encoded")
    try:
        data = base64.b64decode(payload, validate=True)
    except (binascii.Error, ValueError):
        raise ValueError("data: URL payload is not valid base64") from None
    if len(data) > MAX_IMAGE_BYTES:
        raise ValueError(
            f"image exceeds {MAX_IMAGE_BYTES // (1024 * 1024)}MB limit"
        )
    return data


def _reject_private_host(url: str) -> None:
    """SSRF guard: refuse http(s) URLs that resolve to loopback, private,
    link-local, or otherwise non-global addresses (169.254.169.254 metadata
    endpoints, the deployment's own control plane, ...) unless the operator
    opted in via DYN_ALLOW_PRIVATE_IMAGE_URLS=1.

    Depth note: the check resolves once here and aiohttp resolves again at
    connect time (a DNS-rebinding TOCTOU); closing that fully needs a
    pinned-IP connector, which the opt-in env documents as the boundary."""
    import os
    import socket
    import urllib.parse
    from ipaddress import ip_address

    if knobs.get(ALLOW_PRIVATE_ENV):
        return
    host = urllib.parse.urlsplit(url).hostname
    if not host:
        raise ValueError(f"image URL {url!r} has no host")
    try:
        infos = socket.getaddrinfo(host, None)
    except socket.gaierror:
        raise ValueError(f"image host {host!r} does not resolve") from None
    for info in infos:
        addr = ip_address(info[4][0])
        if not addr.is_global:
            raise ValueError(
                f"image host {host!r} resolves to non-global address "
                f"{addr} (set {ALLOW_PRIVATE_ENV}=1 to allow internal "
                "fetches)"
            )


async def _fetch_http(url: str) -> bytes:
    import aiohttp

    _reject_private_host(url)
    timeout = aiohttp.ClientTimeout(total=FETCH_TIMEOUT_S)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        async with session.get(url) as resp:
            if resp.status != 200:
                raise ValueError(f"image fetch failed: HTTP {resp.status} for {url}")
            data = await resp.content.read(MAX_IMAGE_BYTES + 1)
            if len(data) > MAX_IMAGE_BYTES:
                raise ValueError(
                    f"image exceeds {MAX_IMAGE_BYTES // (1024 * 1024)}MB limit"
                )
            return data


async def resolve_image(url: str) -> np.ndarray:
    """URL (data:/http:/https:) → decoded RGB float32 [H, W, 3] in [0, 1]."""
    if url.startswith("data:"):
        data = _decode_data_url(url)
    elif url.startswith(("http://", "https://")):
        data = await _fetch_http(url)
    else:
        scheme = url.split(":", 1)[0] if ":" in url else "<none>"
        raise ValueError(
            f"unsupported image URL scheme {scheme!r}: use data: (base64) "
            "or http(s)"
        )
    return decode_image_bytes(data)
