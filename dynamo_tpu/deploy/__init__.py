"""Deployment plane: CRD-shaped graph/component specs, the reconciling
operator that translates them into Kubernetes manifests, and the graph
artifact registry (api-store).

Reference: deploy/cloud/operator (Go k8s operator, CRDs
DynamoGraphDeployment/DynamoComponentDeployment,
deploy/cloud/operator/api/v1alpha1/*_types.go:33-141) and
deploy/cloud/api-store.  Re-expressed in Python: the reconcile loop is pure
manifest translation + diffing, testable without a cluster via FakeKube.
"""

from dynamo_tpu.deploy.crds import (
    ComponentSpec,
    DynamoComponentDeployment,
    DynamoGraphDeployment,
)
from dynamo_tpu.deploy.operator import FakeKube, GraphReconciler, render_component_manifests

__all__ = [
    "ComponentSpec",
    "DynamoComponentDeployment",
    "DynamoGraphDeployment",
    "FakeKube",
    "GraphReconciler",
    "render_component_manifests",
]
