"""Graph artifact registry ("api-store"; reference: deploy/cloud/api-store —
the FastAPI registry for built graph packages).

Stores named+versioned graph artifacts (the deployment manifest plus an
optional opaque archive) on disk, with an aiohttp JSON API:

    POST   /api/v1/graphs                  {"name","version","manifest",...}
    GET    /api/v1/graphs                  → [{name, versions: [...]}]
    GET    /api/v1/graphs/{name}           → version list
    GET    /api/v1/graphs/{name}/{version} → stored record
    DELETE /api/v1/graphs/{name}/{version}
"""

from __future__ import annotations

import json
import re
import time
from pathlib import Path

from aiohttp import web

from dynamo_tpu.utils.logging import get_logger

logger = get_logger("deploy.api_store")

_NAME_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9._-]{0,127}$")


class ArtifactStore:
    """Disk-backed registry: one JSON record per (name, version)."""

    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, name: str, version: str) -> Path:
        for part in (name, version):
            if not _NAME_RE.match(part):
                raise ValueError(f"invalid name/version {part!r}")
        return self.root / name / f"{version}.json"

    def put(self, name: str, version: str, record: dict) -> dict:
        path = self._path(name, version)
        if path.exists():
            raise FileExistsError(f"{name}:{version} already exists")
        path.parent.mkdir(parents=True, exist_ok=True)
        stored = {**record, "name": name, "version": version, "created_at": time.time()}
        path.write_text(json.dumps(stored, indent=2, sort_keys=True))
        return stored

    def get(self, name: str, version: str) -> dict:
        path = self._path(name, version)
        if not path.exists():
            raise FileNotFoundError(f"{name}:{version}")
        return json.loads(path.read_text())

    def versions(self, name: str) -> list[str]:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid name {name!r}")
        d = self.root / name
        return sorted(p.stem for p in d.glob("*.json")) if d.exists() else []

    def names(self) -> list[str]:
        return sorted(p.name for p in self.root.iterdir() if p.is_dir())

    def delete(self, name: str, version: str) -> bool:
        path = self._path(name, version)
        if not path.exists():
            return False
        path.unlink()
        return True


def make_app(store: ArtifactStore) -> web.Application:
    async def create(request: web.Request) -> web.Response:
        body = await request.json()
        name, version = body.get("name"), body.get("version")
        if not name or not version:
            return web.json_response({"error": "name and version required"}, status=400)
        record = {k: v for k, v in body.items() if k not in ("name", "version")}
        try:
            stored = store.put(name, version, record)
        except FileExistsError:
            return web.json_response({"error": "already exists"}, status=409)
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        logger.info("stored graph artifact %s:%s", name, version)
        return web.json_response(stored, status=201)

    async def list_all(request: web.Request) -> web.Response:
        return web.json_response(
            [{"name": n, "versions": store.versions(n)} for n in store.names()]
        )

    async def list_versions(request: web.Request) -> web.Response:
        name = request.match_info["name"]
        try:
            versions = store.versions(name)
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        if not versions:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"name": name, "versions": versions})

    async def get_one(request: web.Request) -> web.Response:
        try:
            record = store.get(request.match_info["name"], request.match_info["version"])
        except (FileNotFoundError, ValueError):
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(record)

    async def delete_one(request: web.Request) -> web.Response:
        try:
            removed = store.delete(request.match_info["name"], request.match_info["version"])
        except ValueError:
            removed = False
        if not removed:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"deleted": True})

    app = web.Application()
    app.router.add_post("/api/v1/graphs", create)
    app.router.add_get("/api/v1/graphs", list_all)
    app.router.add_get("/api/v1/graphs/{name}", list_versions)
    app.router.add_get("/api/v1/graphs/{name}/{version}", get_one)
    app.router.add_delete("/api/v1/graphs/{name}/{version}", delete_one)
    return app


def main() -> int:
    import argparse
    import asyncio

    from dynamo_tpu.utils.logging import configure_logging

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root", default="./graph-store")
    parser.add_argument("--port", type=int, default=8085)
    args = parser.parse_args()
    configure_logging()

    async def amain() -> None:
        runner = web.AppRunner(make_app(ArtifactStore(args.root)))
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", args.port)
        await site.start()
        logger.info("api-store on :%d root=%s", args.port, args.root)
        await asyncio.Event().wait()

    asyncio.run(amain())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
