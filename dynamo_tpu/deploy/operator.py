"""Reconciling operator: graph CR → component CRs → Kubernetes manifests
(reference: deploy/cloud/operator/internal/controller/
dynamographdeployment_controller.go:263 fan-out and
dynamocomponentdeployment_controller.go:2025 manifest construction, plus the
graph translation in internal/dynamo/graph.go:556).

The reconcile loop is substrate-agnostic: it computes desired objects and
applies the diff through a :class:`KubeClient`.  ``FakeKube`` keeps objects
in memory (tests / dry-run); ``KubectlClient`` shells out to ``kubectl``
when a real cluster is reachable.
"""

from __future__ import annotations

import asyncio
import json
import time
from abc import ABC, abstractmethod

from dynamo_tpu.deploy.crds import (
    API_VERSION,
    ComponentSpec,
    DynamoComponentDeployment,
    DynamoGraphDeployment,
)
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("deploy.operator")

MANAGED_BY = "dynamo-tpu-operator"


def _obj_key(manifest: dict) -> tuple[str, str, str]:
    meta = manifest.get("metadata", {})
    return (manifest.get("kind", ""), meta.get("namespace", "default"), meta.get("name", ""))


class KubeClient(ABC):
    """Minimal apply/list/get/delete/status/watch surface the operator needs."""

    @abstractmethod
    async def apply(self, manifest: dict) -> None: ...

    @abstractmethod
    async def list(self, kind: str, namespace: str, labels: dict[str, str]) -> list[dict]: ...

    @abstractmethod
    async def delete(self, kind: str, namespace: str, name: str) -> None: ...

    @abstractmethod
    async def get(self, kind: str, namespace: str, name: str) -> dict | None: ...

    @abstractmethod
    async def list_all(self, kind: str) -> list[dict]:
        """List a kind across ALL namespaces (resync source)."""

    @abstractmethod
    async def update_status(
        self, kind: str, namespace: str, name: str, status: dict
    ) -> None:
        """Write the object's .status subresource (no spec churn)."""

    @abstractmethod
    def watch(self, kind: str):
        """Async iterator of ``(event_type, manifest)``; event_type in
        ADDED/MODIFIED/DELETED.  May be level-based (poll) per client."""


class FakeKube(KubeClient):
    """In-memory object store (the envtest analog for our reconciler tests)
    with a broadcast watch channel."""

    def __init__(self) -> None:
        self.objects: dict[tuple[str, str, str], dict] = {}
        self.applies = 0
        self.deletes = 0
        self._watchers: list[tuple[str, asyncio.Queue]] = []

    def _notify(self, event: str, manifest: dict) -> None:
        kind = manifest.get("kind", "")
        for want_kind, q in self._watchers:
            if want_kind == kind:
                q.put_nowait((event, json.loads(json.dumps(manifest))))

    async def apply(self, manifest: dict) -> None:
        key = _obj_key(manifest)
        existing = self.objects.get(key)
        stored = json.loads(json.dumps(manifest))
        if existing is not None:  # preserve status across spec applies
            stored.setdefault("status", existing.get("status", {}))
        if existing == stored:
            return  # no-op apply: no event (k8s bumps resourceVersion only on change)
        self.objects[key] = stored
        self.applies += 1
        self._notify("MODIFIED" if existing is not None else "ADDED", stored)

    async def list(self, kind: str, namespace: str, labels: dict[str, str]) -> list[dict]:
        out = []
        for (k, ns, _), obj in self.objects.items():
            if k != kind or ns != namespace:
                continue
            obj_labels = obj.get("metadata", {}).get("labels", {})
            if all(obj_labels.get(lk) == lv for lk, lv in labels.items()):
                out.append(json.loads(json.dumps(obj)))
        return out

    async def delete(self, kind: str, namespace: str, name: str) -> None:
        obj = self.objects.pop((kind, namespace, name), None)
        self.deletes += 1
        if obj is not None:
            self._notify("DELETED", obj)

    async def get(self, kind: str, namespace: str, name: str) -> dict | None:
        # return a COPY, like the API server serializes a response: a caller
        # mutating the result in place must not silently edit the store
        # (that made apply's no-op detection eat a planner scale decision)
        obj = self.objects.get((kind, namespace, name))
        return None if obj is None else json.loads(json.dumps(obj))

    async def list_all(self, kind: str) -> list[dict]:
        return [
            json.loads(json.dumps(obj))
            for (k, _, _), obj in self.objects.items()
            if k == kind
        ]

    async def update_status(
        self, kind: str, namespace: str, name: str, status: dict
    ) -> None:
        obj = self.objects.get((kind, namespace, name))
        if obj is None:
            return
        obj["status"] = json.loads(json.dumps(status))

    def set_deployment_ready(self, namespace: str, name: str, ready: int) -> None:
        """Test hook: simulate the kubelet bringing replicas up."""
        obj = self.objects.get(("Deployment", namespace, name))
        if obj is not None:
            obj.setdefault("status", {})["readyReplicas"] = ready
            self._notify("MODIFIED", obj)

    async def watch(self, kind: str):
        q: asyncio.Queue = asyncio.Queue()
        self._watchers.append((kind, q))
        try:
            # replay current state first (a watch always starts with a list)
            for (k, _, _), obj in list(self.objects.items()):
                if k == kind:
                    yield ("ADDED", json.loads(json.dumps(obj)))
            while True:
                yield await q.get()
        finally:
            self._watchers.remove((kind, q))


class KubectlClient(KubeClient):
    """Shells out to kubectl; used only when a cluster is configured."""

    async def _run(self, *args: str, stdin: bytes | None = None) -> bytes:
        proc = await asyncio.create_subprocess_exec(
            "kubectl", *args,
            stdin=asyncio.subprocess.PIPE if stdin is not None else None,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate(stdin)
        if proc.returncode != 0:
            raise RuntimeError(f"kubectl {' '.join(args)} failed: {err.decode()}")
        return out

    async def apply(self, manifest: dict) -> None:
        await self._run("apply", "-f", "-", stdin=json.dumps(manifest).encode())

    async def list(self, kind: str, namespace: str, labels: dict[str, str]) -> list[dict]:
        selector = ",".join(f"{k}={v}" for k, v in labels.items())
        out = await self._run(
            "get", kind, "-n", namespace, "-l", selector, "-o", "json"
        )
        return json.loads(out).get("items", [])

    async def delete(self, kind: str, namespace: str, name: str) -> None:
        await self._run("delete", kind, name, "-n", namespace, "--ignore-not-found")

    async def get(self, kind: str, namespace: str, name: str) -> dict | None:
        try:
            out = await self._run("get", kind, name, "-n", namespace, "-o", "json")
        except RuntimeError:
            return None
        return json.loads(out)

    async def list_all(self, kind: str) -> list[dict]:
        out = await self._run("get", kind, "-A", "-o", "json")
        return json.loads(out).get("items", [])

    async def update_status(
        self, kind: str, namespace: str, name: str, status: dict
    ) -> None:
        patch = json.dumps({"status": status})
        await self._run(
            "patch", kind, name, "-n", namespace, "--subresource=status",
            "--type=merge", "-p", patch,
        )

    async def watch(self, kind: str, poll_s: float = 10.0):
        """Level-based watch: periodic list-diff (no kubectl watch parsing
        machinery; the operator's reconcile is level-triggered anyway)."""
        known: dict[tuple[str, str, str], dict] = {}  # key -> last full object
        while True:
            out = await self._run("get", kind, "-A", "-o", "json")
            seen: dict[tuple[str, str, str], dict] = {}
            for obj in json.loads(out).get("items", []):
                seen[_obj_key(obj)] = obj
            for key, obj in seen.items():
                prev = known.get(key)
                fingerprint = obj.get("metadata", {}).get("resourceVersion", "")
                if prev is None:
                    yield ("ADDED", obj)
                elif prev.get("metadata", {}).get("resourceVersion", "") != fingerprint:
                    yield ("MODIFIED", obj)
                known[key] = obj
            for key in [k for k in known if k not in seen]:
                # yield the last-seen object so consumers keep its labels
                yield ("DELETED", known.pop(key))
            await asyncio.sleep(poll_s)


# ---------------------------------------------------------------- rendering


def _component_labels(cd: DynamoComponentDeployment) -> dict[str, str]:
    return {
        "app.kubernetes.io/managed-by": MANAGED_BY,
        "dynamo.tpu/graph": cd.graph,
        "dynamo.tpu/service": cd.service_name,
        "dynamo.tpu/component-type": cd.spec.component_type,
    }


def render_component_manifests(cd: DynamoComponentDeployment) -> list[dict]:
    """One component CR → Deployment (+ Service when a port is exposed,
    + ConfigMap when it carries config).  The reference emits the same trio
    per component (dynamocomponentdeployment_controller.go)."""
    spec: ComponentSpec = cd.spec
    labels = _component_labels(cd)
    manifests: list[dict] = []

    env = [{"name": k, "value": v} for k, v in sorted(spec.envs.items())]
    volume_mounts = []
    volumes = []
    if spec.config:
        cm_name = f"{cd.name}-config"
        manifests.append(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": cm_name, "namespace": cd.namespace, "labels": labels},
                "data": {"service.yaml": json.dumps(spec.config, indent=2, sort_keys=True)},
            }
        )
        volumes.append({"name": "service-config", "configMap": {"name": cm_name}})
        volume_mounts.append({"name": "service-config", "mountPath": "/etc/dynamo"})
        env.append({"name": "DYN_SERVICE_CONFIG", "value": "/etc/dynamo/service.yaml"})

    resources: dict = {
        "requests": {"cpu": spec.resources.cpu, "memory": spec.resources.memory},
        "limits": {"memory": spec.resources.memory},
    }
    node_selector: dict[str, str] = {}
    if spec.resources.tpu > 0:
        # TPU chips are scheduled via the google.com/tpu extended resource +
        # accelerator/topology node selectors (GKE convention)
        resources["requests"]["google.com/tpu"] = str(spec.resources.tpu)
        resources["limits"]["google.com/tpu"] = str(spec.resources.tpu)
        if spec.resources.tpu_topology:
            node_selector["cloud.google.com/gke-tpu-topology"] = spec.resources.tpu_topology

    container = {
        "name": cd.service_name,
        "image": spec.image,
        "env": env,
        "resources": resources,
    }
    if spec.command:
        container["command"] = list(spec.command)
    if spec.args:
        container["args"] = list(spec.args)
    if volume_mounts:
        container["volumeMounts"] = volume_mounts
    if spec.port:
        container["ports"] = [{"containerPort": spec.port}]
        container["readinessProbe"] = {
            "httpGet": {"path": "/health", "port": spec.port},
            "periodSeconds": 5,
        }

    pod_spec: dict = {"containers": [container]}
    if volumes:
        pod_spec["volumes"] = volumes
    if node_selector:
        pod_spec["nodeSelector"] = node_selector

    manifests.append(
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": cd.name, "namespace": cd.namespace, "labels": labels},
            "spec": {
                "replicas": spec.replicas,
                "selector": {"matchLabels": {"dynamo.tpu/service": cd.service_name,
                                             "dynamo.tpu/graph": cd.graph}},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": pod_spec,
                },
            },
        }
    )

    if spec.port:
        manifests.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {"name": cd.name, "namespace": cd.namespace, "labels": labels},
                "spec": {
                    "selector": {"dynamo.tpu/service": cd.service_name,
                                 "dynamo.tpu/graph": cd.graph},
                    "ports": [{"port": spec.port, "targetPort": spec.port}],
                },
            }
        )
    if spec.ingress and spec.port:
        rule = {
            "host": spec.ingress.get("host", ""),
            "http": {
                "paths": [
                    {
                        "path": spec.ingress.get("path", "/"),
                        "pathType": spec.ingress.get("pathType", "Prefix"),
                        "backend": {
                            "service": {
                                "name": cd.name,
                                "port": {"number": spec.port},
                            }
                        },
                    }
                ]
            },
        }
        ingress_spec: dict = {"rules": [rule]}
        if spec.ingress.get("className"):
            ingress_spec["ingressClassName"] = spec.ingress["className"]
        manifests.append(
            {
                "apiVersion": "networking.k8s.io/v1",
                "kind": "Ingress",
                "metadata": {"name": cd.name, "namespace": cd.namespace, "labels": labels},
                "spec": ingress_spec,
            }
        )
    return manifests


# ---------------------------------------------------------------- reconciler


class GraphReconciler:
    """Level-triggered reconcile of graph CRs: fan out component CRs, render
    their manifests, apply, and prune children whose service disappeared."""

    def __init__(self, kube: KubeClient):
        self.kube = kube

    @staticmethod
    def component_name(graph: DynamoGraphDeployment, service_name: str) -> str:
        return f"{graph.name}-{service_name}"

    def fan_out(self, graph: DynamoGraphDeployment) -> list[DynamoComponentDeployment]:
        graph.validate()
        return [
            DynamoComponentDeployment(
                name=self.component_name(graph, svc_name),
                namespace=graph.namespace,
                graph=graph.name,
                service_name=svc_name,
                spec=spec,
                graph_uid=graph.uid,
            )
            for svc_name, spec in graph.services.items()
        ]

    async def reconcile(self, graph: DynamoGraphDeployment) -> dict:
        """Returns a status summary {applied: n, pruned: n, components: [...]}."""
        children = self.fan_out(graph)
        desired: set[tuple[str, str]] = set()  # (kind, name) of every applied object
        component_names = set()
        applied = 0
        for child in children:
            component_names.add(child.name)
            desired.add((DynamoComponentDeployment.kind, child.name))
            await self.kube.apply(child.to_manifest())
            for manifest in render_component_manifests(child):
                desired.add((manifest["kind"], manifest["metadata"]["name"]))
                await self.kube.apply(manifest)
                applied += 1

        # Prune by exact object identity: anything graph-labelled that this
        # pass did not render is stale — including a ConfigMap/Service left
        # behind when a service dropped its config/port.
        pruned = 0
        graph_selector = {"dynamo.tpu/graph": graph.name}
        for kind in (DynamoComponentDeployment.kind, "Deployment", "Service", "ConfigMap", "Ingress"):
            for obj in await self.kube.list(kind, graph.namespace, graph_selector):
                name = obj["metadata"]["name"]
                if (kind, name) not in desired:
                    await self.kube.delete(kind, graph.namespace, name)
                    pruned += 1

        status = {
            "applied": applied,
            "pruned": pruned,
            "components": sorted(component_names),
        }
        logger.info("reconciled graph %s: %s", graph.name, status)
        return status

    async def teardown(self, graph: DynamoGraphDeployment) -> int:
        """Delete everything owned by the graph (graph CR deletion path,
        incl. the reference's etcd cleanup analog)."""
        removed = 0
        selector = {"dynamo.tpu/graph": graph.name}
        for kind in (DynamoComponentDeployment.kind, "Deployment", "Service", "ConfigMap", "Ingress"):
            for obj in await self.kube.list(kind, graph.namespace, selector):
                await self.kube.delete(kind, graph.namespace, obj["metadata"]["name"])
                removed += 1
        return removed


# ---------------------------------------------------------------- operator


def _condition(ctype: str, status: bool, reason: str, message: str) -> dict:
    return {
        "type": ctype,
        "status": "True" if status else "False",
        "reason": reason,
        "message": message,
        "lastTransitionTime": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def merge_conditions(existing: list[dict], new: list[dict]) -> list[dict]:
    """Controller-runtime semantics: lastTransitionTime changes only when
    the condition's status flips."""
    by_type = {c["type"]: c for c in existing}
    out = []
    for cond in new:
        prev = by_type.get(cond["type"])
        if prev is not None and prev["status"] == cond["status"]:
            cond = {**cond, "lastTransitionTime": prev["lastTransitionTime"]}
        out.append(cond)
    return out


class Operator:
    """Watch-driven controller for DynamoGraphDeployment CRs (reference:
    dynamographdeployment_controller.go — watch → workqueue → level-triggered
    reconcile with status conditions, requeue-with-backoff on error, and a
    periodic resync).

    Deleted graphs tear down their children; live graphs reconcile and get a
    ``status`` with observedGeneration + Progressing/Ready conditions, Ready
    flipping once every child Deployment reports its replicas ready.
    """

    def __init__(self, kube: KubeClient, *, resync_s: float = 30.0, backoff_s: float = 0.5):
        self.kube = kube
        self.reconciler = GraphReconciler(kube)
        self.resync_s = resync_s
        self.backoff_s = backoff_s
        self.reconciles = 0
        self.errors = 0
        self._queue: asyncio.Queue = asyncio.Queue()
        self._tasks: list[asyncio.Task] = []
        self._requeues: set[asyncio.Task] = set()
        self._failures: dict[tuple[str, str], int] = {}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._tasks = [
            spawn_logged(self._watch_loop(DynamoGraphDeployment.kind)),
            # child Deployment changes (readiness) feed back into status
            spawn_logged(self._watch_loop("Deployment")),
            spawn_logged(self._resync_loop()),
            spawn_logged(self._worker()),
        ]

    async def stop(self) -> None:
        for t in [*self._tasks, *self._requeues]:
            t.cancel()
        for t in [*self._tasks, *self._requeues]:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks = []
        self._requeues.clear()

    # -- event sources -----------------------------------------------------
    async def _watch_loop(self, kind: str) -> None:
        while True:
            try:
                async for event, manifest in self.kube.watch(kind):
                    meta = manifest.get("metadata", {})
                    ns = meta.get("namespace", "default")
                    if kind == DynamoGraphDeployment.kind:
                        self._queue.put_nowait((event, ns, meta.get("name", "")))
                    else:
                        # map child → owning graph via its labels
                        graph = meta.get("labels", {}).get("dynamo.tpu/graph")
                        if graph:
                            self._queue.put_nowait(("CHILD", ns, graph))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — watch dropped: back off, re-list
                logger.exception("watch for %s lost; restarting", kind)
                await asyncio.sleep(1.0)

    async def _resync_loop(self) -> None:
        while True:
            await asyncio.sleep(self.resync_s)
            for obj in await self._all_graphs():
                meta = obj.get("metadata", {})
                self._queue.put_nowait(
                    ("RESYNC", meta.get("namespace", "default"), meta.get("name", ""))
                )

    async def _all_graphs(self) -> list[dict]:
        try:
            return await self.kube.list_all(DynamoGraphDeployment.kind)
        except Exception:  # noqa: BLE001
            return []

    # -- work queue --------------------------------------------------------
    async def _worker(self) -> None:
        while True:
            event, ns, name = await self._queue.get()
            key = (ns, name)
            try:
                if event == "DELETED":
                    # teardown selects children by label; no spec needed
                    graph = DynamoGraphDeployment(name=name, namespace=ns)
                    removed = await self.reconciler.teardown(graph)
                    logger.info("graph %s deleted: removed %d children", name, removed)
                else:
                    await self._reconcile_one(ns, name)
                self._failures.pop(key, None)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — requeue with backoff
                self.errors += 1
                n = self._failures[key] = self._failures.get(key, 0) + 1
                delay = min(self.backoff_s * (2 ** (n - 1)), 30.0)
                logger.exception("reconcile %s/%s failed (attempt %d); requeue in %.1fs", ns, name, n, delay)

                async def requeue(ev=event, ns_=ns, nm=name, d=delay) -> None:
                    await asyncio.sleep(d)
                    self._queue.put_nowait((ev, ns_, nm))

                task = asyncio.ensure_future(requeue())
                self._requeues.add(task)
                task.add_done_callback(self._requeues.discard)

    async def _reconcile_one(self, ns: str, name: str) -> None:
        manifest = await self.kube.get(DynamoGraphDeployment.kind, ns, name)
        if manifest is None:
            return  # deleted since enqueue
        graph = DynamoGraphDeployment.from_manifest(manifest)
        summary = await self.reconciler.reconcile(graph)
        self.reconciles += 1

        # readiness: every child Deployment reports its replicas ready
        ready_parts, total_parts = 0, 0
        for obj in await self.kube.list(
            "Deployment", ns, {"dynamo.tpu/graph": graph.name}
        ):
            total_parts += 1
            want = obj.get("spec", {}).get("replicas", 1)
            have = obj.get("status", {}).get("readyReplicas", 0)
            if have >= want:
                ready_parts += 1
        ready = total_parts > 0 and ready_parts == total_parts
        new_conditions = [
            _condition(
                "Progressing", not ready,
                "Reconciling" if not ready else "Stable",
                f"{ready_parts}/{total_parts} deployments ready",
            ),
            _condition(
                "Ready", ready,
                "AllComponentsReady" if ready else "ComponentsPending",
                f"{ready_parts}/{total_parts} deployments ready",
            ),
        ]
        prev = (manifest.get("status") or {}).get("conditions", [])
        status = {
            "observedGeneration": manifest.get("metadata", {}).get("generation", 0),
            "conditions": merge_conditions(prev, new_conditions),
            "components": summary["components"],
        }
        await self.kube.update_status(DynamoGraphDeployment.kind, ns, name, status)
