"""Reconciling operator: graph CR → component CRs → Kubernetes manifests
(reference: deploy/cloud/operator/internal/controller/
dynamographdeployment_controller.go:263 fan-out and
dynamocomponentdeployment_controller.go:2025 manifest construction, plus the
graph translation in internal/dynamo/graph.go:556).

The reconcile loop is substrate-agnostic: it computes desired objects and
applies the diff through a :class:`KubeClient`.  ``FakeKube`` keeps objects
in memory (tests / dry-run); ``KubectlClient`` shells out to ``kubectl``
when a real cluster is reachable.
"""

from __future__ import annotations

import asyncio
import json
from abc import ABC, abstractmethod

from dynamo_tpu.deploy.crds import (
    API_VERSION,
    ComponentSpec,
    DynamoComponentDeployment,
    DynamoGraphDeployment,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("deploy.operator")

MANAGED_BY = "dynamo-tpu-operator"


def _obj_key(manifest: dict) -> tuple[str, str, str]:
    meta = manifest.get("metadata", {})
    return (manifest.get("kind", ""), meta.get("namespace", "default"), meta.get("name", ""))


class KubeClient(ABC):
    """Minimal apply/list/delete surface the reconciler needs."""

    @abstractmethod
    async def apply(self, manifest: dict) -> None: ...

    @abstractmethod
    async def list(self, kind: str, namespace: str, labels: dict[str, str]) -> list[dict]: ...

    @abstractmethod
    async def delete(self, kind: str, namespace: str, name: str) -> None: ...


class FakeKube(KubeClient):
    """In-memory object store (the envtest analog for our reconciler tests)."""

    def __init__(self) -> None:
        self.objects: dict[tuple[str, str, str], dict] = {}
        self.applies = 0
        self.deletes = 0

    async def apply(self, manifest: dict) -> None:
        self.objects[_obj_key(manifest)] = json.loads(json.dumps(manifest))
        self.applies += 1

    async def list(self, kind: str, namespace: str, labels: dict[str, str]) -> list[dict]:
        out = []
        for (k, ns, _), obj in self.objects.items():
            if k != kind or ns != namespace:
                continue
            obj_labels = obj.get("metadata", {}).get("labels", {})
            if all(obj_labels.get(lk) == lv for lk, lv in labels.items()):
                out.append(obj)
        return out

    async def delete(self, kind: str, namespace: str, name: str) -> None:
        self.objects.pop((kind, namespace, name), None)
        self.deletes += 1


class KubectlClient(KubeClient):
    """Shells out to kubectl; used only when a cluster is configured."""

    async def _run(self, *args: str, stdin: bytes | None = None) -> bytes:
        proc = await asyncio.create_subprocess_exec(
            "kubectl", *args,
            stdin=asyncio.subprocess.PIPE if stdin is not None else None,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate(stdin)
        if proc.returncode != 0:
            raise RuntimeError(f"kubectl {' '.join(args)} failed: {err.decode()}")
        return out

    async def apply(self, manifest: dict) -> None:
        await self._run("apply", "-f", "-", stdin=json.dumps(manifest).encode())

    async def list(self, kind: str, namespace: str, labels: dict[str, str]) -> list[dict]:
        selector = ",".join(f"{k}={v}" for k, v in labels.items())
        out = await self._run(
            "get", kind, "-n", namespace, "-l", selector, "-o", "json"
        )
        return json.loads(out).get("items", [])

    async def delete(self, kind: str, namespace: str, name: str) -> None:
        await self._run("delete", kind, name, "-n", namespace, "--ignore-not-found")


# ---------------------------------------------------------------- rendering


def _component_labels(cd: DynamoComponentDeployment) -> dict[str, str]:
    return {
        "app.kubernetes.io/managed-by": MANAGED_BY,
        "dynamo.tpu/graph": cd.graph,
        "dynamo.tpu/service": cd.service_name,
        "dynamo.tpu/component-type": cd.spec.component_type,
    }


def render_component_manifests(cd: DynamoComponentDeployment) -> list[dict]:
    """One component CR → Deployment (+ Service when a port is exposed,
    + ConfigMap when it carries config).  The reference emits the same trio
    per component (dynamocomponentdeployment_controller.go)."""
    spec: ComponentSpec = cd.spec
    labels = _component_labels(cd)
    manifests: list[dict] = []

    env = [{"name": k, "value": v} for k, v in sorted(spec.envs.items())]
    volume_mounts = []
    volumes = []
    if spec.config:
        cm_name = f"{cd.name}-config"
        manifests.append(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": cm_name, "namespace": cd.namespace, "labels": labels},
                "data": {"service.yaml": json.dumps(spec.config, indent=2, sort_keys=True)},
            }
        )
        volumes.append({"name": "service-config", "configMap": {"name": cm_name}})
        volume_mounts.append({"name": "service-config", "mountPath": "/etc/dynamo"})
        env.append({"name": "DYN_SERVICE_CONFIG", "value": "/etc/dynamo/service.yaml"})

    resources: dict = {
        "requests": {"cpu": spec.resources.cpu, "memory": spec.resources.memory},
        "limits": {"memory": spec.resources.memory},
    }
    node_selector: dict[str, str] = {}
    if spec.resources.tpu > 0:
        # TPU chips are scheduled via the google.com/tpu extended resource +
        # accelerator/topology node selectors (GKE convention)
        resources["requests"]["google.com/tpu"] = str(spec.resources.tpu)
        resources["limits"]["google.com/tpu"] = str(spec.resources.tpu)
        if spec.resources.tpu_topology:
            node_selector["cloud.google.com/gke-tpu-topology"] = spec.resources.tpu_topology

    container = {
        "name": cd.service_name,
        "image": spec.image,
        "env": env,
        "resources": resources,
    }
    if spec.command:
        container["command"] = list(spec.command)
    if spec.args:
        container["args"] = list(spec.args)
    if volume_mounts:
        container["volumeMounts"] = volume_mounts
    if spec.port:
        container["ports"] = [{"containerPort": spec.port}]
        container["readinessProbe"] = {
            "httpGet": {"path": "/health", "port": spec.port},
            "periodSeconds": 5,
        }

    pod_spec: dict = {"containers": [container]}
    if volumes:
        pod_spec["volumes"] = volumes
    if node_selector:
        pod_spec["nodeSelector"] = node_selector

    manifests.append(
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": cd.name, "namespace": cd.namespace, "labels": labels},
            "spec": {
                "replicas": spec.replicas,
                "selector": {"matchLabels": {"dynamo.tpu/service": cd.service_name,
                                             "dynamo.tpu/graph": cd.graph}},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": pod_spec,
                },
            },
        }
    )

    if spec.port:
        manifests.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {"name": cd.name, "namespace": cd.namespace, "labels": labels},
                "spec": {
                    "selector": {"dynamo.tpu/service": cd.service_name,
                                 "dynamo.tpu/graph": cd.graph},
                    "ports": [{"port": spec.port, "targetPort": spec.port}],
                },
            }
        )
    return manifests


# ---------------------------------------------------------------- reconciler


class GraphReconciler:
    """Level-triggered reconcile of graph CRs: fan out component CRs, render
    their manifests, apply, and prune children whose service disappeared."""

    def __init__(self, kube: KubeClient):
        self.kube = kube

    @staticmethod
    def component_name(graph: DynamoGraphDeployment, service_name: str) -> str:
        return f"{graph.name}-{service_name}"

    def fan_out(self, graph: DynamoGraphDeployment) -> list[DynamoComponentDeployment]:
        graph.validate()
        return [
            DynamoComponentDeployment(
                name=self.component_name(graph, svc_name),
                namespace=graph.namespace,
                graph=graph.name,
                service_name=svc_name,
                spec=spec,
                graph_uid=graph.uid,
            )
            for svc_name, spec in graph.services.items()
        ]

    async def reconcile(self, graph: DynamoGraphDeployment) -> dict:
        """Returns a status summary {applied: n, pruned: n, components: [...]}."""
        children = self.fan_out(graph)
        desired: set[tuple[str, str]] = set()  # (kind, name) of every applied object
        component_names = set()
        applied = 0
        for child in children:
            component_names.add(child.name)
            desired.add((DynamoComponentDeployment.kind, child.name))
            await self.kube.apply(child.to_manifest())
            for manifest in render_component_manifests(child):
                desired.add((manifest["kind"], manifest["metadata"]["name"]))
                await self.kube.apply(manifest)
                applied += 1

        # Prune by exact object identity: anything graph-labelled that this
        # pass did not render is stale — including a ConfigMap/Service left
        # behind when a service dropped its config/port.
        pruned = 0
        graph_selector = {"dynamo.tpu/graph": graph.name}
        for kind in (DynamoComponentDeployment.kind, "Deployment", "Service", "ConfigMap"):
            for obj in await self.kube.list(kind, graph.namespace, graph_selector):
                name = obj["metadata"]["name"]
                if (kind, name) not in desired:
                    await self.kube.delete(kind, graph.namespace, name)
                    pruned += 1

        status = {
            "applied": applied,
            "pruned": pruned,
            "components": sorted(component_names),
        }
        logger.info("reconciled graph %s: %s", graph.name, status)
        return status

    async def teardown(self, graph: DynamoGraphDeployment) -> int:
        """Delete everything owned by the graph (graph CR deletion path,
        incl. the reference's etcd cleanup analog)."""
        removed = 0
        selector = {"dynamo.tpu/graph": graph.name}
        for kind in (DynamoComponentDeployment.kind, "Deployment", "Service", "ConfigMap"):
            for obj in await self.kube.list(kind, graph.namespace, selector):
                await self.kube.delete(kind, graph.namespace, obj["metadata"]["name"])
                removed += 1
        return removed
