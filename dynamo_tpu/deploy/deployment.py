"""Deployment plane glue: SDK graph → api-store artifact → operator CR.

The reference ships a ``dynamo build`` / ``dynamo deploy`` pair
(reference: deploy/sdk/src/dynamo/sdk/cli/deployment.py — Typer CLI over a
DeploymentManager that stores artifacts and creates deployments); here the
same path is three composable functions plus ``cli/deployctl.py``:

- :func:`build_graph_manifest` — walk an SDK entry service's dependency
  closure (sdk/graph.py) and render a ``DynamoGraphDeployment`` manifest:
  one ComponentSpec per service, each running ``dynamo_tpu.sdk.runner``
  exactly like local subprocess serving does (sdk/graph.py
  ``to_process_specs``), with replicas/resources from the @service config.
- :func:`push_artifact` / :func:`fetch_artifact` — versioned records in
  the api-store (deploy/api_store.py).
- :func:`deploy_artifact` — apply the stored manifest as a graph CR
  through a :class:`deploy.operator.KubeClient`; the running operator's
  watch reconciles it into component CRs and Deployments.
"""

from __future__ import annotations

from dynamo_tpu.deploy.crds import (
    ComponentSpec,
    DynamoGraphDeployment,
    Resources,
)
from dynamo_tpu.sdk.graph import ServiceConfig, dependency_closure, resolve_entry
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("deploy.deployment")

__all__ = [
    "build_graph_manifest", "push_artifact", "fetch_artifact",
    "deploy_artifact", "resolve_entry",
]


def build_graph_manifest(
    entry: type | str,
    *,
    name: str | None = None,
    namespace: str = "default",
    image: str = "dynamo-tpu:latest",
    control_plane: str = "dynctl:2379",
) -> dict:
    """Render an SDK service graph into a DynamoGraphDeployment manifest."""
    cls = resolve_entry(entry) if isinstance(entry, str) else entry
    services: dict[str, ComponentSpec] = {}
    for svc_cls in dependency_closure(cls):
        config: ServiceConfig = svc_cls._dyn_service
        if config.name in services:
            # two classes sharing a service name would silently overwrite
            # each other in the rendered graph — fail at build time instead
            raise ValueError(
                f"duplicate service name {config.name!r} in the dependency "
                f"closure of {cls.__qualname__} (from {svc_cls.__qualname__})"
            )
        services[config.name] = ComponentSpec(
            component_type=config.component_type,
            replicas=config.workers,
            image=image,
            # the same runner invocation local subprocess serving uses —
            # a container with this repo installed serves the service
            command=["python", "-m", "dynamo_tpu.sdk.runner"],
            args=[
                f"{svc_cls.__module__}:{svc_cls.__qualname__}",
                "--control-plane", control_plane,
            ],
            resources=Resources.from_dict(config.resources or None),
            config={"entry": f"{svc_cls.__module__}:{svc_cls.__qualname__}"},
        )
    graph = DynamoGraphDeployment(
        name=name or cls._dyn_service.name,
        namespace=namespace,
        services=services,
    )
    graph.validate()
    return graph.to_manifest()


async def push_artifact(
    api_store_url: str, name: str, version: str, manifest: dict,
    *, description: str = "",
) -> dict:
    """POST a built graph manifest to the api-store as ``name:version``."""
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.post(
            f"{api_store_url.rstrip('/')}/api/v1/graphs",
            json={
                "name": name,
                "version": version,
                "manifest": manifest,
                "description": description,
            },
        ) as resp:
            if resp.status not in (200, 201):
                # a proxy's HTML 502 must not surface as ContentTypeError
                raise RuntimeError(
                    f"api-store rejected artifact ({resp.status}): "
                    f"{(await resp.text())[:300]}"
                )
            return await resp.json()


async def fetch_artifact(api_store_url: str, name: str, version: str) -> dict:
    """GET a stored record; returns the record dict (manifest under
    ``manifest``)."""
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.get(
            f"{api_store_url.rstrip('/')}/api/v1/graphs/{name}/{version}"
        ) as resp:
            if resp.status == 404:
                raise KeyError(f"artifact {name}:{version} not in the api-store")
            resp.raise_for_status()
            return await resp.json()


async def deploy_artifact(
    kube, record: dict, *, namespace: str | None = None
) -> dict:
    """Apply a stored artifact's graph manifest as a CR; the operator's
    watch takes it from there.  Returns the manifest applied."""
    manifest = record.get("manifest") if "manifest" in record else record
    graph = DynamoGraphDeployment.from_manifest(manifest)
    if namespace:
        graph.namespace = namespace
    graph.validate()
    out = graph.to_manifest()
    await kube.apply(out)
    logger.info(
        "deployed graph %s (%d services) to namespace %s",
        graph.name, len(graph.services), graph.namespace,
    )
    return out
