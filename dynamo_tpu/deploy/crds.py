"""CRD-shaped deployment specs (reference:
deploy/cloud/operator/api/v1alpha1/dynamographdeployment_types.go:33-141 and
dynamocomponentdeployment_types.go — a graph CR fans out into one component
CR per service).

Group/version ``dynamo.tpu/v1alpha1``; YAML CRD definitions for a real
cluster live under ``deploy/crds/``.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any

import yaml

GROUP = "dynamo.tpu"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"

COMPONENT_KINDS = ("frontend", "worker", "prefill-worker", "router", "planner", "metrics")


@dataclass
class Resources:
    """Per-replica resource requests. ``tpu`` counts chips; ``tpu_topology``
    (e.g. "2x4") selects the slice shape via node selectors."""

    cpu: str = "1"
    memory: str = "2Gi"
    tpu: int = 0
    tpu_topology: str = ""

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict | None) -> "Resources":
        d = d or {}
        return cls(
            cpu=str(d.get("cpu", "1")),
            memory=str(d.get("memory", "2Gi")),
            tpu=int(d.get("tpu", 0)),
            tpu_topology=str(d.get("tpu_topology", d.get("tpuTopology", ""))),
        )


@dataclass
class ComponentSpec:
    """One service in the graph (reference: operator service spec,
    internal/dynamo/graph.go:556 translation input)."""

    component_type: str = "worker"  # one of COMPONENT_KINDS
    replicas: int = 1
    image: str = "dynamo-tpu:latest"
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    envs: dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    config: dict[str, Any] = field(default_factory=dict)  # service YAML payload
    port: int = 0  # exposed service port (frontend/router/metrics)
    # ingress: {"host": "...", "path": "/", "className": "..."} — renders a
    # networking.k8s.io/v1 Ingress in front of the Service (reference:
    # operator VirtualService/Ingress wiring,
    # deploy/cloud/operator/internal/controller/dynamocomponentdeployment_controller.go)
    ingress: dict[str, Any] = field(default_factory=dict)

    def validate(self, name: str) -> None:
        if self.component_type not in COMPONENT_KINDS:
            raise ValueError(
                f"service {name!r}: unknown componentType {self.component_type!r} "
                f"(expected one of {COMPONENT_KINDS})"
            )
        if self.replicas < 0:
            raise ValueError(f"service {name!r}: negative replicas")

    def to_dict(self) -> dict:
        d = asdict(self)
        d["componentType"] = d.pop("component_type")
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ComponentSpec":
        return cls(
            component_type=d.get("componentType", d.get("component_type", "worker")),
            replicas=int(d.get("replicas", 1)),
            image=d.get("image", "dynamo-tpu:latest"),
            command=list(d.get("command", [])),
            args=list(d.get("args", [])),
            envs=dict(d.get("envs", {})),
            resources=Resources.from_dict(d.get("resources")),
            config=dict(d.get("config", {})),
            port=int(d.get("port", 0)),
            ingress=dict(d.get("ingress", {})),
        )


@dataclass
class DynamoGraphDeployment:
    """The graph CR: a named set of services deployed together."""

    name: str
    namespace: str = "default"
    services: dict[str, ComponentSpec] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    uid: str = ""  # server-assigned metadata.uid (present once applied to a cluster)

    kind = "DynamoGraphDeployment"

    def validate(self) -> None:
        if not self.name:
            raise ValueError("graph deployment needs metadata.name")
        if not self.services:
            raise ValueError(f"graph {self.name!r} has no services")
        for name, svc in self.services.items():
            svc.validate(name)

    def to_manifest(self) -> dict:
        return {
            "apiVersion": API_VERSION,
            "kind": self.kind,
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "labels": self.labels,
            },
            "spec": {"services": {n: s.to_dict() for n, s in self.services.items()}},
        }

    @classmethod
    def from_manifest(cls, manifest: dict) -> "DynamoGraphDeployment":
        if manifest.get("kind") != cls.kind:
            raise ValueError(f"expected kind {cls.kind}, got {manifest.get('kind')!r}")
        meta = manifest.get("metadata", {})
        spec = manifest.get("spec", {})
        obj = cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            services={
                n: ComponentSpec.from_dict(s) for n, s in spec.get("services", {}).items()
            },
            labels=dict(meta.get("labels", {})),
            uid=meta.get("uid", ""),
        )
        obj.validate()
        return obj

    @classmethod
    def from_yaml(cls, text: str) -> "DynamoGraphDeployment":
        return cls.from_manifest(yaml.safe_load(text))


@dataclass
class DynamoComponentDeployment:
    """Child CR: one service of a graph (reference:
    dynamocomponentdeployment_controller.go reconciles these into
    Deployments/Services)."""

    name: str
    namespace: str
    graph: str  # owning DynamoGraphDeployment name
    service_name: str
    spec: ComponentSpec
    graph_uid: str = ""  # owner CR uid, when known (required for a valid ownerReference)

    kind = "DynamoComponentDeployment"

    def to_manifest(self) -> dict:
        metadata: dict = {
            "name": self.name,
            "namespace": self.namespace,
            "labels": {
                "dynamo.tpu/graph": self.graph,
                "dynamo.tpu/service": self.service_name,
                "dynamo.tpu/component-type": self.spec.component_type,
            },
        }
        # The API server rejects ownerReferences without uid, so only emit
        # one when the owning CR's uid is known (garbage collection); the
        # reconciler's label-based prune covers the uid-less case.
        if self.graph_uid:
            metadata["ownerReferences"] = [
                {
                    "apiVersion": API_VERSION,
                    "kind": DynamoGraphDeployment.kind,
                    "name": self.graph,
                    "uid": self.graph_uid,
                    "controller": True,
                }
            ]
        return {
            "apiVersion": API_VERSION,
            "kind": self.kind,
            "metadata": metadata,
            "spec": self.spec.to_dict(),
        }

    @classmethod
    def from_manifest(cls, manifest: dict) -> "DynamoComponentDeployment":
        meta = manifest.get("metadata", {})
        labels = meta.get("labels", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            graph=labels.get("dynamo.tpu/graph", ""),
            service_name=labels.get("dynamo.tpu/service", ""),
            spec=ComponentSpec.from_dict(manifest.get("spec", {})),
        )
