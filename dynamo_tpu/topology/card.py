"""TopologyCard: what each worker publishes about where it sits.

A card is the discovery half of the topology plane: a small, lease-scoped
control-plane entry describing the worker's physical placement — host
fingerprint, JAX process/slice identity, accelerator coords, and the
data-plane address its KV-transfer server listens on.  The aggregator
(:class:`dynamo_tpu.topology.map.TopologyWatcher`) assembles cards into a
live :class:`TopologyMap`; cards vanish with the worker's lease so churn is
observable the same way instance churn is.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket

from dynamo_tpu.runtime.component import ROOT_PATH
from dynamo_tpu.utils import knobs

CARDS_PREFIX = f"{ROOT_PATH}topology/cards/"


@dataclasses.dataclass
class TopologyCard:
    """One worker's placement facts, as published to the control plane."""

    worker_id: int
    host: str = ""
    pid: int = 0
    process_index: int = -1
    slice_label: str = ""
    coords: list = dataclasses.field(default_factory=list)
    transfer_address: str = ""
    role: str = ""

    def key(self) -> str:
        return f"{CARDS_PREFIX}{self.worker_id:016x}"

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes | str) -> "TopologyCard":
        d = json.loads(data)
        # filter unknown keys so newer publishers stay readable by older
        # aggregators (same wire posture as ForwardPassMetrics.from_json)
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


def _jax_identity() -> tuple[int, str, list]:
    """(process_index, slice_label, coords) from JAX when available.

    Guarded import: the topology plane must work on hosts where JAX is
    absent or where touching the backend would initialize accelerators.
    """
    try:  # pragma: no cover - depends on installed jax backend
        import jax

        process_index = int(jax.process_index())
        slice_label = ""
        coords: list = []
        devices = jax.local_devices()
        if devices:
            dev = devices[0]
            slice_index = getattr(dev, "slice_index", None)
            if slice_index is not None:
                slice_label = f"slice{int(slice_index)}"
            dev_coords = getattr(dev, "coords", None)
            if dev_coords is not None:
                coords = [int(c) for c in dev_coords]
        return process_index, slice_label, coords
    except Exception:
        return -1, "", []


def local_card(
    worker_id: int,
    *,
    transfer_address: str = "",
    role: str = "",
    slice_label: str | None = None,
) -> TopologyCard:
    """Build this process's card.

    Slice label precedence: explicit ``slice_label`` argument (benches and
    soaks that emulate several slices in one process) > ``DYN_TOPO_SLICE``
    knob > JAX device ``slice_index`` > empty (classifier falls back to
    host/pid fingerprints).
    """
    process_index, detected_slice, coords = _jax_identity()
    if slice_label is None:
        slice_label = knobs.get("DYN_TOPO_SLICE") or detected_slice
    return TopologyCard(
        worker_id=worker_id,
        host=socket.gethostname(),
        pid=os.getpid(),
        process_index=process_index,
        slice_label=slice_label,
        coords=coords,
        transfer_address=transfer_address,
        role=role,
    )


async def publish_card(service, card: TopologyCard) -> None:
    """Publish ``card`` under the service's registration lease.

    Same idiom as ``register_llm``: a lease-scoped put means the card is
    reaped with the worker, and the aggregator's watch sees a DELETE.
    """
    await service.runtime.plane.kv.put(
        card.key(), card.to_json(), service._lease.id
    )
