"""dyn_topology_* metric families (text exposition helper).

One renderer serves both scrape surfaces: the frontend appends these lines
to its ``/metrics`` body (next to the resilience counters), and the metrics
service mirrors the same families through its prometheus registry.  Every
family is always DECLARED (``# HELP``/``# TYPE``) even with no map attached,
so ``scripts/check_metrics.py`` can assert the surface unconditionally.
"""

from __future__ import annotations

HOP_CLASSES = ("local", "ici", "dcn")

_FAMILIES = (
    ("dyn_topology_nodes", "Workers with a published topology card"),
    ("dyn_topology_links", "Pairwise links in the fleet topology map by hop class"),
    ("dyn_topology_probe_rtt_seconds", "Probe round-trip EWMA by hop class"),
    ("dyn_topology_probe_bandwidth_bps",
     "Measured link bandwidth EWMA by hop class"),
    ("dyn_topology_map_age_seconds",
     "Seconds since the topology map last changed"),
)


def hop_summaries(topo_map) -> dict[str, dict[str, float]]:
    """Per-hop-class link count + mean measured RTT/bandwidth (means over
    the links of that class that actually carry a measurement)."""
    out = {
        hop: {"links": 0.0, "rtt_s": 0.0, "bps": 0.0, "_rtt_n": 0, "_bps_n": 0}
        for hop in HOP_CLASSES
    }
    if topo_map is None:
        return out
    for (a, b), link in getattr(topo_map, "_links", {}).items():
        row = out.get(link.hop)
        if row is None:
            continue
        row["links"] += 1
        if link.rtt_s > 0:
            row["rtt_s"] += link.rtt_s
            row["_rtt_n"] += 1
        if link.measured_bps > 0:
            row["bps"] += link.measured_bps
            row["_bps_n"] += 1
    for row in out.values():
        if row["_rtt_n"]:
            row["rtt_s"] /= row["_rtt_n"]
        if row["_bps_n"]:
            row["bps"] /= row["_bps_n"]
    return out


def render(topo_map=None) -> bytes:
    """Prometheus text lines for the topology families (frontend surface)."""
    lines: list[str] = []
    for name, help_text in _FAMILIES:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} gauge")
        if name == "dyn_topology_nodes":
            n = len(topo_map.nodes) if topo_map is not None else 0
            lines.append(f"{name} {float(n)}")
        elif name == "dyn_topology_map_age_seconds":
            age = topo_map.age_s() if topo_map is not None else 0.0
            lines.append(f"{name} {age:.6f}")
        else:
            summaries = hop_summaries(topo_map)
            key = {
                "dyn_topology_links": "links",
                "dyn_topology_probe_rtt_seconds": "rtt_s",
                "dyn_topology_probe_bandwidth_bps": "bps",
            }[name]
            for hop in HOP_CLASSES:
                lines.append(
                    f'{name}{{hop="{hop}"}} {summaries[hop][key]:.6f}'
                )
    return ("\n".join(lines) + "\n").encode()
