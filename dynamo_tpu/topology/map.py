"""TopologyMap: live fleet network map assembled from TopologyCards.

Nodes are workers (keyed by worker id); links are unordered pairs classified
``local``/``ici``/``dcn`` from card fingerprints, then refined by probe and
transfer measurements (EWMA — priors decay into measurements).

The parity gate for a single-host fleet is :meth:`TopologyMap.informative`:
a map whose every pair classifies ``local`` carries no placement signal, so
consumers ignore it entirely and behave byte-identically to a fleet with no
topology plane.
"""

from __future__ import annotations

import dataclasses
import logging
import time

from dynamo_tpu.llm.kv_router.cost import DEFAULT_HOP, HOP_BANDWIDTH_BPS
from dynamo_tpu.runtime.controlplane.interface import WatchEventType
from dynamo_tpu.topology.card import CARDS_PREFIX, TopologyCard
from dynamo_tpu.utils.tasks import spawn_logged

logger = logging.getLogger(__name__)


def classify_link(a: TopologyCard, b: TopologyCard) -> str:
    """Hop class between two cards from placement fingerprints alone.

    Explicit slice labels win over host fingerprints: an emulated two-slice
    fleet on one laptop must classify cross-slice pairs ``dcn`` even though
    every worker shares a hostname.
    """
    if a.worker_id == b.worker_id:
        return "local"
    if a.slice_label and b.slice_label and a.slice_label != b.slice_label:
        return "dcn"
    if a.host and a.host == b.host and a.pid == b.pid:
        return "local"
    if a.slice_label and a.slice_label == b.slice_label:
        return "ici"
    if a.host and a.host == b.host:
        return "ici"
    return "dcn"


@dataclasses.dataclass
class TopologyLink:
    """Per-pair state: classified hop + measured RTT/bandwidth EWMAs."""

    hop: str = ""
    rtt_s: float = 0.0
    measured_bps: float = 0.0
    probes_total: int = 0

    def bandwidth_bps(self) -> float:
        if self.measured_bps > 0:
            return self.measured_bps
        return HOP_BANDWIDTH_BPS.get(self.hop, HOP_BANDWIDTH_BPS[DEFAULT_HOP])


class TopologyMap:
    """Nodes + pairwise links; the aggregator's single mutable artifact."""

    def __init__(self, *, ewma_alpha: float = 0.25, clock=time.monotonic):
        self.ewma_alpha = ewma_alpha
        self._clock = clock
        self.nodes: dict[int, TopologyCard] = {}
        self._links: dict[tuple[int, int], TopologyLink] = {}
        self._updated_at: float = clock()

    # -- membership ----------------------------------------------------------
    def upsert(self, card: TopologyCard) -> None:
        self.nodes[card.worker_id] = card
        for other_id, other in self.nodes.items():
            if other_id == card.worker_id:
                continue
            link = self._links.setdefault(
                self._pair(card.worker_id, other_id), TopologyLink()
            )
            link.hop = classify_link(card, other)
        self._updated_at = self._clock()

    def remove(self, worker_id: int) -> None:
        self.nodes.pop(worker_id, None)
        for pair in [p for p in self._links if worker_id in p]:
            del self._links[pair]
        self._updated_at = self._clock()

    # -- lookup --------------------------------------------------------------
    @staticmethod
    def _pair(a: int, b: int) -> tuple[int, int]:
        return (a, b) if a <= b else (b, a)

    def link(self, a: int, b: int) -> TopologyLink | None:
        if a == b:
            return TopologyLink(hop="local")
        return self._links.get(self._pair(a, b))

    def hop(self, a: int, b: int) -> str:
        link = self.link(a, b)
        return link.hop if link is not None else ""

    def pair_bandwidth(self, a: int, b: int) -> float:
        link = self.link(a, b)
        if link is None:
            return HOP_BANDWIDTH_BPS[DEFAULT_HOP]
        return link.bandwidth_bps()

    def worker_by_address(self, address: str) -> int | None:
        for wid, card in self.nodes.items():
            if card.transfer_address and card.transfer_address == address:
                return wid
        return None

    def inbound_hop(self, worker_id: int, *, src_role: str = "prefill") -> str:
        """Best (cheapest) hop class from any ``src_role`` node to this
        worker — the discovered analogue of the old per-worker
        ``DYN_TRANSFER_HOP`` self-report."""
        order = {"local": 0, "ici": 1, "dcn": 2}
        sources = [
            c for c in self.nodes.values()
            if c.role == src_role and c.worker_id != worker_id
        ] or [c for c in self.nodes.values() if c.worker_id != worker_id]
        best = ""
        for src in sources:
            hop = self.hop(src.worker_id, worker_id)
            if hop and (not best or order.get(hop, 3) < order.get(best, 3)):
                best = hop
        return best

    # -- measurement ---------------------------------------------------------
    def observe(
        self,
        a: int,
        b: int,
        *,
        rtt_s: float | None = None,
        nbytes: int | None = None,
        seconds: float | None = None,
        bandwidth_bps: float | None = None,
    ) -> None:
        """Fold one probe/transfer observation into the pair's EWMAs."""
        if a == b:
            return
        link = self._links.setdefault(self._pair(a, b), TopologyLink())
        alpha = self.ewma_alpha
        if rtt_s is not None and rtt_s > 0:
            link.rtt_s = (
                rtt_s if link.rtt_s <= 0
                else (1 - alpha) * link.rtt_s + alpha * rtt_s
            )
        bps = bandwidth_bps
        if bps is None and nbytes and seconds and seconds > 0:
            bps = nbytes / seconds
        if bps is not None and bps > 0:
            link.measured_bps = (
                bps if link.measured_bps <= 0
                else (1 - alpha) * link.measured_bps + alpha * bps
            )
        link.probes_total += 1
        self._updated_at = self._clock()

    # -- summaries -----------------------------------------------------------
    def informative(self) -> bool:
        """True iff the map carries placement signal — at least one pair is
        non-``local``.  A single-host all-local map is NOT informative, so
        consumers fall through to their pre-topology behavior exactly."""
        return any(link.hop not in ("", "local") for link in self._links.values())

    def links_by_class(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for link in self._links.values():
            hop = link.hop or "unknown"
            out[hop] = out.get(hop, 0) + 1
        return out

    def age_s(self) -> float:
        return max(0.0, self._clock() - self._updated_at)

    def to_dict(self) -> dict:
        """JSON-friendly dump (dynctl topology, tests)."""
        return {
            "nodes": {
                f"{wid:016x}": dataclasses.asdict(card)
                for wid, card in sorted(self.nodes.items())
            },
            "links": [
                {
                    "a": f"{a:016x}",
                    "b": f"{b:016x}",
                    "hop": link.hop,
                    "rtt_s": link.rtt_s,
                    "measured_bps": link.measured_bps,
                    "prior_bps": HOP_BANDWIDTH_BPS.get(
                        link.hop, HOP_BANDWIDTH_BPS[DEFAULT_HOP]
                    ),
                    "probes_total": link.probes_total,
                }
                for (a, b), link in sorted(self._links.items())
            ],
            "informative": self.informative(),
            "age_s": self.age_s(),
        }


class TopologyWatcher:
    """Keeps a TopologyMap live off the control plane's card prefix.

    Same shape as ``ModelWatcher``: ``watch_prefix`` replays existing cards
    as PUTs before streaming live events, so no seed read is needed.
    """

    def __init__(self, runtime, *, map: TopologyMap | None = None):
        self.runtime = runtime
        self.map = map if map is not None else TopologyMap()
        self._watch = None
        self._task = None

    async def start(self) -> None:
        self._watch = self.runtime.plane.kv.watch_prefix(CARDS_PREFIX)
        self._task = spawn_logged(self._loop(), name="topology-watcher")

    async def stop(self) -> None:
        if self._watch is not None:
            self._watch.cancel()
            self._watch = None
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        try:
            async for event in self._watch:
                if event.type == WatchEventType.PUT:
                    try:
                        card = TopologyCard.from_json(event.entry.value)
                    except (ValueError, TypeError) as exc:
                        logger.warning("topology: bad card %s: %s", event.entry.key, exc)
                        continue
                    self.map.upsert(card)
                elif event.type == WatchEventType.DELETE:
                    suffix = event.entry.key[len(CARDS_PREFIX):]
                    try:
                        self.map.remove(int(suffix, 16))
                    except ValueError:
                        logger.warning("topology: bad card key %s", event.entry.key)
        except ConnectionError as exc:
            # keep serving off the last good map; reconnect is the runtime's
            # problem, staleness shows up in dyn_topology_map_age_seconds
            logger.warning("topology watch lost: %s", exc)
