"""Fleet topology plane: discovery, link probing, and network-aware placement.

Three layers (see docs/topology.md):

- ``card``: each worker publishes a :class:`TopologyCard` (host fingerprint,
  JAX slice/process identity, data-plane address) through the control plane,
  lease-scoped like model registration so churn is visible as watch DELETEs.
- ``map``: :class:`TopologyMap` aggregates cards into nodes + pairwise links
  classified ``local``/``ici``/``dcn``; :class:`TopologyWatcher` keeps a map
  live off a ``watch_prefix`` the same way ``ModelWatcher`` tracks models.
- ``prober``: :class:`TopologyProber` measures pairwise RTT/bandwidth over the
  existing KV-transfer transport and folds results — plus ``KvTransferClient``
  per-destination send EWMAs — into the map, so priors decay into measurements.

Consumers (TransferCostModel, disagg router, planner rebalance, prefetch
pager) only act on a map that is *informative* — a single-host fleet discovers
an all-``local`` map and behaves byte-identically to a fleet with no topology
plane at all.
"""

from dynamo_tpu.topology.card import (
    CARDS_PREFIX,
    TopologyCard,
    local_card,
    publish_card,
)
from dynamo_tpu.topology.map import (
    TopologyLink,
    TopologyMap,
    TopologyWatcher,
    classify_link,
)
from dynamo_tpu.topology.prober import TopologyProber

__all__ = [
    "CARDS_PREFIX",
    "TopologyCard",
    "TopologyLink",
    "TopologyMap",
    "TopologyProber",
    "TopologyWatcher",
    "classify_link",
    "local_card",
    "publish_card",
]
