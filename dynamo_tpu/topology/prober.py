"""TopologyProber: bounded active measurement over the KV-transfer plane.

The prober reuses the data-plane transport (KvTransferClient → peer's
KvTransferServer) so measured RTT/bandwidth reflect the path real KV blocks
take — staging, framing, ack — not a synthetic ping.  Probe payloads carry a
reserved seq-id prefix; servers ack them without delivering to the engine
sink, so probing is invisible to decode state.

Budget: one tick every ``DYN_TOPO_PROBE_PERIOD_S`` probes at most
``DYN_TOPO_PROBE_MAX_PER_TICK`` peers (round-robin cursor), each with a
``DYN_TOPO_PROBE_BYTES`` payload.  Passive measurements — the
``KvTransferClient`` per-destination send EWMAs that real transfers already
maintain — are folded in by :meth:`merge_client_ewmas`, so a busy fleet
barely needs active probes at all.
"""

from __future__ import annotations

import logging
import time
import uuid

import numpy as np

from dynamo_tpu.parallel.kv_transfer import (
    PROBE_SEQ_PREFIX,
    KvTransferClient,
    KvTransferPayload,
)
from dynamo_tpu.topology.map import TopologyMap
from dynamo_tpu.utils import knobs
from dynamo_tpu.utils.tasks import spawn_logged

logger = logging.getLogger(__name__)


class TopologyProber:
    def __init__(
        self,
        topo_map: TopologyMap,
        *,
        self_worker_id: int,
        client: KvTransferClient | None = None,
        period_s: float | None = None,
        probe_bytes: int | None = None,
        max_per_tick: int | None = None,
        clock=time.monotonic,
    ):
        self.map = topo_map
        self.self_worker_id = self_worker_id
        self.client = client if client is not None else KvTransferClient()
        self.period_s = (
            period_s if period_s is not None
            else knobs.get("DYN_TOPO_PROBE_PERIOD_S")
        )
        self.probe_bytes = (
            probe_bytes if probe_bytes is not None
            else knobs.get("DYN_TOPO_PROBE_BYTES")
        )
        self.max_per_tick = (
            max_per_tick if max_per_tick is not None
            else knobs.get("DYN_TOPO_PROBE_MAX_PER_TICK")
        )
        self._clock = clock
        self._cursor = 0
        self._task = None
        self.probes_sent = 0
        self.probe_failures = 0

    async def start(self) -> None:
        import asyncio

        async def _loop() -> None:
            while True:
                await asyncio.sleep(self.period_s)
                await self.probe_once()
                self.merge_client_ewmas()

        self._task = spawn_logged(_loop(), name="topology-prober")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def _peers(self) -> list:
        return [
            card for wid, card in sorted(self.map.nodes.items())
            if wid != self.self_worker_id and card.transfer_address
        ]

    async def probe_once(self) -> int:
        """Probe up to ``max_per_tick`` peers; returns probes completed."""
        peers = self._peers()
        if not peers:
            return 0
        done = 0
        n = min(self.max_per_tick, len(peers))
        for i in range(n):
            card = peers[(self._cursor + i) % len(peers)]
            payload = KvTransferPayload(
                seq_id=f"{PROBE_SEQ_PREFIX}{uuid.uuid4().hex}",
                first_token=-1,
                block_ids=[],
                blocks={"probe": np.zeros(self.probe_bytes, dtype=np.uint8)},
            )
            start = self._clock()
            try:
                await self.client.send(card.transfer_address, payload)
            except (OSError, ConnectionError) as exc:
                self.probe_failures += 1
                logger.debug(
                    "topology probe to %s failed: %s", card.transfer_address, exc
                )
                continue
            elapsed = self._clock() - start
            self.map.observe(
                self.self_worker_id,
                card.worker_id,
                rtt_s=elapsed,
                nbytes=self.probe_bytes,
                seconds=elapsed,
            )
            self.probes_sent += 1
            done += 1
        self._cursor = (self._cursor + n) % max(1, len(peers))
        return done

    def merge_client_ewmas(self, client: KvTransferClient | None = None) -> int:
        """Fold a KvTransferClient's per-address bandwidth EWMAs into the
        map (the ROADMAP's "feed the per-destination client EWMA back into
        the router" — the router reads the map).  Returns links updated."""
        source = client if client is not None else self.client
        merged = 0
        for address, bps in list(source.bandwidth_bps.items()):
            if bps <= 0:
                continue
            peer = self.map.worker_by_address(address)
            if peer is None or peer == self.self_worker_id:
                continue
            self.map.observe(self.self_worker_id, peer, bandwidth_bps=bps)
            merged += 1
        return merged

    def stats(self) -> dict:
        return {
            "topo_probes_sent": self.probes_sent,
            "topo_probe_failures": self.probe_failures,
        }
