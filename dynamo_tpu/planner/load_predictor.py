"""Load predictors (reference: components/planner/.../utils/load_predictor.py
— constant / ARIMA / Prophet).  Here: constant, EWMA, linear-trend, an
AR(p)-with-differencing forecaster fitted by least squares (the ARIMA(p,d,0)
role), and a seasonal trend decomposition (the Prophet role) — numpy-only,
no pandas/pmdarima/Prophet runtime.

Every predictor also answers ``predict_ahead(steps)`` — the ``steps``-tick
forecast the planner needs to act BEFORE a load crest instead of reacting
at it — and ``replay_trace()`` fits a predictor offline from a flight
recorder dump (observability/flight.py), so a soak's telemetry closes the
loop back into planning."""

from __future__ import annotations

from collections import deque
from pathlib import Path

import numpy as np


class ConstantPredictor:
    """Next value = last observation."""

    def __init__(self, **_):
        self._last = 0.0

    def observe(self, value: float) -> None:
        self._last = value

    def predict(self) -> float:
        return self._last

    def predict_ahead(self, steps: int = 1) -> float:
        return self.predict()


class EwmaPredictor:
    """Exponentially-weighted moving average."""

    def __init__(self, alpha: float = 0.5, **_):
        self.alpha = alpha
        self._value: float | None = None

    def observe(self, value: float) -> None:
        if self._value is None:
            self._value = value
        else:
            self._value = self.alpha * value + (1 - self.alpha) * self._value

    def predict(self) -> float:
        return self._value or 0.0

    def predict_ahead(self, steps: int = 1) -> float:
        # the EWMA level is a flat forecast at any horizon
        return self.predict()


class LinearTrendPredictor:
    """Least-squares line over a sliding window, extrapolated one step."""

    def __init__(self, window: int = 8, **_):
        self._obs: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._obs.append(value)

    def predict(self) -> float:
        return self.predict_ahead(1)

    def predict_ahead(self, steps: int = 1) -> float:
        n = len(self._obs)
        if n == 0:
            return 0.0
        if n == 1:
            return self._obs[0]
        xs = range(n)
        mean_x = (n - 1) / 2
        mean_y = sum(self._obs) / n
        cov = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, self._obs))
        var = sum((x - mean_x) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        return max(0.0, mean_y + slope * (n - 1 + steps - mean_x))


class ArPredictor:
    """ARIMA(p, d, 0) by ordinary least squares over a sliding window.

    The series is differenced ``d`` times, an order-``p`` autoregression is
    fitted with ``np.linalg.lstsq`` (with intercept), the one-step forecast
    is produced in the differenced domain and integrated back.  Falls back
    to last-value while the window is shorter than ``2p + d + 1``."""

    def __init__(self, p: int = 3, d: int = 1, window: int = 64, **_):
        if p < 1 or d < 0:
            raise ValueError("ArPredictor needs p >= 1, d >= 0")
        self.p = p
        self.d = d
        self._obs: deque[float] = deque(maxlen=max(window, 2 * p + d + 4))

    def observe(self, value: float) -> None:
        self._obs.append(float(value))

    def predict(self) -> float:
        y = np.asarray(self._obs, np.float64)
        if y.size == 0:
            return 0.0
        z = y.copy()
        for _ in range(self.d):
            if z.size < 2:
                return float(y[-1])
            z = np.diff(z)
        if z.size < 2 * self.p + 1:
            return float(y[-1])
        # lagged design matrix: z[t] ~ c + sum_i phi_i * z[t-i]
        rows = z.size - self.p
        X = np.ones((rows, self.p + 1))
        for i in range(1, self.p + 1):
            X[:, i] = z[self.p - i : self.p - i + rows]
        target = z[self.p :]
        coef, *_ = np.linalg.lstsq(X, target, rcond=None)
        z_next = coef[0] + coef[1:] @ z[-1 : -self.p - 1 : -1]
        # integrate the differencing back: forecast = last level(s) + z_next
        forecast = z_next
        tail = y.copy()
        for _ in range(self.d):
            forecast = forecast + tail[-1]
            tail = np.diff(tail) if tail.size > 1 else tail
        return float(max(0.0, forecast))

    def predict_ahead(self, steps: int = 1) -> float:
        # roll the one-step forecast forward, feeding each prediction back
        # as an observation (the standard iterated AR multi-step forecast);
        # the window is restored afterwards, so this is side-effect free
        saved = list(self._obs)
        try:
            value = self.predict()
            for _ in range(int(steps) - 1):
                self._obs.append(value)
                value = self.predict()
            return value
        finally:
            self._obs.clear()
            self._obs.extend(saved)


class SeasonalPredictor:
    """Seasonal-trend decomposition forecast (the Prophet role): a linear
    trend is fitted on the window, per-phase seasonal offsets (period ``m``)
    are averaged over the detrended series, and the one-step forecast is
    trend(t+1) + season((t+1) mod m).  Falls back to last-value until two
    full periods are observed."""

    def __init__(self, period: int = 12, window: int = 96, **_):
        if period < 2:
            raise ValueError("SeasonalPredictor needs period >= 2")
        self.period = period
        self._obs: deque[float] = deque(maxlen=max(window, 4 * period))
        self._t = 0  # absolute index of the NEXT observation (phase anchor)

    def observe(self, value: float) -> None:
        self._obs.append(float(value))
        self._t += 1

    def predict(self) -> float:
        return self.predict_ahead(1)

    def predict_ahead(self, steps: int = 1) -> float:
        y = np.asarray(self._obs, np.float64)
        n = y.size
        if n == 0:
            return 0.0
        if n < 2 * self.period:
            return float(y[-1])
        m = self.period
        # JOINT least squares of trend + seasonal phase dummies: fitting
        # trend first then averaging residuals leaks (a sinusoid correlates
        # with t even over whole periods), so solve them together
        xs = np.arange(n, dtype=np.float64)
        start = self._t - n  # absolute index of window position 0
        phases = ((start + np.arange(n)) % m).astype(int)
        X = np.zeros((n, m + 1))
        X[:, 0] = xs
        X[:, 1] = 1.0
        for ph in range(m - 1):  # last phase is the baseline
            X[:, 2 + ph] = phases == ph
        coef, *_ = np.linalg.lstsq(X, y, rcond=None)
        x_next = np.zeros(m + 1)
        x_next[0] = n - 1 + steps
        x_next[1] = 1.0
        next_phase = (self._t - 1 + steps) % m
        if next_phase < m - 1:
            x_next[2 + next_phase] = 1.0
        return float(max(0.0, coef @ x_next))


def make_predictor(kind: str = "constant", **kwargs):
    return {
        "constant": ConstantPredictor,
        "ewma": EwmaPredictor,
        "linear": LinearTrendPredictor,
        "ar": ArPredictor,
        "arima": ArPredictor,
        "seasonal": SeasonalPredictor,
        "prophet": SeasonalPredictor,
    }[kind](**kwargs)


def replay_trace(
    source,
    *,
    kind: str = "seasonal",
    field: str = "num_running",
    bucket_s: float = 1.0,
    agg: str = "mean",
    **kwargs,
):
    """Fit a predictor offline from a flight-recorder trace.

    ``source`` is a flight dump path (observability/flight.py JSONL) or an
    iterable of already-loaded record dicts.  The trace's ``step`` records
    are bucketed into a regular ``bucket_s`` series on the recorder's
    monotonic clock — ``field`` per bucket, aggregated by ``agg``
    ("mean" for level signals like num_running, "sum" for rate signals
    like decode_tokens) — and replayed through ``make_predictor(kind)``.
    Gaps hold the last level under "mean" and read zero under "sum".

    Returns the fitted predictor, ready for ``predict_ahead()``."""
    if isinstance(source, (str, Path)):
        from dynamo_tpu.observability.flight import load_dump

        _header, records = load_dump(source)
    else:
        records = list(source)
    if agg not in ("mean", "sum"):
        raise ValueError(f"agg must be mean|sum, got {agg!r}")
    steps = [
        r for r in records
        if r.get("kind") == "step" and field in r and "t" in r
    ]
    if not steps:
        raise ValueError(f"no step records carrying {field!r} in the trace")
    if bucket_s <= 0:
        raise ValueError("bucket_s must be > 0")
    t0 = min(float(r["t"]) for r in steps)
    buckets: dict[int, list[float]] = {}
    for r in steps:
        idx = int((float(r["t"]) - t0) / bucket_s)
        buckets.setdefault(idx, []).append(float(r[field]))
    predictor = make_predictor(kind, **kwargs)
    level = 0.0
    for i in range(max(buckets) + 1):
        vals = buckets.get(i)
        if vals:
            level = sum(vals) if agg == "sum" else sum(vals) / len(vals)
        elif agg == "sum":
            level = 0.0
        predictor.observe(level)
    return predictor
