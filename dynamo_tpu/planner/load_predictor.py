"""Load predictors (reference: components/planner/.../utils/load_predictor.py
— constant / ARIMA / Prophet; here: constant, EWMA, and linear-trend, which
cover the same roles without heavyweight deps)."""

from __future__ import annotations

from collections import deque


class ConstantPredictor:
    """Next value = last observation."""

    def __init__(self, **_):
        self._last = 0.0

    def observe(self, value: float) -> None:
        self._last = value

    def predict(self) -> float:
        return self._last


class EwmaPredictor:
    """Exponentially-weighted moving average."""

    def __init__(self, alpha: float = 0.5, **_):
        self.alpha = alpha
        self._value: float | None = None

    def observe(self, value: float) -> None:
        if self._value is None:
            self._value = value
        else:
            self._value = self.alpha * value + (1 - self.alpha) * self._value

    def predict(self) -> float:
        return self._value or 0.0


class LinearTrendPredictor:
    """Least-squares line over a sliding window, extrapolated one step."""

    def __init__(self, window: int = 8, **_):
        self._obs: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self._obs.append(value)

    def predict(self) -> float:
        n = len(self._obs)
        if n == 0:
            return 0.0
        if n == 1:
            return self._obs[0]
        xs = range(n)
        mean_x = (n - 1) / 2
        mean_y = sum(self._obs) / n
        cov = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, self._obs))
        var = sum((x - mean_x) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))


def make_predictor(kind: str = "constant", **kwargs):
    return {
        "constant": ConstantPredictor,
        "ewma": EwmaPredictor,
        "linear": LinearTrendPredictor,
    }[kind](**kwargs)
