"""Planner-driven fleet defragmentation via live session migration.

Long decode sessions pin KV blocks to whichever worker admitted them; over
hours a fleet develops hot workers (occupancy near the ceiling, every new
admission a near-miss) next to cold ones.  Scaling can't fix that — the
capacity exists, it's just in the wrong place.  The :class:`Defragmenter`
fixes placement instead: each planner interval it looks at per-worker KV
occupancy, and when the hottest worker with live sessions sits more than
``occupancy_spread`` above the coldest eligible peer, it migrates sessions
off the hot worker through the dispatcher's
:class:`~dynamo_tpu.runtime.migration.MigrationCoordinator` — the zero-loss
mid-decode handoff, so defrag is invisible to clients.

Deliberately conservative, in the planner's own idiom (cooldowns, bounded
steps):

- bounded rate: at most ``max_per_step`` migrations per step, and after any
  committed move the loop holds off for ``cooldown_s`` so the occupancy
  signal can settle before it re-judges the fleet;
- never cross-slice: destinations a DCN hop away are filtered out — only a
  drain (a doomed worker) justifies paying the cross-slice bill, and the
  drain path prices that itself;
- prefix-local targets: among eligible destinations the cheapest discovered
  hop wins first (local, then ICI), coldest occupancy second — the moved
  session lands where its continuation re-prefill is cheapest;
- an idle fleet is left alone: the hot worker must itself be above
  ``min_occupancy`` before shuffling sessions buys anything.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from dynamo_tpu.runtime.migration import _HOP_COST
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("planner.defrag")


@dataclass
class DefragConfig:
    enabled: bool = False
    # trigger: hottest-vs-coldest KV occupancy gap (fractions of the cache)
    occupancy_spread: float = 0.25
    # the hot worker must itself be at least this full — moving sessions
    # around a cold fleet is churn, not defragmentation
    min_occupancy: float = 0.5
    max_per_step: int = 1
    cooldown_s: float = 8.0


class Defragmenter:
    """One defrag loop per dispatcher; stepped on the planner's cadence."""

    def __init__(self, coordinator, config: DefragConfig | None = None,
                 clock=time.monotonic):
        self.coordinator = coordinator
        self.config = config or DefragConfig()
        self._clock = clock
        self._cooldown_until = float("-inf")
        self.moves: list[dict] = []      # committed migrations, for the logs

    @staticmethod
    def spread(occupancy: dict[int, float]) -> float:
        if len(occupancy) < 2:
            return 0.0
        vals = occupancy.values()
        return max(vals) - min(vals)

    def _pick(self, occupancy: dict[int, float]) -> tuple[int | None, int | None]:
        """(hot worker to empty, destination) or (None, None).  The hot
        worker must hold live sessions (an occupancy spike with nothing to
        move is the admission controller's problem, not defrag's)."""
        coord = self.coordinator
        sessions = coord.sessions()
        loaded = {h for h in sessions.values() if h in occupancy}
        if not loaded:
            return None, None
        hot = max(loaded, key=lambda w: occupancy[w])
        if occupancy[hot] < self.config.min_occupancy:
            return None, None
        healthy = set(coord.router.healthy_ids({hot}))
        eligible = []
        for w, occ in occupancy.items():
            if w == hot or w not in healthy:
                continue
            if occupancy[hot] - occ < self.config.occupancy_spread:
                continue
            hop = coord.hop(hot, w)
            if hop == "dcn":
                continue     # never cross-slice for a mere rebalance
            eligible.append((w, _HOP_COST.get(hop, 2), occ))
        if not eligible:
            return hot, None
        # cheapest hop first (prefix-local re-prefill), coldest second
        eligible.sort(key=lambda e: (e[1], e[2], e[0]))
        return hot, eligible[0][0]

    async def step(self, occupancy: dict[int, float],
                   now: float | None = None) -> list[dict]:
        """One defrag pass over a per-worker occupancy snapshot (fractions,
        e.g. the aggregated ``gpu_cache_usage_perc``).  Returns the migration
        results it drove (possibly aborted ones — the coordinator's safety
        story means an abort costs nothing)."""
        cfg = self.config
        if not cfg.enabled or self.coordinator is None:
            return []
        now = self._clock() if now is None else now
        if now < self._cooldown_until:
            return []
        hot, dst = self._pick(occupancy)
        if hot is None or dst is None:
            return []
        coord = self.coordinator
        results: list[dict] = []
        for rid in sorted(coord.sessions_on(hot))[: max(cfg.max_per_step, 1)]:
            res = await coord.migrate(rid, dst, reason="defrag")
            results.append(res)
            if res.get("ok"):
                self.moves.append({
                    "t": round(now, 3), "request": rid,
                    "src": res["src"], "dst": res["dst"],
                    "hop": res.get("hop") or "",
                })
        if any(r.get("ok") for r in results):
            self._cooldown_until = now + cfg.cooldown_s
            logger.info(
                "defrag: moved %d session(s) off %x (occupancy %.2f)",
                sum(1 for r in results if r.get("ok")), hot, occupancy[hot],
            )
        return results
