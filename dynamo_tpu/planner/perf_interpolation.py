"""Profiled performance interpolation (reference: components/planner/
.../utils/perf_interpolation.py).

A profile is a grid of measured points (isl, osl, concurrency →
prefill_throughput tok/s/chip, decode_throughput, ttft, itl); the planner
interpolates between the nearest profiled points to estimate capacity at the
current workload.  Profiles come from ``benchmarks/profile_sla.py`` runs on
the target TPU slice.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass
from pathlib import Path


@dataclass
class ProfilePoint:
    isl: int
    osl: int
    concurrency: int = 1
    prefill_tok_s: float = 0.0   # prompt tokens/s/chip during prefill
    decode_tok_s: float = 0.0    # generated tokens/s/chip during decode
    ttft_s: float = 0.0
    itl_s: float = 0.0


class PerfProfile:
    def __init__(self, points: list[ProfilePoint]):
        if not points:
            raise ValueError("empty profile")
        self.points = points

    @classmethod
    def load(cls, path: str | Path) -> "PerfProfile":
        data = json.loads(Path(path).read_text())
        return cls([ProfilePoint(**p) for p in data["points"]])

    def save(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps({"points": [asdict(p) for p in self.points]}))

    def concurrencies(self) -> list[int]:
        return sorted({p.concurrency for p in self.points})

    def _interp(
        self, isl: float, osl: float, field: str, concurrency: int | None = None
    ) -> float:
        """Inverse-distance-weighted interpolation over the (isl, osl) grid —
        robust to irregular profile grids.  Interpolation is always within
        ONE concurrency level (blending single-stream and saturated numbers
        would be meaningless); default = the lowest profiled level."""
        if concurrency is None:
            concurrency = self.concurrencies()[0]
        pts = [p for p in self.points if p.concurrency == concurrency]
        if not pts:
            raise ValueError(
                f"no profiled points at concurrency={concurrency} "
                f"(have {self.concurrencies()})"
            )
        weights = 0.0
        acc = 0.0
        for p in pts:
            d2 = ((p.isl - isl) / 512.0) ** 2 + ((p.osl - osl) / 128.0) ** 2
            if d2 < 1e-12:
                return getattr(p, field)
            w = 1.0 / d2
            weights += w
            acc += w * getattr(p, field)
        return acc / weights

    def prefill_tok_s(self, isl: float, osl: float, concurrency: int | None = None) -> float:
        return self._interp(isl, osl, "prefill_tok_s", concurrency)

    def decode_tok_s(self, isl: float, osl: float, concurrency: int | None = None) -> float:
        return self._interp(isl, osl, "decode_tok_s", concurrency)

    def ttft_s(self, isl: float, osl: float, concurrency: int | None = None) -> float:
        return self._interp(isl, osl, "ttft_s", concurrency)

    def itl_s(self, isl: float, osl: float, concurrency: int | None = None) -> float:
        return self._interp(isl, osl, "itl_s", concurrency)
