"""Planner core loop.

Every ``adjustment_interval``: observe (request rate, ISL/OSL, TTFT/ITL) →
apply correction factors vs the profile → predict next-interval load →
compute required prefill/decode replicas → scale via the connector, within
min/max bounds and chip budget (reference: planner_core.py:162-240,
planner_sla.py:115).

Disaggregation-aware: prefill replicas are sized from predicted prompt
tokens/s against profiled prefill throughput; decode replicas from predicted
generated tokens/s against profiled decode throughput (degraded by the
observed correction factor).

SLO-native autopilot: when the sample carries burn rates (frontend ``/slo``,
``sample_from_slo_status``) the planner escalates the BURNING pool past what
the demand math asked for — TTFT burn grows the prefill pool, ITL burn the
decode pool, error burn both — and while any objective burns (or within
``cooldown_s`` of a scale-up) it refuses to scale below the current fleet.
At the chip budget it rebalances instead of growing: one replica moves from
an idle pool (occupancy under ``rebalance_occupancy``, own objective not
burning) to the burning pool, the FlowKV-style load-aware split for
disaggregated prefill/decode fleets.
"""

from __future__ import annotations

import asyncio
import math
import time
from dataclasses import dataclass, field

from dynamo_tpu.planner.load_predictor import make_predictor
from dynamo_tpu.planner.perf_interpolation import PerfProfile
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.tasks import spawn_logged

logger = get_logger("planner")


@dataclass
class WorkloadSample:
    request_rate: float        # req/s
    avg_isl: float             # prompt tokens/request
    avg_osl: float             # generated tokens/request
    ttft_s: float = 0.0
    itl_s: float = 0.0
    # Observed fleet utilization (observability/perf.py via the metrics
    # service): when present, the planner sizes replicas from REAL measured
    # per-replica throughput instead of interpolating the offline profile —
    # the profile stays as bootstrap and fallback.
    observed_prefill_tok_s: float = 0.0   # fleet prompt tokens/s actually served
    observed_decode_tok_s: float = 0.0    # fleet emitted tokens/s (goodput)
    num_prefill_replicas: int = 0
    num_decode_replicas: int = 0
    # mean decode-lane occupancy across the fleet: observed throughput only
    # counts as CAPACITY when measured near saturation (an idle replica's
    # low goodput is headroom, not a ceiling)
    avg_occupancy: float = 0.0
    # SLO burn-rate inputs (frontend /slo, worst window per objective):
    # bad-fraction / error-budget — >1 means the objective is burning faster
    # than its budget.  0 disables the burn terms (legacy callers).
    ttft_burn_rate: float = 0.0
    itl_burn_rate: float = 0.0
    error_burn_rate: float = 0.0
    # utilization headroom inputs: per-pool occupancy lets the planner see
    # that one pool idles while the other burns (rebalance signal); avg_mfu
    # rides along for decision logs and the dyn_planner_* gauges
    prefill_occupancy: float = 0.0
    decode_occupancy: float = 0.0
    avg_mfu: float = 0.0
    # topology: the set of slice labels each pool's replicas live on (from
    # the fleet TopologyMap).  Empty = unknown — the slice-aware rebalance
    # guard stays inert, preserving the pre-topology planner exactly.
    prefill_slices: tuple = ()
    decode_slices: tuple = ()


def burn_rates_from_slo(status: dict | None) -> dict[str, float]:
    """Worst-window burn rate per objective from a frontend ``/slo`` payload
    (observability/slo.SloTracker.status()).  Tolerates payloads without the
    per-objective ``worst_burn_rate`` field by scanning the windows."""
    out: dict[str, float] = {}
    if not status:
        return out
    for name, obj in (status.get("objectives") or {}).items():
        worst = obj.get("worst_burn_rate")
        if worst is None:
            windows = obj.get("windows") or {}
            worst = max(
                (w.get("burn_rate", 0.0) for w in windows.values()), default=0.0
            )
        out[name] = float(worst)
    return out


def sample_from_endpoints(
    endpoints,
    *,
    request_rate: float,
    avg_isl: float,
    avg_osl: float,
    ttft_s: float = 0.0,
    itl_s: float = 0.0,
    roles: dict[int, str] | None = None,
    slo_status: dict | None = None,
    slices: dict[int, str] | None = None,
) -> WorkloadSample:
    """Build a WorkloadSample from a live fleet snapshot
    (llm/kv_router/metrics_aggregator.ProcessedEndpoints): per-worker
    goodput sums into the observed capacity terms.

    Disaggregated fleets carry a role per worker — ``roles`` maps
    worker_id → "prefill"/"decode" and overrides any role the worker
    self-reported in its ForwardPassMetrics.  Workers with no role serve
    both phases and count in both pools.  Single-pool deployments (no roles
    anywhere) degrade to the legacy behavior: the same worker set reported
    for both pools.

    ``slo_status`` is the frontend ``/slo`` JSON; when given, the worst
    window per objective becomes the sample's burn-rate inputs.

    ``slices`` maps worker_id → discovered slice label (fleet TopologyMap);
    the per-pool slice sets feed the planner's cross-slice rebalance guard.
    """
    worker_map = dict(getattr(endpoints, "workers", {}))
    roles = roles or {}
    slices = slices or {}

    def _role(wid, m) -> str:
        return roles.get(wid) or str(getattr(m, "role", "") or "")

    prefill_pool = [
        m for wid, m in worker_map.items() if _role(wid, m) in ("", "prefill")
    ]
    decode_pool = [
        m for wid, m in worker_map.items() if _role(wid, m) in ("", "decode")
    ]

    def _pool_slices(role: str) -> tuple:
        return tuple(sorted({
            slices[wid] for wid, m in worker_map.items()
            if wid in slices and slices[wid] and _role(wid, m) in ("", role)
        }))

    def _occ(pool) -> float:
        return (
            sum(getattr(m, "batch_occupancy_perc", 0.0) for m in pool) / len(pool)
            if pool else 0.0
        )

    workers = list(worker_map.values())
    goodput = sum(getattr(m, "goodput_tokens_per_second", 0.0) for m in decode_pool)
    prefill = sum(getattr(m, "prefill_tokens_per_second", 0.0) for m in prefill_pool)
    mfu = (
        sum(getattr(m, "mfu_perc", 0.0) for m in workers) / len(workers)
        if workers else 0.0
    )
    burn = burn_rates_from_slo(slo_status)
    return WorkloadSample(
        avg_occupancy=_occ(workers),
        request_rate=request_rate,
        avg_isl=avg_isl,
        avg_osl=avg_osl,
        ttft_s=ttft_s,
        itl_s=itl_s,
        observed_prefill_tok_s=prefill,
        observed_decode_tok_s=goodput,
        num_prefill_replicas=len(prefill_pool),
        num_decode_replicas=len(decode_pool),
        prefill_occupancy=_occ(prefill_pool),
        decode_occupancy=_occ(decode_pool),
        avg_mfu=mfu,
        ttft_burn_rate=burn.get("ttft", 0.0),
        itl_burn_rate=burn.get("itl", 0.0),
        error_burn_rate=burn.get("error_rate", burn.get("error", 0.0)),
        prefill_slices=_pool_slices("prefill"),
        decode_slices=_pool_slices("decode"),
    )


@dataclass
class PlannerConfig:
    adjustment_interval_s: float = 30.0
    predictor: str = "ewma"
    min_prefill: int = 1
    max_prefill: int = 8
    min_decode: int = 1
    max_decode: int = 8
    max_total_chips: int = 16
    chips_per_prefill: int = 1
    chips_per_decode: int = 1
    # SLA targets (0 disables the SLA term)
    ttft_target_s: float = 0.0
    itl_target_s: float = 0.0
    scale_down_headroom: float = 1.3   # keep 30% slack before scaling down
    # min fleet decode-lane occupancy for an observed-throughput sample to
    # update the capacity estimate (see WorkloadSample.avg_occupancy)
    saturation_occupancy: float = 0.8
    # -- SLO-native autopilot knobs (0 disables the corresponding term) ----
    # burn rate above which the burning pool is grown past the demand math
    burn_upscale: float = 1.0
    # while any objective's burn exceeds this, never scale below the current
    # fleet (latency recovery needs the capacity it is about to get)
    burn_hold: float = 0.25
    # after a burn/SLA scale-up, refuse scale-down for this long — stops the
    # flap where the freshly-grown fleet looks idle the next interval
    cooldown_s: float = 60.0
    # at the chip budget, move a replica from an idle pool (occupancy below
    # rebalance_occupancy, own objective not burning) to the burning pool
    rebalance: bool = True
    rebalance_occupancy: float = 0.5
    # pool-per-slice awareness: when the two pools' discovered slice sets
    # are disjoint, a rebalance would move a replica across DCN and split a
    # hot prefill↔decode pair — refuse the move (demand scaling unaffected)
    rebalance_slice_aware: bool = True


@dataclass
class PlannerDecision:
    num_prefill: int
    num_decode: int
    reason: str = ""


class Planner:
    def __init__(
        self,
        profile: PerfProfile,
        connector,
        config: PlannerConfig | None = None,
        clock=time.monotonic,
    ):
        self.profile = profile
        self.connector = connector
        self.config = config or PlannerConfig()
        self._clock = clock
        self._rate_pred = make_predictor(self.config.predictor)
        self._isl_pred = make_predictor(self.config.predictor)
        self._osl_pred = make_predictor(self.config.predictor)
        # correction factors: observed perf / profiled perf (reference:
        # planner_core.py correction factors)
        self._ttft_correction = 1.0
        self._itl_correction = 1.0
        # observed per-replica throughput (EWMA over samples that carried
        # utilization): replaces the profile interpolation as the capacity
        # denominator once real measurements exist
        self._prefill_cap_obs = 0.0
        self._decode_cap_obs = 0.0
        # SLO-autopilot state from the latest sample: current fleet shape,
        # per-objective burn, per-pool occupancy (0 / unknown ⇒ the burn and
        # rebalance terms stay inert and the legacy demand math rules)
        self._cur_prefill = 0
        self._cur_decode = 0
        self._burn: dict[str, float] = {"ttft": 0.0, "itl": 0.0, "error": 0.0}
        self._prefill_occ = 0.0
        self._decode_occ = 0.0
        self._prefill_slices: tuple = ()
        self._decode_slices: tuple = ()
        self._cooldown_until = float("-inf")
        self.last_decision: PlannerDecision | None = None
        self._task: asyncio.Task | None = None
        self.metrics_source = None  # set for loop mode
        # optional planner/state.PlannerStatePublisher: step() emits a
        # PlannerStateEvent after every executed decision
        self.state_publisher = None

    # observed per-replica capacity accessors (dyn_planner_* gauges)
    @property
    def observed_prefill_capacity(self) -> float:
        return self._prefill_cap_obs

    @property
    def observed_decode_capacity(self) -> float:
        return self._decode_cap_obs

    @property
    def worst_burn_input(self) -> float:
        return max(self._burn.values(), default=0.0)

    # -- one planning step -------------------------------------------------
    def observe(self, sample: WorkloadSample) -> None:
        self._rate_pred.observe(sample.request_rate)
        self._isl_pred.observe(sample.avg_isl)
        self._osl_pred.observe(sample.avg_osl)
        if sample.ttft_s > 0:
            expected = self.profile.ttft_s(sample.avg_isl, sample.avg_osl)
            if expected > 0:
                self._ttft_correction = sample.ttft_s / expected
        if sample.itl_s > 0:
            expected = self.profile.itl_s(sample.avg_isl, sample.avg_osl)
            if expected > 0:
                self._itl_correction = sample.itl_s / expected
        self._cur_prefill = sample.num_prefill_replicas
        self._cur_decode = sample.num_decode_replicas
        self._burn = {
            "ttft": sample.ttft_burn_rate,
            "itl": sample.itl_burn_rate,
            "error": sample.error_burn_rate,
        }
        self._prefill_occ = sample.prefill_occupancy or sample.avg_occupancy
        self._decode_occ = sample.decode_occupancy or sample.avg_occupancy
        self._prefill_slices = tuple(sample.prefill_slices)
        self._decode_slices = tuple(sample.decode_slices)
        # real utilization (when the sample carries it): EWMA of measured
        # per-replica throughput.  Only samples with actual flow update it —
        # an idle interval says nothing about capacity.
        alpha = 0.5
        if sample.avg_occupancy < self.config.saturation_occupancy:
            return
        if sample.num_prefill_replicas > 0 and sample.observed_prefill_tok_s > 0:
            per_replica = sample.observed_prefill_tok_s / sample.num_prefill_replicas
            self._prefill_cap_obs = (
                per_replica if self._prefill_cap_obs == 0
                else alpha * per_replica + (1 - alpha) * self._prefill_cap_obs
            )
        if sample.num_decode_replicas > 0 and sample.observed_decode_tok_s > 0:
            per_replica = sample.observed_decode_tok_s / sample.num_decode_replicas
            self._decode_cap_obs = (
                per_replica if self._decode_cap_obs == 0
                else alpha * per_replica + (1 - alpha) * self._decode_cap_obs
            )

    def plan(self, now: float | None = None) -> PlannerDecision:
        cfg = self.config
        now = self._clock() if now is None else now
        rate = self._rate_pred.predict()
        isl = max(self._isl_pred.predict(), 1.0)
        osl = max(self._osl_pred.predict(), 1.0)

        prefill_demand = rate * isl          # prompt tokens/s
        decode_demand = rate * osl           # generated tokens/s

        # capacity: measured per-replica throughput at saturation beats the
        # offline profile; the profile bootstraps and serves cold fleets
        prefill_capacity = self._prefill_cap_obs or (
            self.profile.prefill_tok_s(isl, osl) / max(self._ttft_correction, 1e-6)
        )
        decode_capacity = self._decode_cap_obs or (
            self.profile.decode_tok_s(isl, osl) / max(self._itl_correction, 1e-6)
        )

        num_prefill = math.ceil(prefill_demand / max(prefill_capacity, 1e-6) * cfg.scale_down_headroom) if prefill_demand else cfg.min_prefill
        num_decode = math.ceil(decode_demand / max(decode_capacity, 1e-6) * cfg.scale_down_headroom) if decode_demand else cfg.min_decode

        # SLA escalation: if observed latency breaches target, add capacity
        reasons: list[str] = []
        if cfg.ttft_target_s and self._ttft_correction * self.profile.ttft_s(isl, osl) > cfg.ttft_target_s:
            num_prefill += 1
            reasons.append("ttft_sla")
        if cfg.itl_target_s and self._itl_correction * self.profile.itl_s(isl, osl) > cfg.itl_target_s:
            num_decode += 1
            reasons.append("itl_sla")

        # SLO burn escalation: a burning objective grows ITS pool past the
        # demand math, relative to the fleet we actually have — demand says
        # what SHOULD suffice, burn says it demonstrably doesn't
        burn = self._burn
        cur_p, cur_d = self._cur_prefill, self._cur_decode
        if cfg.burn_upscale > 0:
            if burn["ttft"] > cfg.burn_upscale and cur_p > 0:
                num_prefill = max(num_prefill, cur_p + 1)
                reasons.append("ttft_burn")
            if burn["itl"] > cfg.burn_upscale and cur_d > 0:
                num_decode = max(num_decode, cur_d + 1)
                reasons.append("itl_burn")
            if burn["error"] > cfg.burn_upscale and (cur_p > 0 or cur_d > 0):
                num_prefill = max(num_prefill, cur_p + 1) if cur_p else num_prefill
                num_decode = max(num_decode, cur_d + 1) if cur_d else num_decode
                reasons.append("error_burn")

        # hold: while burning (or cooling down from a scale-up) never drop
        # below the current fleet — recovery needs the capacity to drain the
        # backlog, and a fresh scale-up must not be undone the next tick
        burning = cfg.burn_hold > 0 and max(burn.values()) > cfg.burn_hold
        cooling = now < self._cooldown_until
        if burning or cooling:
            if cur_p > 0:
                num_prefill = max(num_prefill, cur_p)
            if cur_d > 0:
                num_decode = max(num_decode, cur_d)
            if burning and "burn" not in "".join(reasons):
                reasons.append("burn_hold")

        num_prefill = min(max(num_prefill, cfg.min_prefill), cfg.max_prefill)
        num_decode = min(max(num_decode, cfg.min_decode), cfg.max_decode)
        want_prefill, want_decode = num_prefill, num_decode

        # chip budget: shrink the larger pool first
        while (
            num_prefill * cfg.chips_per_prefill + num_decode * cfg.chips_per_decode
            > cfg.max_total_chips
        ):
            if num_prefill * cfg.chips_per_prefill >= num_decode * cfg.chips_per_decode and num_prefill > cfg.min_prefill:
                num_prefill -= 1
            elif num_decode > cfg.min_decode:
                num_decode -= 1
            else:
                break

        # rebalance at the budget: the clamped pool stays starved while the
        # other pool idles below the occupancy bar and its own objective is
        # quiet — shift one replica toward the burn instead of giving up
        if cfg.rebalance and cfg.burn_upscale > 0:
            prefill_starved = (
                want_prefill > num_prefill and burn["ttft"] > cfg.burn_upscale
            )
            decode_starved = (
                want_decode > num_decode and burn["itl"] > cfg.burn_upscale
            )
            def _fits(p: int, d: int) -> bool:
                return (
                    p * cfg.chips_per_prefill + d * cfg.chips_per_decode
                    <= cfg.max_total_chips
                )

            # slice guard: with both pools' placements known and sharing no
            # slice, the moved replica would land a DCN hop away from every
            # partner — the transfer bill eats what the rebalance buys
            cross_slice = (
                cfg.rebalance_slice_aware
                and self._prefill_slices and self._decode_slices
                and not set(self._prefill_slices) & set(self._decode_slices)
            )
            if cross_slice and (prefill_starved or decode_starved):
                reasons.append("rebalance_blocked_cross_slice")
            elif (
                prefill_starved and not decode_starved
                and num_decode > cfg.min_decode
                and self._decode_occ < cfg.rebalance_occupancy
                and burn["itl"] <= cfg.burn_hold
                and _fits(num_prefill + 1, num_decode - 1)
            ):
                num_decode -= 1
                num_prefill += 1
                reasons.append("rebalance_to_prefill")
            elif (
                decode_starved and not prefill_starved
                and num_prefill > cfg.min_prefill
                and self._prefill_occ < cfg.rebalance_occupancy
                and burn["ttft"] <= cfg.burn_hold
                and _fits(num_prefill - 1, num_decode + 1)
            ):
                num_prefill -= 1
                num_decode += 1
                reasons.append("rebalance_to_decode")

        # arm the cooldown when the decision grows a pool past the current
        # fleet (only meaningful when the current shape is known)
        if cfg.cooldown_s > 0 and (
            (cur_p > 0 and num_prefill > cur_p) or (cur_d > 0 and num_decode > cur_d)
        ):
            self._cooldown_until = now + cfg.cooldown_s

        reason = "+".join(reasons) if reasons else "load"
        decision = PlannerDecision(num_prefill=num_prefill, num_decode=num_decode, reason=reason)
        self.last_decision = decision
        return decision

    async def step(
        self, sample: WorkloadSample, now: float | None = None
    ) -> PlannerDecision:
        self.observe(sample)
        decision = self.plan(now=now)
        await self.connector.scale(decision)
        if self.state_publisher is not None:
            try:
                await self.state_publisher.publish_decision(self, decision)
            except Exception:  # noqa: BLE001 — observability must not stop scaling
                logger.exception("planner state publish failed")
        return decision

    # -- loop mode -----------------------------------------------------------
    def start(self, metrics_source) -> None:
        """metrics_source: async callable returning WorkloadSample."""
        self.metrics_source = metrics_source
        self._task = spawn_logged(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                sample = await self.metrics_source()
                decision = await self.step(sample)
                logger.info(
                    "planner: rate=%.2f → prefill=%d decode=%d (%s)",
                    sample.request_rate, decision.num_prefill, decision.num_decode,
                    decision.reason,
                )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("planner step failed")
            await asyncio.sleep(self.config.adjustment_interval_s)
