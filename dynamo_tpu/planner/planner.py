"""Planner core loop.

Every ``adjustment_interval``: observe (request rate, ISL/OSL, TTFT/ITL) →
apply correction factors vs the profile → predict next-interval load →
compute required prefill/decode replicas → scale via the connector, within
min/max bounds and chip budget (reference: planner_core.py:162-240,
planner_sla.py:115).

Disaggregation-aware: prefill replicas are sized from predicted prompt
tokens/s against profiled prefill throughput; decode replicas from predicted
generated tokens/s against profiled decode throughput (degraded by the
observed correction factor).
"""

from __future__ import annotations

import asyncio
import math
from dataclasses import dataclass, field

from dynamo_tpu.planner.load_predictor import make_predictor
from dynamo_tpu.planner.perf_interpolation import PerfProfile
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("planner")


@dataclass
class WorkloadSample:
    request_rate: float        # req/s
    avg_isl: float             # prompt tokens/request
    avg_osl: float             # generated tokens/request
    ttft_s: float = 0.0
    itl_s: float = 0.0
    # Observed fleet utilization (observability/perf.py via the metrics
    # service): when present, the planner sizes replicas from REAL measured
    # per-replica throughput instead of interpolating the offline profile —
    # the profile stays as bootstrap and fallback.
    observed_prefill_tok_s: float = 0.0   # fleet prompt tokens/s actually served
    observed_decode_tok_s: float = 0.0    # fleet emitted tokens/s (goodput)
    num_prefill_replicas: int = 0
    num_decode_replicas: int = 0
    # mean decode-lane occupancy across the fleet: observed throughput only
    # counts as CAPACITY when measured near saturation (an idle replica's
    # low goodput is headroom, not a ceiling)
    avg_occupancy: float = 0.0


def sample_from_endpoints(
    endpoints,
    *,
    request_rate: float,
    avg_isl: float,
    avg_osl: float,
    ttft_s: float = 0.0,
    itl_s: float = 0.0,
) -> WorkloadSample:
    """Build a WorkloadSample from a live fleet snapshot
    (llm/kv_router/metrics_aggregator.ProcessedEndpoints): per-worker
    goodput sums into the observed capacity terms.  Single-pool (non-disagg)
    deployments report the same worker set for both roles; the planner only
    consumes the role it scales."""
    workers = list(getattr(endpoints, "workers", {}).values())
    goodput = sum(getattr(m, "goodput_tokens_per_second", 0.0) for m in workers)
    prefill = sum(getattr(m, "prefill_tokens_per_second", 0.0) for m in workers)
    occupancy = (
        sum(getattr(m, "batch_occupancy_perc", 0.0) for m in workers) / len(workers)
        if workers else 0.0
    )
    return WorkloadSample(
        avg_occupancy=occupancy,
        request_rate=request_rate,
        avg_isl=avg_isl,
        avg_osl=avg_osl,
        ttft_s=ttft_s,
        itl_s=itl_s,
        observed_prefill_tok_s=prefill,
        observed_decode_tok_s=goodput,
        num_prefill_replicas=len(workers),
        num_decode_replicas=len(workers),
    )


@dataclass
class PlannerConfig:
    adjustment_interval_s: float = 30.0
    predictor: str = "ewma"
    min_prefill: int = 1
    max_prefill: int = 8
    min_decode: int = 1
    max_decode: int = 8
    max_total_chips: int = 16
    chips_per_prefill: int = 1
    chips_per_decode: int = 1
    # SLA targets (0 disables the SLA term)
    ttft_target_s: float = 0.0
    itl_target_s: float = 0.0
    scale_down_headroom: float = 1.3   # keep 30% slack before scaling down
    # min fleet decode-lane occupancy for an observed-throughput sample to
    # update the capacity estimate (see WorkloadSample.avg_occupancy)
    saturation_occupancy: float = 0.8


@dataclass
class PlannerDecision:
    num_prefill: int
    num_decode: int
    reason: str = ""


class Planner:
    def __init__(
        self,
        profile: PerfProfile,
        connector,
        config: PlannerConfig | None = None,
    ):
        self.profile = profile
        self.connector = connector
        self.config = config or PlannerConfig()
        self._rate_pred = make_predictor(self.config.predictor)
        self._isl_pred = make_predictor(self.config.predictor)
        self._osl_pred = make_predictor(self.config.predictor)
        # correction factors: observed perf / profiled perf (reference:
        # planner_core.py correction factors)
        self._ttft_correction = 1.0
        self._itl_correction = 1.0
        # observed per-replica throughput (EWMA over samples that carried
        # utilization): replaces the profile interpolation as the capacity
        # denominator once real measurements exist
        self._prefill_cap_obs = 0.0
        self._decode_cap_obs = 0.0
        self.last_decision: PlannerDecision | None = None
        self._task: asyncio.Task | None = None
        self.metrics_source = None  # set for loop mode

    # -- one planning step -------------------------------------------------
    def observe(self, sample: WorkloadSample) -> None:
        self._rate_pred.observe(sample.request_rate)
        self._isl_pred.observe(sample.avg_isl)
        self._osl_pred.observe(sample.avg_osl)
        if sample.ttft_s > 0:
            expected = self.profile.ttft_s(sample.avg_isl, sample.avg_osl)
            if expected > 0:
                self._ttft_correction = sample.ttft_s / expected
        if sample.itl_s > 0:
            expected = self.profile.itl_s(sample.avg_isl, sample.avg_osl)
            if expected > 0:
                self._itl_correction = sample.itl_s / expected
        # real utilization (when the sample carries it): EWMA of measured
        # per-replica throughput.  Only samples with actual flow update it —
        # an idle interval says nothing about capacity.
        alpha = 0.5
        if sample.avg_occupancy < self.config.saturation_occupancy:
            return
        if sample.num_prefill_replicas > 0 and sample.observed_prefill_tok_s > 0:
            per_replica = sample.observed_prefill_tok_s / sample.num_prefill_replicas
            self._prefill_cap_obs = (
                per_replica if self._prefill_cap_obs == 0
                else alpha * per_replica + (1 - alpha) * self._prefill_cap_obs
            )
        if sample.num_decode_replicas > 0 and sample.observed_decode_tok_s > 0:
            per_replica = sample.observed_decode_tok_s / sample.num_decode_replicas
            self._decode_cap_obs = (
                per_replica if self._decode_cap_obs == 0
                else alpha * per_replica + (1 - alpha) * self._decode_cap_obs
            )

    def plan(self) -> PlannerDecision:
        cfg = self.config
        rate = self._rate_pred.predict()
        isl = max(self._isl_pred.predict(), 1.0)
        osl = max(self._osl_pred.predict(), 1.0)

        prefill_demand = rate * isl          # prompt tokens/s
        decode_demand = rate * osl           # generated tokens/s

        # capacity: measured per-replica throughput at saturation beats the
        # offline profile; the profile bootstraps and serves cold fleets
        prefill_capacity = self._prefill_cap_obs or (
            self.profile.prefill_tok_s(isl, osl) / max(self._ttft_correction, 1e-6)
        )
        decode_capacity = self._decode_cap_obs or (
            self.profile.decode_tok_s(isl, osl) / max(self._itl_correction, 1e-6)
        )

        num_prefill = math.ceil(prefill_demand / max(prefill_capacity, 1e-6) * cfg.scale_down_headroom) if prefill_demand else cfg.min_prefill
        num_decode = math.ceil(decode_demand / max(decode_capacity, 1e-6) * cfg.scale_down_headroom) if decode_demand else cfg.min_decode

        # SLA escalation: if observed latency breaches target, add capacity
        reason = "load"
        if cfg.ttft_target_s and self._ttft_correction * self.profile.ttft_s(isl, osl) > cfg.ttft_target_s:
            num_prefill += 1
            reason = "ttft_sla"
        if cfg.itl_target_s and self._itl_correction * self.profile.itl_s(isl, osl) > cfg.itl_target_s:
            num_decode += 1
            reason = "itl_sla" if reason == "load" else "ttft+itl_sla"

        num_prefill = min(max(num_prefill, cfg.min_prefill), cfg.max_prefill)
        num_decode = min(max(num_decode, cfg.min_decode), cfg.max_decode)

        # chip budget: shrink the larger pool first
        while (
            num_prefill * cfg.chips_per_prefill + num_decode * cfg.chips_per_decode
            > cfg.max_total_chips
        ):
            if num_prefill * cfg.chips_per_prefill >= num_decode * cfg.chips_per_decode and num_prefill > cfg.min_prefill:
                num_prefill -= 1
            elif num_decode > cfg.min_decode:
                num_decode -= 1
            else:
                break

        decision = PlannerDecision(num_prefill=num_prefill, num_decode=num_decode, reason=reason)
        self.last_decision = decision
        return decision

    async def step(self, sample: WorkloadSample) -> PlannerDecision:
        self.observe(sample)
        decision = self.plan()
        await self.connector.scale(decision)
        return decision

    # -- loop mode -----------------------------------------------------------
    def start(self, metrics_source) -> None:
        """metrics_source: async callable returning WorkloadSample."""
        self.metrics_source = metrics_source
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                sample = await self.metrics_source()
                decision = await self.step(sample)
                logger.info(
                    "planner: rate=%.2f → prefill=%d decode=%d (%s)",
                    sample.request_rate, decision.num_prefill, decision.num_decode,
                    decision.reason,
                )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                logger.exception("planner step failed")
            await asyncio.sleep(self.config.adjustment_interval_s)
