"""Planner connectors: apply scaling decisions to a deployment substrate
(reference: components/planner local_connector.py (circus) and
kubernetes_connector.py (CRD scaling))."""

from __future__ import annotations

from dynamo_tpu.planner.planner import PlannerDecision
from dynamo_tpu.sdk.supervisor import ProcessSupervisor
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("planner.connectors")


class LocalConnector:
    """Scales prefill/decode worker replicas under the local supervisor."""

    def __init__(
        self,
        supervisor: ProcessSupervisor,
        *,
        prefill_watcher: str = "prefill",
        decode_watcher: str = "decode",
    ):
        self.supervisor = supervisor
        self.prefill_watcher = prefill_watcher
        self.decode_watcher = decode_watcher

    async def scale(self, decision: PlannerDecision) -> None:
        await self.supervisor.set_replicas(self.prefill_watcher, decision.num_prefill)
        await self.supervisor.set_replicas(self.decode_watcher, decision.num_decode)


class RecordingConnector:
    """Test/dry-run connector: records decisions."""

    def __init__(self) -> None:
        self.decisions: list[PlannerDecision] = []

    async def scale(self, decision: PlannerDecision) -> None:
        self.decisions.append(decision)


class KubernetesConnector:
    """Emits scale patches for DynamoGraphDeployment-style CRs.  Without a
    cluster in this environment, the connector renders the patch bodies and
    hands them to an injectable ``apply`` callable (kubectl/API client in
    production)."""

    def __init__(self, apply, *, namespace: str = "default", deployment: str = "dynamo"):
        self._apply = apply
        self.namespace = namespace
        self.deployment = deployment

    async def scale(self, decision: PlannerDecision) -> None:
        for component, replicas in (
            ("prefill-worker", decision.num_prefill),
            ("decode-worker", decision.num_decode),
        ):
            await self._apply(
                {
                    "apiVersion": "dynamo.tpu/v1alpha1",
                    "kind": "DynamoComponentDeployment",
                    "metadata": {
                        "name": f"{self.deployment}-{component}",
                        "namespace": self.namespace,
                    },
                    "spec": {"replicas": replicas},
                }
            )
