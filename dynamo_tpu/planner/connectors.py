"""Planner connectors: apply scaling decisions to a deployment substrate
(reference: components/planner local_connector.py (circus) and
kubernetes_connector.py (CRD scaling))."""

from __future__ import annotations

from dynamo_tpu.planner.planner import PlannerDecision
from dynamo_tpu.sdk.supervisor import ProcessSupervisor
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("planner.connectors")


class LocalConnector:
    """Scales prefill/decode worker replicas under the local supervisor."""

    def __init__(
        self,
        supervisor: ProcessSupervisor,
        *,
        prefill_watcher: str = "prefill",
        decode_watcher: str = "decode",
    ):
        self.supervisor = supervisor
        self.prefill_watcher = prefill_watcher
        self.decode_watcher = decode_watcher

    async def scale(self, decision: PlannerDecision) -> None:
        await self.supervisor.set_replicas(self.prefill_watcher, decision.num_prefill)
        await self.supervisor.set_replicas(self.decode_watcher, decision.num_decode)


class RecordingConnector:
    """Test/dry-run connector: records decisions."""

    def __init__(self) -> None:
        self.decisions: list[PlannerDecision] = []

    async def scale(self, decision: PlannerDecision) -> None:
        self.decisions.append(decision)


class KubernetesConnector:
    """Scales a DynamoGraphDeployment by patching ``spec.services.<name>
    .replicas`` on the GRAPH CR through a :class:`deploy.operator.KubeClient`
    (FakeKube in tests, KubectlClient against a cluster) — the operator's
    watch then reconciles the change into component CRs and Deployments.

    Patching the graph (not the child component CRs) mirrors the reference
    (components/planner/src/dynamo/planner/kubernetes_connector.py:36-43
    update_graph_replicas) and is what makes the change durable: the
    operator re-renders children from the graph spec on every reconcile,
    so a child-level patch would be overwritten at the next resync.
    """

    def __init__(
        self,
        kube,
        *,
        namespace: str = "default",
        graph: str = "dynamo",
        prefill_service: str = "prefill-worker",
        decode_service: str = "decode-worker",
    ):
        self.kube = kube
        self.namespace = namespace
        self.graph = graph
        self.prefill_service = prefill_service
        self.decode_service = decode_service

    async def scale(self, decision: PlannerDecision) -> None:
        import copy

        from dynamo_tpu.deploy.crds import DynamoGraphDeployment

        fetched = await self.kube.get(
            DynamoGraphDeployment.kind, self.namespace, self.graph
        )
        if fetched is None:
            raise ValueError(
                f"graph {self.graph!r} not found in namespace {self.namespace!r}"
            )
        # re-apply only what a client owns: apiVersion/kind/name/labels/spec.
        # Echoing back server-populated fields (status, resourceVersion,
        # managedFields from a kubectl get) would turn this read-modify-write
        # into a lost-update/conflict hazard against a live cluster.
        manifest = {
            "apiVersion": fetched.get("apiVersion", "dynamo.tpu/v1alpha1"),
            "kind": fetched.get("kind", DynamoGraphDeployment.kind),
            "metadata": {
                "name": self.graph,
                "namespace": self.namespace,
                **(
                    {"labels": fetched["metadata"]["labels"]}
                    if fetched.get("metadata", {}).get("labels")
                    else {}
                ),
            },
            "spec": copy.deepcopy(fetched.get("spec", {})),
        }
        services = manifest["spec"].setdefault("services", {})
        changed = False
        for svc_name, replicas in (
            (self.prefill_service, decision.num_prefill),
            (self.decode_service, decision.num_decode),
        ):
            svc = services.get(svc_name)
            if svc is None:
                logger.warning(
                    "graph %s has no service %r; skipping scale", self.graph, svc_name
                )
                continue
            if svc.get("replicas", 1) != replicas:
                svc["replicas"] = replicas
                changed = True
        if changed:
            await self.kube.apply(manifest)
