"""Autoscaling planner.

Observes load + SLA metrics, predicts the next interval, computes required
prefill/decode replica counts from profiled performance, and scales through
a connector (reference: components/planner — load-based planner_core.py and
SLA planner_sla.py, predictors utils/load_predictor.py, interpolation
utils/perf_interpolation.py, connectors local/kubernetes).
"""

from dynamo_tpu.planner.load_predictor import (
    ConstantPredictor,
    EwmaPredictor,
    LinearTrendPredictor,
    make_predictor,
)
from dynamo_tpu.planner.perf_interpolation import PerfProfile, ProfilePoint
from dynamo_tpu.planner.planner import Planner, PlannerConfig, PlannerDecision

__all__ = [
    "ConstantPredictor",
    "EwmaPredictor",
    "LinearTrendPredictor",
    "make_predictor",
    "PerfProfile",
    "ProfilePoint",
    "Planner",
    "PlannerConfig",
    "PlannerDecision",
]
