"""Autoscaling planner.

Observes load + SLA metrics, predicts the next interval, computes required
prefill/decode replica counts from profiled performance, and scales through
a connector (reference: components/planner — load-based planner_core.py and
SLA planner_sla.py, predictors utils/load_predictor.py, interpolation
utils/perf_interpolation.py, connectors local/kubernetes).

SLO-native autopilot: WorkloadSample carries frontend burn rates and
per-pool occupancy (sample_from_endpoints / burn_rates_from_slo), plan()
escalates the burning pool and rebalances prefill↔decode at the chip
budget, and state.PlannerStatePublisher mirrors every executed decision to
the metrics service's dyn_planner_* gauges.
"""

from dynamo_tpu.planner.defrag import DefragConfig, Defragmenter
from dynamo_tpu.planner.load_predictor import (
    ConstantPredictor,
    EwmaPredictor,
    LinearTrendPredictor,
    make_predictor,
    replay_trace,
)
from dynamo_tpu.planner.perf_interpolation import PerfProfile, ProfilePoint
from dynamo_tpu.planner.planner import (
    Planner,
    PlannerConfig,
    PlannerDecision,
    WorkloadSample,
    burn_rates_from_slo,
    sample_from_endpoints,
)
from dynamo_tpu.planner.state import (
    PLANNER_STATE_EVENT,
    PlannerStateEvent,
    PlannerStatePublisher,
)

__all__ = [
    "ConstantPredictor",
    "DefragConfig",
    "Defragmenter",
    "EwmaPredictor",
    "LinearTrendPredictor",
    "make_predictor",
    "replay_trace",
    "PerfProfile",
    "ProfilePoint",
    "Planner",
    "PlannerConfig",
    "PlannerDecision",
    "WorkloadSample",
    "burn_rates_from_slo",
    "sample_from_endpoints",
    "PLANNER_STATE_EVENT",
    "PlannerStateEvent",
    "PlannerStatePublisher",
]
