"""Planner decision state on the event bus.

The planner is a control loop; the metrics service is the observability
plane.  They meet here: after every executed decision the planner publishes
a ``PlannerStateEvent`` on the component's ``planner_state`` event subject,
and the metrics service mirrors the latest event into the
``dyn_planner_{target_replicas,observed_capacity_tok_s,burn_rate_input}``
gauges so `dyn_top` and Prometheus can see WHAT the autopilot decided and
WHY (burn input, per-pool capacity estimates) without scraping the planner
process itself.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

PLANNER_STATE_EVENT = "planner_state"


@dataclass
class PlannerStateEvent:
    target_prefill: int = 0
    target_decode: int = 0
    # observed per-replica capacity estimates (EWMA at saturation)
    observed_prefill_tok_s: float = 0.0
    observed_decode_tok_s: float = 0.0
    # the worst per-objective burn rate the planner consumed for this decision
    burn_rate_input: float = 0.0
    reason: str = ""
    ts: float = 0.0

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes | str) -> "PlannerStateEvent":
        data = json.loads(raw)
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in data.items() if k in known})


def event_from_planner(planner, decision, ts: float = 0.0) -> PlannerStateEvent:
    """Snapshot a planner + its latest decision into an event."""
    return PlannerStateEvent(
        target_prefill=decision.num_prefill,
        target_decode=decision.num_decode,
        observed_prefill_tok_s=planner.observed_prefill_capacity,
        observed_decode_tok_s=planner.observed_decode_capacity,
        burn_rate_input=planner.worst_burn_input,
        reason=decision.reason,
        ts=ts,
    )


class PlannerStatePublisher:
    """Publishes planner decisions on ``component.event_subject("planner_state")``.

    Attach to a Planner via ``planner.state_publisher = PlannerStatePublisher(comp)``;
    ``Planner.step`` calls :meth:`publish_decision` after each executed scale.
    """

    def __init__(self, component, clock=None):
        self._component = component
        self._clock = clock
        self.published: list[PlannerStateEvent] = []

    @property
    def subject(self) -> str:
        return self._component.event_subject(PLANNER_STATE_EVENT)

    async def publish(self, event: PlannerStateEvent) -> None:
        self.published.append(event)
        bus = self._component.runtime.plane.bus
        await bus.publish(self.subject, event.to_json())

    async def publish_decision(self, planner, decision) -> None:
        ts = self._clock() if self._clock is not None else 0.0
        await self.publish(event_from_planner(planner, decision, ts=ts))
