"""Scenario soak engine: adversarial production traffic against a live
routed fleet, with the SLO-native planner autopilot steering mid-soak.

- ``spec``: declarative JSON scenario format (phases × traffic shapes ×
  chaos fault schedules × SLO burn assertions)
- ``traffic``: shape → deterministic arrival/session plans
- ``fleet``: SoakFleet — live scalable mocker pools + metrics/frontend surface
- ``runner``: ScenarioRunner — drive, sample, steer, assert, produce the
  SCENARIO_SOAK.json artifact

Run the shipped soak: ``python -m dynamo_tpu.scenarios.soak``.
"""

from dynamo_tpu.scenarios.spec import (
    AutopilotSpec,
    FaultEvent,
    FleetSpec,
    Phase,
    PhaseAssertions,
    ScenarioSpec,
    SloSpec,
    TrafficShape,
    builtin_spec_path,
)

__all__ = [
    "AutopilotSpec",
    "FaultEvent",
    "FleetSpec",
    "Phase",
    "PhaseAssertions",
    "ScenarioSpec",
    "SloSpec",
    "TrafficShape",
    "builtin_spec_path",
]
