"""Traffic shapes → concrete arrival plans.

Given a Phase's TrafficShape and a seed, produce the deterministic list of
request arrivals (phase-relative simulated seconds) and, for session
swarms, the closed-loop multi-turn sessions.  Open-loop kinds draw Poisson
arrivals against a (possibly time-varying) rate function; the swarm reuses
bench.data_generator's session synthesizer so the soak's multi-turn traffic
is the same shape the routing benchmarks replay.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field, replace

from dynamo_tpu.bench.data_generator import SessionConfig, generate_sessions
from dynamo_tpu.scenarios.spec import Phase, TrafficShape

VOCAB = 8_000  # small enough for fast mocker hashing, big enough to not collide


@dataclass
class Arrival:
    at_s: float                  # phase-relative simulated seconds
    isl: int
    osl: int
    kind: str = "plain"          # plain | long | guided


@dataclass
class PhasePlan:
    arrivals: list = field(default_factory=list)   # [Arrival] open-loop
    sessions: list = field(default_factory=list)   # [Session] closed-loop

    @property
    def expected_requests(self) -> int:
        return len(self.arrivals) + sum(len(s.turns) for s in self.sessions)


def _rate_at(shape: TrafficShape, t: float) -> float:
    """Instantaneous arrival rate (req / sim-s) at phase time ``t``."""
    if shape.kind == "burst":
        in_burst = (
            shape.burst_duration_s > 0
            and shape.burst_start_s <= t < shape.burst_start_s + shape.burst_duration_s
        )
        return shape.burst_rate if in_burst else shape.rate
    if shape.kind == "diurnal":
        peak = shape.peak_rate or shape.rate
        period = shape.period_s or 1.0
        # sinusoid between rate (trough) and peak_rate (crest) — a whole
        # diurnal cycle compressed into period_s simulated seconds
        mid = (shape.rate + peak) / 2.0
        amp = (peak - shape.rate) / 2.0
        return max(mid + amp * math.sin(2 * math.pi * t / period), 0.0)
    return shape.rate


def _poisson_arrivals(shape: TrafficShape, duration_s: float,
                      rng: random.Random) -> list[float]:
    """Thinning sampler for an inhomogeneous Poisson process: draw at the
    envelope rate, keep each point with prob rate(t)/envelope."""
    envelope = max(
        shape.rate, shape.burst_rate, shape.peak_rate, 1e-9
    )
    out: list[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(envelope)
        if t >= duration_s:
            return out
        if rng.random() * envelope <= _rate_at(shape, t):
            out.append(t)


def plan_phase(phase: Phase, seed: int) -> PhasePlan:
    """Deterministic arrival plan for one phase."""
    shape = phase.traffic
    rng = random.Random((seed, phase.name).__repr__())

    if shape.kind == "session_swarm":
        sessions = generate_sessions(SessionConfig(
            num_sessions=shape.num_sessions,
            turns_per_session=shape.turns_per_session,
            session_rate=shape.session_rate,
            system_tokens=shape.system_tokens,
            user_tokens_per_turn=shape.isl,
            turn_gap_mean_s=shape.turn_gap_s,
            osl=shape.osl,
            vocab_size=VOCAB,
            seed=rng.randrange(1 << 30),
        ))
        # clamp session starts into the phase window so the swarm actually
        # lands inside the phase it describes
        sessions = [
            replace(s, start_s=min(s.start_s, max(phase.duration_s - 1e-3, 0.0)))
            for s in sessions
        ]
        return PhasePlan(sessions=sessions)

    if shape.requests > 0:
        # closed count (chaos_smoke phases): evenly spaced at 1/rate
        gap = 1.0 / max(shape.rate, 1e-9)
        times = [i * gap for i in range(shape.requests)]
    else:
        times = _poisson_arrivals(shape, phase.duration_s, rng)

    arrivals: list[Arrival] = []
    for t in times:
        isl, osl, kind = shape.isl, shape.osl, "plain"
        if shape.kind == "long_context" and rng.random() < shape.long_fraction:
            isl = shape.isl_long or shape.isl * 8
            kind = "long"
        elif shape.kind == "guided_mix" and rng.random() < shape.guided_fraction:
            osl = shape.osl_guided or shape.osl * 2
            kind = "guided"
        arrivals.append(Arrival(at_s=t, isl=isl, osl=osl, kind=kind))
    return PhasePlan(arrivals=arrivals)


def prompt_tokens(n: int, rng: random.Random) -> list[int]:
    return [rng.randrange(10, VOCAB) for _ in range(n)]
