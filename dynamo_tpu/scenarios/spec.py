"""Declarative scenario specs for adversarial fleet soaks.

A scenario is a JSON document: fleet shape + SLO definition + autopilot
knobs + an ordered list of PHASES.  Each phase names a traffic shape
(constant, diurnal burst, multi-turn session swarm, long-context
stragglers, guided/speculative mixes), an optional chaos schedule (fault
events that arm the ``DYN_FAULTS`` registry mid-phase), and the assertions
that must hold when the phase drains: per-objective SLO burn-rate ceilings,
an MFU/goodput floor, and a completion floor.

All times and rates in a spec are SIMULATED seconds — the runner compresses
them by ``speedup`` exactly like the mocker's cost model, so one spec means
the same workload at any compression.

The same format feeds both ends of the chaos story: the tier-1 chaos gate
(scripts/chaos_smoke.py loads its canned phases from
``specs/chaos_smoke.json``) and the full scenario soak
(``specs/default_soak.json`` → SCENARIO_SOAK.json artifact).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from pathlib import Path

TRAFFIC_KINDS = (
    "constant", "burst", "diurnal", "session_swarm", "long_context",
    "guided_mix",
)


@dataclass
class TrafficShape:
    """One phase's arrival process (simulated seconds / req per sim-s)."""

    kind: str = "constant"
    rate: float = 2.0              # req/s (base rate for burst/diurnal)
    isl: int = 96                  # prompt tokens/request
    osl: int = 24                  # generated tokens/request
    # burst: a rate spike inside the phase
    burst_rate: float = 0.0
    burst_start_s: float = 0.0
    burst_duration_s: float = 0.0
    # diurnal: sinusoid between rate and peak_rate with this period
    peak_rate: float = 0.0
    period_s: float = 0.0
    # session_swarm: multi-turn chat sessions (bench.data_generator); the
    # swarm is CLOSED-loop per session — turn n+1 waits for turn n
    num_sessions: int = 0
    turns_per_session: int = 3
    session_rate: float = 2.0      # Poisson session starts / sim-s
    system_tokens: int = 64
    turn_gap_s: float = 1.0
    # long_context: fraction of arrivals that are stragglers with isl_long
    long_fraction: float = 0.0
    isl_long: int = 0
    # guided_mix: fraction of requests tagged guided/speculative — they pay
    # a longer decode (osl_guided) like constrained decoding does
    guided_fraction: float = 0.0
    osl_guided: int = 0
    # closed request count (chaos_smoke phases): exactly this many
    # arrivals, spaced by 1/rate — 0 means open-loop rate × duration
    requests: int = 0

    def validate(self) -> None:
        if self.kind not in TRAFFIC_KINDS:
            raise ValueError(
                f"unknown traffic kind {self.kind!r} (want one of {TRAFFIC_KINDS})"
            )
        if self.kind == "session_swarm" and self.num_sessions <= 0:
            raise ValueError("session_swarm traffic needs num_sessions > 0")
        if self.kind == "long_context" and not (0 < self.long_fraction <= 1):
            raise ValueError("long_context traffic needs 0 < long_fraction <= 1")


@dataclass
class FaultEvent:
    """Arm a ``DYN_FAULTS`` schedule at a phase-relative simulated time.

    ``schedule`` uses the registry grammar (robustness/faults.py):
    ``point:trigger[:opt=val...]`` joined by ``;`` — e.g.
    ``worker.generate:nth=3`` or ``cp.recv:once``."""

    at_s: float = 0.0
    schedule: str = ""

    def validate(self) -> None:
        from dynamo_tpu.robustness.faults import parse_faults

        if not self.schedule:
            raise ValueError("fault event needs a schedule")
        parse_faults(self.schedule)  # raises on bad grammar


@dataclass
class WorkerKillEvent:
    """Take one live worker out of a pool at a phase-relative simulated time.

    ``mode="kill"`` is abrupt: the worker's lease is revoked and its handler
    tasks are cancelled with no grace, so in-flight streams break mid-stream
    and the dispatcher's generation journal must resume them on a peer.
    ``mode="drain"`` runs the graceful drain state machine instead (the
    operator/scale-down path)."""

    at_s: float = 0.0
    pool: str = "decode"
    mode: str = "kill"

    def validate(self) -> None:
        if self.mode not in ("kill", "drain"):
            raise ValueError(
                f"worker kill mode must be kill|drain, got {self.mode!r}"
            )


@dataclass
class MigrationEvent:
    """Migrate up to ``count`` live decode sessions at a phase-relative
    simulated time — the runner walks the dispatcher's migration registry
    and asks the coordinator to move each one to its cheapest-hop healthy
    destination (runtime/migration.py).  ``reason`` other than "manual"
    also authorizes DCN-hop destinations, mirroring ``dynctl migrate``."""

    at_s: float = 0.0
    count: int = 1
    reason: str = "manual"

    def validate(self) -> None:
        if self.count <= 0:
            raise ValueError("migration event needs count > 0")


@dataclass
class PhaseAssertions:
    """What must hold when the phase drains.  Burn-rate ceilings are
    evaluated on PHASE-LOCAL counts ((bad/total)/budget over exactly the
    phase's observations), so one phase's damage cannot fail its neighbor.
    Zero/empty disables a check."""

    max_burn_rate: dict = field(default_factory=dict)  # objective → ceiling
    min_goodput_tok_s: float = 0.0   # mean fleet goodput over phase ticks
    min_mfu: float = 0.0             # mean fleet MFU over phase ticks
    min_completed: int = 0
    # topology-aware routing (fleet.slices): floor on the fraction of this
    # phase's KV-router selections that landed on a worker in the NEAR
    # slice (the prefill pool's slice) — the multi-slice soak's proof that
    # discovered link classes steer decode selection
    min_near_slice_fraction: float = 0.0
    # live migration: floor on sessions COMMITTED to a new worker during
    # this phase (migration events, drain integration, or planner defrag)
    min_migrations_committed: int = 0
    # ceiling on client-visible failed requests; -1 disables the check
    # (0 demands the migration soak's hard "zero failed requests")
    max_failed: int = -1


@dataclass
class Phase:
    name: str = "phase"
    duration_s: float = 10.0         # simulated seconds
    traffic: TrafficShape = field(default_factory=TrafficShape)
    faults: list = field(default_factory=list)        # [FaultEvent]
    worker_kills: list = field(default_factory=list)  # [WorkerKillEvent]
    migrations: list = field(default_factory=list)    # [MigrationEvent]
    assertions: PhaseAssertions = field(default_factory=PhaseAssertions)

    def validate(self) -> None:
        if self.duration_s <= 0:
            raise ValueError(f"phase {self.name!r}: duration_s must be > 0")
        self.traffic.validate()
        for ev in self.faults:
            ev.validate()
        for ev in self.worker_kills:
            ev.validate()
        for ev in self.migrations:
            ev.validate()


@dataclass
class SloSpec:
    """Maps onto observability/slo.SloConfig — thresholds in SIMULATED
    seconds (the runner feeds the tracker a simulated clock)."""

    ttft_s: float = 0.5
    ttft_target: float = 0.9
    itl_s: float = 0.1
    itl_target: float = 0.9
    error_target: float = 0.99
    windows_s: list = field(default_factory=lambda: [5.0, 20.0])
    shed_burn: float = 0.0


@dataclass
class FleetSpec:
    """The fleet under test: named pools served on one endpoint."""

    pools: dict = field(default_factory=lambda: {"prefill": 1, "decode": 1})
    policy: str = "kv"               # "kv" (KV-affine) or "random"
    block_size: int = 16
    num_blocks: int = 512
    max_batch_size: int = 8
    metrics_period_s: float = 0.25   # simulated seconds
    mocker: dict = field(default_factory=dict)   # MockerConfig overrides
    # "mocker" (cost-model sim — how scenarios usually run) or "jax": REAL
    # JaxLlmEngine workers stepping the actual model/scheduler/allocator
    # hot path.  jax mode requires the scenario's speedup to be 1.0 — real
    # engines serve in real time, so compressed arrivals would soak the
    # queue, not the system (same rule as bench.routed_fleet.FleetConfig).
    engine: str = "mocker"
    # jax mode: engine context window; size it to the workload's longest
    # prompt+generation (bucket ladder tops out here)
    max_model_len: int = 512
    # emulated multi-slice placement: pool → list of slice labels assigned
    # round-robin to that pool's workers (published as TopologyCards, so the
    # fleet's KV router discovers the link classes).  Empty = single slice
    # (the topology plane sees an all-local map and changes nothing).
    slices: dict = field(default_factory=dict)
    # mocker-side per-pair latency: hop class → extra simulated seconds each
    # prefill pays on a worker behind that link (the KV-transfer bill a far
    # slice really pays; see MockerConfig.transfer_delay_s)
    link_delay_s: dict = field(default_factory=dict)

    def validate(self) -> None:
        if self.policy not in ("kv", "random"):
            raise ValueError(f"fleet policy must be kv|random, got {self.policy!r}")
        if self.engine not in ("mocker", "jax"):
            raise ValueError(
                f"fleet engine must be mocker|jax, got {self.engine!r}"
            )
        if not self.pools or any(n < 0 for n in self.pools.values()):
            raise ValueError("fleet pools must map name → replicas >= 0")
        if any(not labels for labels in self.slices.values()):
            raise ValueError("fleet slices must map pool → non-empty label list")
        bad = set(self.link_delay_s) - {"local", "ici", "dcn"}
        if bad:
            raise ValueError(f"link_delay_s keys must be hop classes, got {sorted(bad)}")


@dataclass
class AutopilotSpec:
    """Planner knobs for the soak (simulated seconds); ``profile`` is the
    optimistic bootstrap PerfProfile — deliberately generous, so any
    mid-soak scale-up is attributable to burn/SLA evidence, not to the
    demand math alone."""

    enabled: bool = True
    interval_s: float = 2.0
    min_prefill: int = 1
    max_prefill: int = 4
    min_decode: int = 1
    max_decode: int = 4
    max_total_chips: int = 8
    burn_upscale: float = 1.0
    burn_hold: float = 0.25
    cooldown_s: float = 6.0
    rebalance: bool = True
    rebalance_occupancy: float = 0.5
    saturation_occupancy: float = 0.8
    scale_down_headroom: float = 1.3
    # bootstrap profile (per-replica): high throughput + low latency means
    # "the current fleet should be fine" until reality disagrees
    profile: dict = field(default_factory=lambda: {
        "prefill_tok_s": 50_000.0, "decode_tok_s": 5_000.0,
        "ttft_s": 0.02, "itl_s": 0.01,
    })
    # acceptance: the soak summary fails unless at least one executed
    # decision was burn/SLA-driven (reason beyond plain "load")
    expect_decision: bool = False
    # planner-driven defragmentation (planner/defrag.py): stepped on the
    # autopilot interval against per-worker KV occupancy, it migrates live
    # sessions off hot workers through the dispatcher's migration
    # coordinator (bounded rate, cooldown, never cross-slice)
    defrag: bool = False
    defrag_spread: float = 0.25
    defrag_min_occupancy: float = 0.5
    defrag_max_per_step: int = 1
    defrag_cooldown_s: float = 8.0


@dataclass
class ScenarioSpec:
    name: str = "scenario"
    seed: int = 0
    speedup: float = 8.0             # sim-time compression (mocker-style)
    tick_s: float = 1.0              # sampling cadence, simulated seconds
    drain_s: float = 10.0            # post-phase drain budget, simulated
    retry_max: int = 2               # runner-side pre-first-token retries
    # check every completed request's streamed tokens against the mocker's
    # deterministic chain — the migration soak's "byte-identical output vs
    # an unmigrated greedy reference" proof (any corruption fails the phase)
    verify_outputs: bool = False
    slo: SloSpec = field(default_factory=SloSpec)
    fleet: FleetSpec = field(default_factory=FleetSpec)
    autopilot: AutopilotSpec = field(default_factory=AutopilotSpec)
    phases: list = field(default_factory=list)        # [Phase]

    def validate(self) -> "ScenarioSpec":
        if not self.phases:
            raise ValueError("scenario needs at least one phase")
        if self.speedup <= 0 or self.tick_s <= 0:
            raise ValueError("speedup and tick_s must be > 0")
        self.fleet.validate()
        if self.fleet.engine == "jax" and self.speedup != 1.0:
            raise ValueError(
                "fleet.engine='jax' requires speedup=1.0: real engines serve "
                "in real time, so compressed arrivals measure queue depth "
                "instead of the system under test"
            )
        names = [p.name for p in self.phases]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate phase names: {names}")
        for p in self.phases:
            p.validate()
        return self

    # -- JSON ---------------------------------------------------------------
    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "ScenarioSpec":
        def _build(dc, payload, casts=None):
            known = dc.__dataclass_fields__
            kwargs = {k: v for k, v in (payload or {}).items() if k in known}
            unknown = set(payload or {}) - set(known)
            if unknown:
                raise ValueError(
                    f"{dc.__name__}: unknown spec keys {sorted(unknown)}"
                )
            for key, fn in (casts or {}).items():
                if key in kwargs:
                    kwargs[key] = fn(kwargs[key])
            return dc(**kwargs)

        phases = [
            _build(
                Phase, p,
                casts={
                    "traffic": lambda t: _build(TrafficShape, t),
                    "faults": lambda fs: [_build(FaultEvent, f) for f in fs],
                    "worker_kills": lambda ks: [
                        _build(WorkerKillEvent, k) for k in ks
                    ],
                    "migrations": lambda ms: [
                        _build(MigrationEvent, m) for m in ms
                    ],
                    "assertions": lambda a: _build(PhaseAssertions, a),
                },
            )
            for p in data.get("phases", [])
        ]
        spec = _build(
            ScenarioSpec, data,
            casts={
                "slo": lambda s: _build(SloSpec, s),
                "fleet": lambda f: _build(FleetSpec, f),
                "autopilot": lambda a: _build(AutopilotSpec, a),
                "phases": lambda _: phases,
            },
        )
        return spec.validate()

    @classmethod
    def load(cls, path: str | Path) -> "ScenarioSpec":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def save(self, path: str | Path) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
            f.write("\n")


def builtin_spec_path(name: str) -> Path:
    """Path of a spec shipped with the package (``specs/<name>.json``)."""
    return Path(__file__).parent / "specs" / f"{name}.json"
