"""Scenario soak CLI.

Run a scenario spec against a live routed mocker fleet and write the
SCENARIO_SOAK.json artifact:

    python -m dynamo_tpu.scenarios.soak                      # shipped default
    python -m dynamo_tpu.scenarios.soak --spec my_soak.json  # custom spec
    python -m dynamo_tpu.scenarios.soak --list               # shipped specs

Exit code 0 iff every phase's assertions held AND (when the spec sets
``autopilot.expect_decision``) the planner executed at least one burn/SLA
driven decision mid-soak.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from pathlib import Path

from dynamo_tpu.scenarios.runner import run_scenario
from dynamo_tpu.scenarios.spec import ScenarioSpec, builtin_spec_path


def _specs_dir() -> Path:
    return builtin_spec_path("_").parent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spec", default=None,
                    help="path to a scenario JSON, or a shipped spec name "
                         "(default: default_soak)")
    ap.add_argument("--out", default="SCENARIO_SOAK.json",
                    help="artifact path (default: SCENARIO_SOAK.json)")
    ap.add_argument("--speedup", type=float, default=None,
                    help="override the spec's sim-time compression")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the spec's seed")
    ap.add_argument("--list", action="store_true", help="list shipped specs")
    args = ap.parse_args(argv)

    if args.list:
        for p in sorted(_specs_dir().glob("*.json")):
            print(p.stem)
        return 0

    raw = args.spec or "default_soak"
    path = Path(raw) if Path(raw).exists() else builtin_spec_path(raw)
    if not path.exists():
        print(f"no such spec: {raw}", file=sys.stderr)
        return 2
    spec = ScenarioSpec.load(path)
    if args.speedup is not None:
        spec.speedup = args.speedup
    if args.seed is not None:
        spec.seed = args.seed

    from dynamo_tpu.bench.perfgate import provenance_stamp

    artifact = asyncio.run(run_scenario(spec))
    artifact["generated_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    # shared provenance header: lets scripts/perfgate.py refuse to diff
    # artifacts from an incompatible schema generation
    artifact["provenance"] = provenance_stamp()
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")

    for phase in artifact["phases"]:
        ok = phase["assertions"]["passed"]
        print(f"[{'PASS' if ok else 'FAIL'}] {phase['name']:<18} "
              f"{phase['requests']['completed']}/{phase['requests']['submitted']} ok  "
              f"burn={phase['burn_rates']}  "
              f"goodput={phase['goodput_tok_s_mean']} tok/s  "
              f"mfu={phase['mfu_mean']}")
        for failure in phase["assertions"]["failures"]:
            print(f"       - {failure}")
    planner = artifact["planner"]
    print(f"planner: {len(planner['decisions'])} decisions, "
          f"{planner['steering_decisions']} burn/SLA-driven, "
          f"{len(planner['scale_events'])} scale events executed")
    print(f"{'PASS' if artifact['passed'] else 'FAIL'}: "
          f"{artifact['scenario']} ({artifact['sim_s']} sim-s "
          f"in {artifact['wall_s']} wall-s) → {args.out}")
    return 0 if artifact["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
