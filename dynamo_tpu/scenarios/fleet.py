"""SoakFleet: a live, scalable mocker fleet for scenario soaks.

Named pools ("prefill"/"decode") of MockerEngine workers served on one
control-plane endpoint with real KV-event and load publishers, dispatched
through PushRouter (optionally KV-affine via KvRouter), with a real
in-process MetricsService and a minimal frontend surface exposing
``/slo`` + ``/metrics`` — so ``scripts/dyn_top.collect_snapshot`` works
against the soak exactly as against production.

The fleet IS the planner's supervisor: it implements the
``set_replicas(name, n)`` / ``replica_count(name)`` duck-type that
``planner.connectors.LocalConnector`` drives, spawning and retiring live
workers mid-soak.  That closes the loop the soak exists to prove — a
planner decision becomes real capacity while traffic is in flight.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from aiohttp import web

from dynamo_tpu.components.metrics_service import MetricsService
from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.llm.kv_router.router import KvPushRouter, KvRouter
from dynamo_tpu.llm.mocker import MockerConfig, MockerEngine
from dynamo_tpu.observability.slo import SloTracker
from dynamo_tpu.robustness import counters
from dynamo_tpu.runtime.client import PushRouter, RouterMode
from dynamo_tpu.runtime.controlplane.memory import MemoryControlPlane
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.scenarios.spec import ScenarioSpec
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import get_logger

logger = get_logger("scenarios.fleet")


@dataclass
class _Worker:
    pool: str
    engine: object                  # MockerEngine or JaxLlmEngine
    service: object
    kv_pub: KvEventPublisher
    metrics_pub: WorkerMetricsPublisher
    slice_label: str = ""

    @property
    def worker_id(self) -> int:
        return self.service.instance.instance_id


@dataclass
class SoakFleet:
    spec: ScenarioSpec
    slo: SloTracker
    sim_now: object                     # () -> simulated seconds
    name: str = "soak"

    rt: DistributedRuntime = None
    comp: object = None
    ep: object = None
    dispatcher: object = None
    push: PushRouter = None
    kv_router: KvRouter | None = None
    metrics_service: MetricsService | None = None
    frontend_url: str = ""
    worker_url: str = ""
    _pools: dict = field(default_factory=dict)     # pool → [_Worker]
    _frontend_runner: web.AppRunner | None = None
    _scale_lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    scale_log: list = field(default_factory=list)  # executed scale ops
    # multi-slice emulation (FleetSpec.slices): per-worker selection counts
    # from the router's hit-rate events + the discovered TopologyMap
    topo_watch: object = None
    near_slice: str = ""
    selection_counts: dict = field(default_factory=dict)  # worker_id → picks
    _spawned: dict = field(default_factory=dict)   # pool → spawn counter
    # jax engine mode: one host param init shared by every worker (engines
    # never mutate params, and N random inits would dominate bring-up)
    _params_cache: dict = field(default_factory=dict)
    _slice_by_worker: dict = field(default_factory=dict)  # survives retirement
    _hit_sub: object = None
    _hit_task: object = None

    # -- bring-up / teardown -------------------------------------------------
    async def start(self) -> None:
        fl = self.spec.fleet
        MemoryControlPlane.reset_named()
        self.rt = await DistributedRuntime.create(
            RuntimeConfig(control_plane=f"memory://{self.name}")
        )
        self.comp = self.rt.namespace("soak").component("backend")
        self.ep = self.comp.endpoint("generate")
        if fl.slices:
            # NEAR = the prefill pool's slice: decode selection is judged by
            # how far the prefix blocks must travel from where prefill ran
            labels = fl.slices.get("prefill") or next(iter(fl.slices.values()))
            self.near_slice = labels[0]
        for pool, n in fl.pools.items():
            self._pools[pool] = []
            for _ in range(n):
                self._pools[pool].append(await self._spawn(pool))
        self.push = await PushRouter.from_endpoint(self.ep, mode=RouterMode.RANDOM)
        if fl.policy == "kv":
            self.kv_router = KvRouter(
                self.comp, block_size=fl.block_size, enable_prefetch=False
            )
            await self.kv_router.start()
            self.dispatcher = KvPushRouter(self.push, self.kv_router)
        else:
            self.dispatcher = self.push
        await self.push.client.wait_for_instances(self.worker_count(), timeout=10)

        if fl.slices:
            await self._start_topology()

        # real metrics service (scrapeable by dyn_top / check_metrics)
        self.metrics_service = MetricsService(self.comp, host="127.0.0.1", port=0)
        if self.topo_watch is not None:
            self.metrics_service.attach_topology(self.topo_watch.map)
        await self.metrics_service.start()
        self.worker_url = f"http://127.0.0.1:{self.metrics_service.port}"

        # minimal frontend surface: /slo + /metrics on the simulated clock
        app = web.Application()
        app.router.add_get("/slo", self._handle_slo)
        app.router.add_get("/metrics", self._handle_metrics)
        self._frontend_runner = web.AppRunner(app, access_log=None)
        await self._frontend_runner.setup()
        site = web.TCPSite(self._frontend_runner, "127.0.0.1", 0)
        await site.start()
        port = next(iter(site._server.sockets)).getsockname()[1]
        self.frontend_url = f"http://127.0.0.1:{port}"

    async def stop(self) -> None:
        if self._frontend_runner is not None:
            await self._frontend_runner.cleanup()
        if self.metrics_service is not None:
            await self.metrics_service.stop()
        if self._hit_task is not None:
            self._hit_task.cancel()
        if self._hit_sub is not None:
            await self._hit_sub.unsubscribe()
        if self.topo_watch is not None:
            await self.topo_watch.stop()
        if self.kv_router is not None:
            await self.kv_router.stop()
        for pool in list(self._pools):
            for worker in self._pools[pool]:
                await self._retire(worker)
            self._pools[pool] = []
        if self.rt is not None:
            await self.rt.close()

    # -- topology plane (FleetSpec.slices) -----------------------------------
    async def _start_topology(self) -> None:
        """Discover the emulated multi-slice fleet and wire its consumers:
        the KV router prices candidates by discovered link class, and the
        router's per-request hit-rate events feed the near-slice selection
        ledger the ``min_near_slice_fraction`` assertion reads."""
        from dynamo_tpu.llm.kv_router.protocols import (
            KV_HIT_RATE_SUBJECT,
            KvHitRateEvent,
        )
        from dynamo_tpu.topology import TopologyWatcher, local_card
        from dynamo_tpu.utils.tasks import spawn_logged

        self.topo_watch = TopologyWatcher(self.rt)
        await self.topo_watch.start()
        await self._await_nodes()
        if len(self.topo_watch.map.nodes) < self.worker_count():
            # DYN_TOPO is off, so the workers didn't self-publish — the spec
            # asked for slices explicitly, so publish their cards here
            for pool, ws in self._pools.items():
                for w in ws:
                    card = local_card(
                        w.worker_id, role=pool,
                        slice_label=w.slice_label or None,
                    )
                    await self.rt.plane.kv.put(
                        card.key(), card.to_json(), w.service._lease.id
                    )
            await self._await_nodes()
        if self.kv_router is not None:
            self.kv_router.attach_topology(self.topo_watch.map)
        self._hit_sub = await self.rt.plane.bus.subscribe(
            self.comp.event_subject(KV_HIT_RATE_SUBJECT)
        )

        async def _count() -> None:
            async for msg in self._hit_sub:
                try:
                    ev = KvHitRateEvent.from_json(msg.payload)
                except Exception:  # noqa: BLE001
                    continue
                self.selection_counts[ev.worker_id] = (
                    self.selection_counts.get(ev.worker_id, 0) + 1
                )

        self._hit_task = spawn_logged(_count())

    async def _await_nodes(self) -> None:
        for _ in range(200):
            if len(self.topo_watch.map.nodes) >= self.worker_count():
                return
            await asyncio.sleep(0.01)

    def slice_of(self, worker_id: int) -> str:
        return self._slice_by_worker.get(worker_id, "")

    # -- frontend surface ----------------------------------------------------
    async def _handle_slo(self, request: web.Request) -> web.Response:
        return web.json_response(self.slo.status(self.sim_now()))

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        body = self.slo.render(self.sim_now()) + counters.render()
        return web.Response(body=body, content_type="text/plain")

    # -- worker lifecycle ----------------------------------------------------
    def _mocker_config(self, pool: str) -> MockerConfig:
        fl = self.spec.fleet
        overrides = dict(fl.mocker)
        return MockerConfig(
            num_blocks=fl.num_blocks,
            block_size=fl.block_size,
            max_batch_size=fl.max_batch_size,
            speedup=self.spec.speedup,
            role=pool,
            **overrides,
        )

    def _jax_engine(self):
        """A real JaxLlmEngine worker (FleetSpec.engine='jax'): the actual
        model/scheduler/allocator hot path behind the same endpoint surface
        the mocker serves, so one scenario spec drives either."""
        import jax as _jax

        from dynamo_tpu.engine import EngineConfig, JaxLlmEngine
        from dynamo_tpu.models.llama import LlamaConfig, init_params

        fl = self.spec.fleet
        mcfg = LlamaConfig.tiny()
        if "params" not in self._params_cache:
            self._params_cache["params"] = init_params(
                mcfg, _jax.random.PRNGKey(0)
            )
        # bucket ladder sized to the context window (routed_fleet idiom):
        # every serving program is warmed before traffic, so fewer buckets
        # = faster bring-up, and the top bucket covers max_model_len
        buckets = tuple(
            b for b in (128, 256, 512, 1024, 2048) if b < fl.max_model_len
        ) + (fl.max_model_len,)
        return JaxLlmEngine(
            EngineConfig(
                model=mcfg,
                num_blocks=fl.num_blocks,
                block_size=fl.block_size,
                max_batch_size=fl.max_batch_size,
                prefill_buckets=buckets,
                max_model_len=fl.max_model_len,
            ),
            params=self._params_cache["params"],
        )

    async def _spawn(self, pool: str) -> _Worker:
        fl = self.spec.fleet
        slice_label = ""
        labels = fl.slices.get(pool) or []
        if labels:
            slice_label = labels[self._spawned.get(pool, 0) % len(labels)]
        self._spawned[pool] = self._spawned.get(pool, 0) + 1
        if fl.engine == "jax":
            engine = self._jax_engine()
        else:
            cfg = self._mocker_config(pool)
            if slice_label:
                # mocker-side per-pair latency: a worker off the prefill
                # slice pays the DCN-class transfer bill on every prefill
                far = bool(self.near_slice) and slice_label != self.near_slice
                hop = "dcn" if far else "local"
                cfg.transfer_delay_s = float(
                    fl.link_delay_s.get(hop, cfg.transfer_delay_s)
                )
            engine = MockerEngine(cfg)
        service = await self.ep.serve(
            engine, stats_handler=engine.stats,
            topo_role=pool, topo_slice=slice_label or None,
        )
        self._slice_by_worker[service.instance.instance_id] = slice_label
        kv_pub = KvEventPublisher(self.comp, worker_id=service.instance.instance_id)
        kv_pub.start()
        engine._event_sink = kv_pub.sink
        metrics_pub = WorkerMetricsPublisher(
            self.comp, service.instance.instance_id, engine.stats,
            period_s=self.spec.fleet.metrics_period_s / self.spec.speedup,
        )
        metrics_pub.start()
        engine.start()
        if fl.engine == "jax":
            # compile every serving program before traffic: lazy compiles
            # mid-phase would dominate TTFT and fail the SLO assertions
            # for reasons that have nothing to do with the system under test
            await engine.warmup()
        return _Worker(pool, engine, service, kv_pub, metrics_pub, slice_label)

    async def _retire(self, worker: _Worker) -> None:
        # graceful scale-down IS the drain state machine: admissions stop,
        # stragglers hand off via resume-redispatch instead of being killed
        await worker.service.drain(2.0)
        await worker.metrics_pub.stop()
        await worker.kv_pub.stop()
        worker.engine.stop()

    async def kill_worker(self, pool: str, *, mode: str = "kill") -> int | None:
        """Chaos seam for worker-kill scenarios: take one live worker out of
        ``pool`` mid-soak.  ``kill`` is abrupt (lease revoked, handlers
        cancelled mid-stream — the dispatcher's generation journal must
        resume those streams on a peer); ``drain`` runs the graceful state
        machine.  Returns the removed worker id, or None if the pool is
        empty."""
        async with self._scale_lock:
            workers = self._pools.get(pool) or []
            if not workers:
                return None
            # oldest first: it holds the most in-flight work and the
            # warmest KV — the hardest worker to lose
            worker = workers.pop(0)
        if mode == "drain":
            await worker.service.drain()
        else:
            await worker.service.abort()
        await worker.metrics_pub.stop()
        await worker.kv_pub.stop()
        worker.engine.stop()
        self.scale_log.append(
            {"t": self.sim_now(), "pool": pool, "op": mode,
             "worker": f"{worker.worker_id:x}"}
        )
        return worker.worker_id

    # -- planner supervisor duck-type (connectors.LocalConnector) ------------
    def replica_count(self, pool: str) -> int:
        return len(self._pools.get(pool, []))

    def worker_count(self) -> int:
        return sum(len(ws) for ws in self._pools.values())

    async def set_replicas(self, pool: str, n: int) -> None:
        async with self._scale_lock:
            workers = self._pools.setdefault(pool, [])
            before = len(workers)
            if n == before:
                return
            if n > before:
                for _ in range(n - before):
                    workers.append(await self._spawn(pool))
                try:
                    await self.push.client.wait_for_instances(
                        self.worker_count(), timeout=5
                    )
                except TimeoutError:
                    logger.warning("scale-up of %s not fully visible yet", pool)
            else:
                # retire newest-first: the oldest workers hold the warmest
                # KV and the most session affinity
                while len(workers) > n:
                    await self._retire(workers.pop())
            self.scale_log.append(
                {"t": self.sim_now(), "pool": pool, "from": before, "to": n}
            )
            logger.info("pool %s: %d → %d replicas", pool, before, n)

    # -- sampling ------------------------------------------------------------
    def roles(self) -> dict[int, str]:
        return {
            w.worker_id: pool
            for pool, ws in self._pools.items() for w in ws
        }

    def stat_sum(self, key: str) -> float:
        return sum(
            w.engine.stats().get(key, 0)
            for ws in self._pools.values() for w in ws
        )
